"""The gateway's routing core: prefix-affinity dispatch with
exactly-once completion semantics, fleet-wide admission, and the
scale-from-zero door queue (ISSUE 11 tentpole). Transport-injected and
jax-free: the binary (cmd/gateway.py) plugs in an HTTP transport, the
tests drive REAL ServingLoops, and benches mix both — the routing/
retry/queueing state machine is identical everywhere.

This productionizes the retrying router that until now lived as a test
fixture (``tests/test_fleet_chaos.py``): the fixture proved fleet-level
outcome conservation — every request finishes EXACTLY ONCE even when
replicas drain, die mid-request, or 503 through a supervised restart —
and this module keeps that contract while adding what a fixture never
needed:

- **prefix-affinity dispatch** (``gateway/ring.py``): requests sharing
  a leading block-chain land on the replica whose ``PrefixBlockIndex``
  already holds those KV blocks, least-loaded fallback past a bounded
  per-replica imbalance;
- **global admission**: the per-replica ``/stats`` the fleet controller
  already scrapes, aggregated at the door — fleet-wide pending depth or
  HBM pressure sheds BEFORE work reaches a replica, with
  machine-readable reasons (``fleet_queue_full`` / ``fleet_hbm_admission``
  / ``door_queue_full``) so clients and the autoscaler can tell
  capacity pressure from everything else;
- **deadline propagation**: a request's completion budget starts at
  the DOOR; time spent queued or retrying shrinks what is forwarded to
  the replica (the existing ``X-Request-Deadline-S`` header in the
  HTTP transport), and an expired budget sheds at the gateway without
  burning replica work;
- **the scale-from-zero door queue**: with no admitting replica,
  requests park in FIFO arrival order (bounded), the gateway publishes
  an activation signal (``nos_tpu_gateway_door_queue`` gauge, /stats
  ``door_queue``, and the ``on_activation`` hook the binary uses to
  stamp the ``nos.ai/gateway-queued`` annotation) which the
  ``FleetController`` consumes as pressure — and the queue flushes the
  moment the first replica turns ready.

Exactly-once semantics, precisely: the router resubmits a request ONLY
when the previous attempt raised before delivering a result (shed,
recovering, draining, unreachable, death mid-request). A replica that
died mid-request accounts its own interrupted attempt terminally
(``failed``/``cancelled`` — the serving loop's exactly-once outcome
discipline), so the fleet-wide ledger shows exactly one ``finished``
per request and the client sees exactly one completion. Streaming
retries only until the FIRST delta is on the wire; after that a
failure propagates (replaying tokens the client already holds would be
a double-finish in stream form).
"""
from __future__ import annotations

import logging
import random
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Deque, Dict, Iterable, List, Optional

from nos_tpu.gateway.ring import HashRing, affinity_pick, prefix_key
from nos_tpu.kvfabric.codec import chain_digest
from nos_tpu.kvfabric.fleet import FleetPrefixIndex
from nos_tpu.models.errors import (
    DeadlineExceeded, EngineRecovering, Infeasible, QueueFull,
    TenantQuotaExceeded,
)
from nos_tpu.models.tenantquota import TenantQuotaConfig
from nos_tpu.obs import tracing
from nos_tpu.obs.slo import IDLE_TENANT, aggregate_slo
from nos_tpu.utils.metrics import default_registry

logger = logging.getLogger(__name__)

__all__ = ["GatewayRouter", "HandoffResumeError", "Replica",
           "ReplicaUnreachable", "RouterConfig"]

#: terminal outcomes nos_tpu_gateway_requests_total reports
OUTCOMES = ("completed", "shed", "deadline", "failed")

#: door-shed reason slugs (the gateway's own additions to the
#: serving-plane reason table in docs/autoscaling.md)
REASON_FLEET_QUEUE = "fleet_queue_full"
REASON_FLEET_HBM = "fleet_hbm_admission"
REASON_DOOR_QUEUE = "door_queue_full"
REASON_NO_REPLICAS = "no_ready_replicas"
#: the request-level elastic-quota shed (ISSUE 13): the submitting
#: tenant's FLEET-WIDE token-rate (summed from the scraped per-replica
#: /stats ``tenants`` sections) is at/over its gateway-configured max —
#: same slug as the per-replica shed, so clients see one reason
#: whichever door refused them
REASON_TENANT = "tenant_quota"


class HandoffResumeError(Exception):
    """Phase 2 of a disaggregated request failed: the prefill replica
    already shipped the KV to a decode replica, so re-dispatching from
    scratch would re-prefill AND orphan the adopted request — the
    router therefore never retries the whole request past this point
    (deliberately NOT a RuntimeError: the retry arms catch those)."""


class ReplicaUnreachable(RuntimeError):
    """The transport could not reach the replica (connection refused /
    reset, scrape-dead pod): the request may or may not have started
    there — either way THIS attempt delivered nothing, so the router
    requeues it. The replica side accounts its own interrupted attempt
    exactly once; resubmission cannot double-finish."""


@dataclass
class Replica:
    """One replica as the router sees it. ``handle`` is opaque transport
    state (a base URL for HTTP, a ServingLoop in tests, a SimReplica in
    benches); ``stats`` is the last scraped ``/stats`` snapshot (the
    same surface the fleet controller reads); ``inflight`` counts
    requests THIS router currently has dispatched there — the load term
    that is always fresh even when scrapes lag."""

    name: str
    handle: Any = None
    ready: bool = True
    draining: bool = False
    stats: dict = field(default_factory=dict)
    inflight: int = 0
    # prefill/decode disaggregation role (the replica's /stats config
    # echo): NEW requests route only to "colocated"/"prefill" replicas;
    # "decode" replicas never join the ring — they receive work as KV
    # handoffs from prefill replicas, and the router only talks to
    # them in phase 2 (resume_transport) of a handed-off request
    role: str = "colocated"

    def load(self) -> float:
        pend = (self.stats.get("pending") or {}).get("depth", 0) or 0
        active = self.stats.get("active_slots") or 0
        return float(self.inflight + pend + active)

    def hbm_frac(self) -> Optional[float]:
        hbm = (self.stats.get("kv") or {}).get("hbm") or {}
        in_use, limit = hbm.get("in_use"), hbm.get("limit")
        if in_use is None or not limit:
            return None
        return in_use / limit


@dataclass(frozen=True)
class RouterConfig:
    """Routing/admission knobs (helm: ``gateway.*``)."""

    # affinity hashing: must match the replicas' --kv-block-size so the
    # routed block-chain is the one PrefixBlockIndex actually shares;
    # affinity_blocks caps the keyed depth (see ring.prefix_key)
    block_size: int = 16
    affinity_blocks: int = 4
    # a ring candidate may exceed the least-loaded replica's load by at
    # most this many requests before affinity yields to balance
    max_imbalance: float = 4.0
    # global admission (0 = disabled): shed at the door when fleet-wide
    # pending per admitting replica exceeds the bound, or when EVERY
    # admitting replica reports HBM use at/above the fraction
    admit_pending_per_replica: float = 0.0
    admit_hbm_frac: float = 0.0
    # scale-from-zero door queue: how many requests may park while no
    # replica admits, and how long one may wait before shedding
    max_door_queue: int = 256
    door_wait_s: float = 30.0
    # retry budget per request (attempts, not replicas) and the
    # reason-aware backoff base (seeded jitter on top)
    max_attempts: int = 12
    backoff_s: float = 0.05
    backoff_max_s: float = 1.0
    seed: int = 0
    # request-level elastic quota at the door (None = off): fleet-wide
    # per-tenant token-rate max (summed from the scraped /stats
    # ``tenants`` sections), shed reason=tenant_quota before work
    # reaches any replica; also scopes the affinity key per tenant
    # (share_prefix opts out) and bounds the TOTAL dispatch attempts
    # answered tenant_quota before the request fails as 429 (the Nth
    # quota shed is the failing one; 1 = fail on the first) — a burst
    # tenant backs off on ITS quota instead of consuming the fleet's
    # retry capacity while guaranteed tenants wait
    tenant_config: Optional[TenantQuotaConfig] = None
    tenant_quota_attempts: int = 2
    # fleet-wide KV fabric (ISSUE 17): when on, the gateway keeps a
    # union index over the replicas' /stats ``prefix_index`` sections
    # and, on a dispatch whose routed replica is NOT the warmest
    # holder of the prompt's prefix chain, attaches ONE peer-pull
    # offer (``kv_sources``) naming the warmest peer's
    # /v1/kvchain/<digest> — the replica pulls the chain instead of
    # re-prefilling. fabric_max_blocks caps how deep a prompt prefix
    # the gateway enumerates digests for (cost is one digest per
    # block, longest-first).
    fabric: bool = False
    fabric_max_blocks: int = 32
    # fleet SLO roll-up (ISSUE 20): fast-window burn-rate at/above this
    # marks an aggregated (tenant, objective) row ``breaching`` in
    # GET /v1/slo — fleet burn is recomputed from SUMMED window counts,
    # not averaged per-replica ratios
    slo_burn_threshold: float = 14.4


class GatewayRouter:
    """See module docstring. ``transport(replica, request) -> tokens``
    performs one unary attempt; ``stream_transport(replica, request)``
    returns an iterator of token-list deltas. ``request`` is a dict:
    ``{"prompt", "max_new_tokens", "deadline_s", "sampling"}`` with
    ``deadline_s`` already reduced to the REMAINING budget (None =
    unbounded). Both raise the serving-plane error types (QueueFull /
    EngineRecovering / DrainingError-shaped RuntimeErrors) or
    ``ReplicaUnreachable``; anything retryable is retried on the next
    candidate, everything else propagates."""

    def __init__(self, cfg: RouterConfig = RouterConfig(),
                 transport: Optional[Callable[[Replica, dict], list]] = None,
                 stream_transport: Optional[
                     Callable[[Replica, dict], Iterable[list]]] = None,
                 on_activation: Optional[Callable[[int], None]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep,
                 resume_transport: Optional[
                     Callable[[Replica, dict, Optional[float]],
                              list]] = None,
                 resume_stream_transport: Optional[
                     Callable[[Replica, dict, Optional[float]],
                              Iterable[list]]] = None):
        self.cfg = cfg
        self.transport = transport
        self.stream_transport = stream_transport
        # disaggregation phase 2: a prefill replica answered with a
        # handoff descriptor {"target", "rid"} — these fetch/stream the
        # tokens from the decode replica it names. HTTP binary: GET
        # /v1/result/<rid> and /v1/stream/<rid>; tests inject
        # ServingLoop.result/watch directly.
        self.resume_transport = resume_transport
        self.resume_stream_transport = resume_stream_transport
        self.on_activation = on_activation
        self.clock = clock
        self.sleep = sleep
        self._rng = random.Random(cfg.seed)
        self._lock = threading.Condition()
        self._replicas: Dict[str, Replica] = {}
        # in-flight attempts keyed by NAME, owned by the router — the
        # Replica objects are replaced wholesale on every discovery
        # update, so counting on them would lose decrements from
        # requests that outlive one poll (the load signal would creep
        # up forever). The table objects mirror the dict for load().
        self._inflight: Dict[str, int] = {}
        self._ring = HashRing()
        self._door: Deque[int] = deque()        # ticket FIFO (rids)
        self._next_ticket = 0
        self._door_peak = 0
        self._counts: Dict[str, int] = {k: 0 for k in OUTCOMES}
        self._handoffs = 0
        self._shed: Dict[str, int] = {}
        self._tenant_shed: Dict[str, int] = {}
        self._routes: Dict[str, int] = {}
        self._retries = 0
        # KV fabric: the union view over replica prefix_index sections
        # (synced wholesale in update(), so unscrapable/departed
        # replicas age out), plus an injectable URL builder for the
        # peer-pull source — tests with in-process loop handles
        # override it; the default only knows string handles (the HTTP
        # base URL).
        self._fleet_index = FleetPrefixIndex()
        self._fabric_offered = 0
        self.chain_url: Optional[Callable[[Replica, str], str]] = None
        reg = default_registry()
        self.m_requests = reg.counter(
            "nos_tpu_gateway_requests_total",
            "Requests leaving the gateway, by terminal outcome "
            "(completed | shed = refused at the door with a reason | "
            "deadline = budget spent before a replica delivered | "
            "failed = retry budget exhausted or non-retryable error); "
            "exactly one outcome per request",
            ("outcome",))
        self.m_shed = reg.counter(
            "nos_tpu_gateway_shed_total",
            "Door sheds by machine-readable reason (fleet_queue_full | "
            "fleet_hbm_admission | door_queue_full | no_ready_replicas "
            "| tenant_quota = a tenant at/over its fleet-wide max "
            "token-rate — the gateway's own reasons, disjoint from the "
            "per-replica 429 reasons it retries through)",
            ("reason",))
        self.m_route = reg.counter(
            "nos_tpu_gateway_route_total",
            "Routing decisions by path (affinity = the prefix key's "
            "ring candidate took it | fallback = ring candidates were "
            "saturated/not admitting, least-loaded took it | no_key = "
            "prompt had no full-block prefix to key on)",
            ("path",))
        self.m_handoff = reg.counter(
            "nos_tpu_gateway_handoff_total",
            "Disaggregated requests the gateway followed from a "
            "prefill replica's handoff descriptor to a decode replica, "
            "by outcome (resumed = tokens delivered from the decode "
            "replica | failed = phase 2 exhausted its attempts — the "
            "request is NOT re-dispatched, its KV already moved)",
            ("outcome",))
        self.m_retries = reg.counter(
            "nos_tpu_gateway_retries_total",
            "Dispatch attempts beyond each request's first, by cause "
            "(shed | recovering | unreachable | error)",
            ("cause",))
        self.g_door = reg.gauge(
            "nos_tpu_gateway_door_queue",
            "Requests parked at the gateway because no replica is "
            "admitting — the scale-from-zero activation signal the "
            "fleet controller consumes as pressure")
        self.h_door_wait = reg.histogram(
            "nos_tpu_gateway_door_wait_seconds",
            "Time requests spent parked in the door queue before "
            "dispatch or shed")
        self.m_fabric_offered = reg.counter(
            "nos_tpu_gateway_kvfabric_offered_total",
            "Peer-pull chain offers the gateway attached to dispatched "
            "requests (KV fabric): the routed replica was colder than "
            "a peer on the prompt's prefix chain, so the request "
            "carried one kv_sources entry naming the warmest peer's "
            "/v1/kvchain/<digest>")
        self.g_replicas = reg.gauge(
            "nos_tpu_gateway_replicas",
            "Replicas as the gateway's discovery sees them, by state "
            "(ready = admitting | draining | down = known but not "
            "admitting for any other reason)",
            ("state",))
        # fleet SLO roll-up (ISSUE 20): gauges mirror the aggregated
        # view GET /v1/slo serves; label rows appear as replicas with
        # configured tenants join the scrape
        self.g_slo_budget = reg.gauge(
            "nos_tpu_gateway_slo_budget_remaining_ratio",
            "Fleet-wide fraction of each tenant objective's slow-window "
            "error budget still unspent (1 = untouched, 0 = exhausted), "
            "recomputed from summed per-replica window counts",
            ("tenant", "objective"))
        self.g_slo_burn = reg.gauge(
            "nos_tpu_gateway_slo_burn_rate",
            "Fleet-wide SLO burn rate (bad fraction / allowed) per "
            "tenant objective and window (fast | slow), from summed "
            "per-replica window counts — fast at/above the burn "
            "threshold marks the row breaching in GET /v1/slo",
            ("tenant", "objective", "window"))
        # chip-second harvest feed for useful-work-per-chip-hour: the
        # binary wires a /stats scrape of the harvest controller here
        # (--harvest-url); tests inject HarvestController.stats
        self.harvest_source: Optional[Callable[[], Optional[dict]]] = None

    # -- membership ------------------------------------------------------
    def update(self, replicas: Iterable[Replica]) -> None:
        """Level-triggered membership + stats refresh from discovery.
        The ring holds exactly the ADMITTING replicas (ready and not
        draining): a draining replica must stop attracting its keys —
        its cache leaves with it — and ring points are derived from the
        name, so a replica bouncing through not-ready and back restores
        the identical mapping. A 0 -> >=1 admitting transition flushes
        the door queue."""
        with self._lock:
            had_admitting = bool(self._admitting())
            fresh = {}
            for r in replicas:
                r.inflight = self._inflight.get(r.name, 0)
                fresh[r.name] = r
            self._replicas = fresh
            # prune settled counts for replicas that left the fleet
            for name in [n for n, c in self._inflight.items()
                         if c == 0 and n not in fresh]:
                del self._inflight[name]
            self._ring.sync(n for n in fresh
                            if fresh[n].ready and not fresh[n].draining
                            and fresh[n].role != "decode")
            if self.cfg.fabric:
                # fleet prefix index: wholesale per scrape, so a
                # replica that left the fleet (or stopped answering
                # /stats — its snapshot is empty) ages out with it
                self._fleet_index.sync({
                    name: (r.stats or {}).get("prefix_index")
                    for name, r in fresh.items()})
            n_ready = len(self._admitting())
            n_drain = sum(1 for r in fresh.values() if r.draining)
            self.g_replicas.labels("ready").set(n_ready)
            self.g_replicas.labels("draining").set(n_drain)
            self.g_replicas.labels("down").set(
                len(fresh) - n_ready - n_drain)
            for row in self._slo_locked()["objectives"]:
                self.g_slo_budget.labels(
                    row["tenant"], row["objective"]).set(
                    row["budget_remaining_ratio"])
                self.g_slo_burn.labels(
                    row["tenant"], row["objective"], "fast").set(
                    row["burn_fast"])
                self.g_slo_burn.labels(
                    row["tenant"], row["objective"], "slow").set(
                    row["burn_slow"])
            if not had_admitting and n_ready:
                self._lock.notify_all()     # flush the door queue

    def _admitting(self) -> List[str]:
        """Replicas NEW requests may land on: ready, not draining, and
        not decode-role (decode replicas only take handed-off KV)."""
        return [n for n, r in self._replicas.items()
                if r.ready and not r.draining and r.role != "decode"]

    def _inflight_delta(self, name: str, delta: int) -> None:
        """Caller holds the lock. The dict is the truth; the current
        table object (which discovery may have replaced since the
        attempt started) mirrors it for ``load()``."""
        self._inflight[name] = max(0, self._inflight.get(name, 0) + delta)
        rep = self._replicas.get(name)
        if rep is not None:
            rep.inflight = self._inflight[name]

    # -- admission -------------------------------------------------------
    def fleet_tenant_rate(self, tenant: Optional[str]) -> float:
        """The tenant's fleet-wide token-rate: its per-replica rate
        rows (the scraped /stats ``tenants`` sections) summed over
        every known replica — the aggregate the gateway's own min/max
        semantics judge, mirroring how the fleet controller aggregates
        every other per-replica signal. Caller holds the lock."""
        tc = self.cfg.tenant_config
        if tc is None:
            return 0.0
        label = tc.resolve(tenant)
        total = 0.0
        for r in self._replicas.values():
            row = (r.stats.get("tenants") or {}).get(label) or {}
            total += row.get("rate_tokens_per_s", 0.0) or 0.0
        return total

    def _admit(self, tenant: Optional[str] = None) -> None:
        """Fleet-wide admission, caller holds the lock: shed at the
        door — with a machine-readable reason — before work reaches a
        replica. Uses the same scraped /stats the controller reads plus
        the router's own in-flight attribution (fresh even when scrapes
        lag)."""
        cfg = self.cfg
        tc = cfg.tenant_config
        if tc is not None:
            # the request-level quota's door arm: the tenant's
            # FLEET-WIDE rate at/over its gateway max sheds here, with
            # the same tenant_quota slug the replicas use — before the
            # request burns door-queue space or a retry ladder. min is
            # deliberately not door-enforced: guarantees are enforced
            # where slots live (weighted admission + reclaim inside
            # each engine); the door only stops over-ceiling traffic.
            spec = tc.spec(tenant)
            if spec.max_rate > 0 \
                    and self.fleet_tenant_rate(tenant) >= spec.max_rate:
                label = tc.resolve(tenant)
                self._tenant_shed[label] = \
                    self._tenant_shed.get(label, 0) + 1
                self._note_shed(REASON_TENANT)
                raise TenantQuotaExceeded(
                    f"tenant {label!r} is at/over its fleet-wide max "
                    f"of {spec.max_rate:.1f} tokens/s; back off until "
                    f"its window drains")
        admitting = self._admitting()
        if not admitting:
            return                  # the door queue's job, not a shed
        if cfg.admit_pending_per_replica > 0:
            pending = sum(self._replicas[n].load() for n in admitting) \
                + len(self._door)
            if pending / len(admitting) > cfg.admit_pending_per_replica:
                self._note_shed(REASON_FLEET_QUEUE)
                raise QueueFull(
                    f"fleet saturated: {pending:.0f} requests pending "
                    f"across {len(admitting)} replicas (bound "
                    f"{cfg.admit_pending_per_replica}/replica); retry "
                    f"when load drops", reason=REASON_FLEET_QUEUE)
        if cfg.admit_hbm_frac > 0:
            fracs = [self._replicas[n].hbm_frac() for n in admitting]
            fracs = [f for f in fracs if f is not None]
            if fracs and min(fracs) >= cfg.admit_hbm_frac:
                self._note_shed(REASON_FLEET_HBM)
                raise QueueFull(
                    f"every replica reports HBM use >= "
                    f"{cfg.admit_hbm_frac:.0%} — KV memory, not slots, "
                    f"is the fleet bottleneck", reason=REASON_FLEET_HBM)

    def _note_shed(self, reason: str) -> None:
        self._shed[reason] = self._shed.get(reason, 0) + 1
        self.m_shed.labels(reason).inc()
        self._counts["shed"] += 1
        self.m_requests.labels("shed").inc()

    # -- the door queue (scale-from-zero) --------------------------------
    def _door_depth_changed(self) -> None:
        # on_activation runs UNDER the router lock (every depth change
        # originates inside it): implementations must hand off — set an
        # event, bump an atomic — never block on I/O here. The binary's
        # annotation stamper is a separate thread for exactly this.
        depth = len(self._door)
        self._door_peak = max(self._door_peak, depth)
        self.g_door.set(depth)
        if self.on_activation is not None:
            try:
                self.on_activation(depth)
            except Exception:   # noqa: BLE001 — the signal is advisory;
                pass            # a failed stamp must never fail a request

    def _wait_for_replica(self, deadline: Optional[float],
                          sp=None) -> None:
        """Park until some replica admits (FIFO ticket, bounded queue,
        bounded wait). Caller holds the lock. Raises QueueFull /
        DeadlineExceeded on shed — each with its one terminal
        accounting. ``sp`` (the journey's root span) gets the measured
        wait as ``door_wait_s`` so a stitched trace can attribute TTFT
        to the door."""
        cfg = self.cfg
        if len(self._door) >= cfg.max_door_queue:
            self._note_shed(REASON_DOOR_QUEUE)
            raise QueueFull(
                f"gateway door queue full ({cfg.max_door_queue}) with "
                f"no replica admitting", reason=REASON_DOOR_QUEUE)
        ticket = self._next_ticket
        self._next_ticket += 1
        self._door.append(ticket)
        self._door_depth_changed()
        t0 = self.clock()
        give_up = t0 + cfg.door_wait_s
        if deadline is not None:
            give_up = min(give_up, deadline)
        try:
            while not self._admitting():
                now = self.clock()
                if now >= give_up:
                    if deadline is not None and now >= deadline:
                        self._counts["deadline"] += 1
                        self.m_requests.labels("deadline").inc()
                        raise DeadlineExceeded(
                            "request spent its deadline parked at the "
                            "gateway door (no replica became ready)")
                    self._note_shed(REASON_NO_REPLICAS)
                    raise QueueFull(
                        f"no replica became ready within "
                        f"{cfg.door_wait_s:.0f}s", reason=REASON_NO_REPLICAS)
                self._lock.wait(timeout=min(0.05, give_up - now))
        finally:
            self._door.remove(ticket)
            self._door_depth_changed()
            waited = self.clock() - t0
            self.h_door_wait.observe(waited)
            if sp is not None and sp.recording:
                sp.set_attr("door_wait_s", round(waited, 6))

    # -- dispatch --------------------------------------------------------
    def _pick(self, key: Optional[str],
              tried: Optional[set] = None) -> Optional[Replica]:
        """One routing decision. ``tried`` excludes replicas that
        already failed THIS request (the fixture router's discipline):
        a dead-but-still-listed replica must not eat the whole retry
        budget. When every admitting replica has been tried, the set
        widens — transient sheds (429 under load, 503 mid-restart)
        deserve a second lap."""
        admitting = self._admitting()
        if tried:
            fresh = [n for n in admitting if n not in tried]
            if fresh:
                admitting = fresh
            else:
                tried.clear()       # widen: second lap over everyone
        loads = {n: self._replicas[n].load() for n in admitting}
        name, route = affinity_pick(key, self._ring, loads, admitting,
                                    self.cfg.max_imbalance)
        if name is None:
            return None
        self._routes[route] = self._routes.get(route, 0) + 1
        self.m_route.labels(route).inc()
        return self._replicas[name]

    def _backoff_s(self, exc: Exception, attempt: int) -> float:
        """Reason-aware: capacity sheds (429 queue_full/hbm) back off
        exponentially — hammering a saturated fleet helps nobody;
        deadline_unmeetable retries the NEXT replica immediately (the
        estimate that shed it is replica-local); recovering/draining/
        unreachable use a short flat delay (a different replica is
        expected to answer now)."""
        cfg = self.cfg
        if isinstance(exc, QueueFull):
            if exc.reason == "deadline_unmeetable":
                return 0.0
            if exc.reason == REASON_TENANT:
                # quota, not capacity: the shed clears when the
                # tenant's OWN window drains, so go straight to the
                # ceiling instead of probing the fleet on the way up
                d = cfg.backoff_max_s
            else:
                d = min(cfg.backoff_max_s,
                        cfg.backoff_s * (2 ** attempt))
        else:
            d = cfg.backoff_s
        return d * (0.5 + self._rng.random())

    def _key_scope(self, tenant: Optional[str]) -> Optional[str]:
        """The affinity key's tenant scope: the RESOLVED tenant under
        a quota config (unless ``share_prefix`` opts the fleet out of
        scoping), None otherwise. Tenancy unconfigured = legacy
        tenant-free keys even for labeled traffic: the replicas only
        scope their chains when THEY run a tenant config, and
        splitting the gateway's keys by a label the replica caches
        ignore would scatter one shared prefix across replicas for no
        isolation gain. Resolution mirrors the replicas' own
        ``_prefix_scope`` (unknown labels fold into the default
        tenant), so the colocated cache hits the routing promises
        actually exist."""
        tc = self.cfg.tenant_config
        if tc is None or tc.share_prefix:
            return None
        return tc.resolve(tenant)

    def _fabric_offer(self, rep: Replica, prompt: List[int],
                      tenant: Optional[str]) -> Optional[dict]:
        """At most ONE peer-pull offer for this dispatch, or None.
        Caller holds the lock. Enumerates the prompt's block-aligned
        prefix digests LONGEST-first (capped at fabric_max_blocks) and
        offers the warmest peer holding any of them — but only when
        that peer's chain is strictly longer than anything the routed
        replica itself holds (pulling what the target already has, or
        less, wastes a fetch on the latency path). Digests embed the
        tenant scope, so a lookup can only ever surface chains
        published under the requester's own scope — cross-tenant
        migration is structurally impossible, not just filtered."""
        if not self.cfg.fabric:
            return None
        bs = self.cfg.block_size
        nblk = min(len(prompt) // bs, self.cfg.fabric_max_blocks)
        scope = self._key_scope(tenant)
        own_best = 0
        best = None                     # (len, peer Replica, digest)
        for b in range(nblk, 0, -1):
            digest = chain_digest(prompt[:b * bs], scope)
            own_best = max(own_best,
                           self._fleet_index.replica_len(rep.name, digest))
            for name, row in self._fleet_index.holders(
                    digest, exclude=rep.name):
                peer = self._replicas.get(name)
                if peer is None:
                    continue
                ln = int(row.get("len") or 0)
                if best is None or ln > best[0]:
                    best = (ln, peer, digest)
            if best is not None:
                # longest-first enumeration: the first depth with any
                # peer holder IS the longest pullable chain, and every
                # own-chain candidate at least that deep has already
                # been folded into own_best
                break
        if best is None or best[0] <= own_best:
            return None
        ln, peer, digest = best
        try:
            url = (self.chain_url(peer, digest)
                   if self.chain_url is not None
                   else f"{peer.handle}/v1/kvchain/{digest}"
                   if isinstance(peer.handle, str) else None)
        except Exception:   # noqa: BLE001 — offers are best-effort
            url = None
        if not url:
            return None
        self._fabric_offered += 1
        self.m_fabric_offered.inc()
        return {"url": url, "digest": digest, "len": ln,
                "replica": peer.name}

    def dispatch(self, prompt: List[int], max_new_tokens: int,
                 deadline_s: Optional[float] = None,
                 tenant: Optional[str] = None, **sampling):
        """Unary request through the fleet: returns ``(tokens,
        replica_name, attempts)``. Exactly-once: resubmission happens
        only after an attempt raised without delivering. ``tenant``
        rides the door admission (fleet-wide max), scopes the affinity
        key, and forwards to the replica for its own weighted
        admission."""
        cfg = self.cfg
        t0 = self.clock()
        deadline = t0 + deadline_s if deadline_s else None
        key = prefix_key(prompt, cfg.block_size, cfg.affinity_blocks,
                         tenant=self._key_scope(tenant))
        with tracing.span("gateway.request", component="gateway",
                          attrs={"prompt_tokens": len(prompt),
                                 "tenant": tenant or "",
                                 "affinity_key": key or ""}) as sp:
            tokens, name, attempts = self._dispatch(
                prompt, max_new_tokens, deadline, key, sampling,
                tenant, sp)
            sp.set_attr("replica", name)
            sp.set_attr("attempts", attempts)
        return tokens, name, attempts

    def _remaining(self, deadline: Optional[float]) -> Optional[float]:
        if deadline is None:
            return None
        rem = deadline - self.clock()
        if rem <= 0:
            with self._lock:
                self._counts["deadline"] += 1
                self.m_requests.labels("deadline").inc()
            raise DeadlineExceeded(
                "request spent its deadline at the gateway (queueing + "
                "retries consumed the budget before a replica delivered)")
        return rem

    def _dispatch(self, prompt, max_new_tokens, deadline, key, sampling,
                  tenant=None, sp=None):
        if self.transport is None:
            raise RuntimeError("router has no transport")
        last: Optional[Exception] = None
        tried: set = set()
        tq_sheds = 0
        samp = dict(sampling)
        if tenant is not None:
            samp["tenant"] = tenant     # the replica's own admission
        for attempt in range(self.cfg.max_attempts):
            rem = self._remaining(deadline)
            with self._lock:
                if not self._admitting():
                    self._wait_for_replica(deadline, sp)
                self._admit(tenant)
                rep = self._pick(key, tried)
                if rep is None:
                    continue
                self._inflight_delta(rep.name, +1)
                offer = self._fabric_offer(rep, prompt, tenant)
            # each attempt is its own child span under the journey root:
            # retries show up as SIBLINGS, and the winning attempt's
            # context rides the wire as `traceparent` so the replica's
            # serve.request parents into this trace instead of minting
            # a fresh one
            asp = tracing.start_span(
                "gateway.attempt", component="gateway", parent=sp,
                attrs={"replica": rep.name, "attempt": attempt + 1})
            req = {"prompt": list(prompt),
                   "max_new_tokens": max_new_tokens,
                   "deadline_s": rem, "sampling": dict(samp)}
            if asp.recording:
                req["traceparent"] = asp.context.encode()
            if offer is not None:
                req["kv_sources"] = [offer]
            try:
                tokens = self.transport(rep, req)
                asp.set_attr("outcome", "completed")
            except Infeasible as e:
                asp.set_attr("outcome", "infeasible")
                asp.set_error(str(e))
                with self._lock:
                    self._counts["failed"] += 1
                self.m_requests.labels("failed").inc()
                raise
            except DeadlineExceeded as e:
                asp.set_attr("outcome", "deadline")
                asp.set_error(str(e))
                with self._lock:
                    self._counts["deadline"] += 1
                self.m_requests.labels("deadline").inc()
                raise
            except (QueueFull, ReplicaUnreachable, TimeoutError,
                    RuntimeError) as e:
                cause = self._retry_cause(e)
                asp.set_attr("outcome", cause)
                asp.set_attr("backoff_reason", cause)
                asp.set_error(str(e))
                last = e
                tried.add(rep.name)
                with self._lock:
                    self._retries += 1
                self.m_retries.labels(cause).inc()
                if isinstance(e, QueueFull) \
                        and e.reason == REASON_TENANT:
                    # tenant-aware retry: per-replica quota sheds get
                    # a SMALL dedicated budget — a burst tenant being
                    # told "you are over YOUR ceiling" must back off,
                    # not walk the whole fleet retrying while
                    # guaranteed tenants' requests queue behind its
                    # attempts
                    tq_sheds += 1
                    if tq_sheds >= self.cfg.tenant_quota_attempts:
                        self._raise_exhausted(e)
                self.sleep(self._backoff_s(e, attempt))
                continue
            finally:
                asp.end()
                with self._lock:
                    self._inflight_delta(rep.name, -1)
            if isinstance(tokens, dict):
                # a prefill-role replica answers with a handoff
                # descriptor (follow it — phase 2, never re-dispatched)
                # or {"tokens": ...} when the first token completed
                # the request locally
                if "handoff" in tokens:
                    tokens = self._follow_handoff(tokens["handoff"],
                                                  deadline)
                else:
                    tokens = tokens.get("tokens", tokens)
            with self._lock:
                self._counts["completed"] += 1
            self.m_requests.labels("completed").inc()
            return tokens, rep.name, attempt + 1
        self._raise_exhausted(last)

    def _resolve_target(self, target) -> Replica:
        """The decode replica a handoff descriptor names: matched by
        name or transport handle against discovery's table, else a
        synthetic Replica around the raw target (the prefill server
        addresses its pool by base URL, which IS the HTTP handle)."""
        with self._lock:
            for r in self._replicas.values():
                if r.name == target or r.handle == target:
                    return r
        return Replica(name=str(target), handle=target, role="decode")

    def _follow_handoff(self, desc: dict, deadline: Optional[float]):
        """Phase 2 of a disaggregated request: fetch the tokens from
        the decode replica the descriptor names. Bounded retries
        against THAT replica only (attach is idempotent until the
        result is handed out); on exhaustion the request fails
        terminally — the KV already moved, so re-dispatching from
        scratch would re-prefill and orphan the adopted request."""
        if self.resume_transport is None:
            raise HandoffResumeError(
                "prefill replica answered with a handoff but the "
                "router has no resume_transport configured")
        rep = self._resolve_target(desc.get("target"))
        last: Optional[Exception] = None
        for attempt in range(3):
            try:
                rem = self._remaining(deadline)
            except DeadlineExceeded:
                # gateway-side expiry between attempts (_remaining
                # self-accounts the request outcome): the handoff
                # counter must record the failed resume too, exactly
                # like the decode-raised 504 arm below
                self.m_handoff.labels("failed").inc()
                raise
            try:
                tokens = self.resume_transport(rep, desc, rem)
            except (ReplicaUnreachable, EngineRecovering,
                    TimeoutError) as e:
                last = e
                self.sleep(self._backoff_s(e, attempt))
                continue
            except DeadlineExceeded:
                # the DECODE side says the budget expired: one
                # terminal deadline outcome, like every other exit
                with self._lock:
                    self._counts["deadline"] += 1
                self.m_requests.labels("deadline").inc()
                self.m_handoff.labels("failed").inc()
                raise
            except Exception as e:  # noqa: BLE001 — non-retryable
                # 400/404/500/draining from the decode replica: no
                # amount of retrying THIS replica helps, and retrying
                # elsewhere is forbidden (the KV lives only there)
                last = e
                break
            with self._lock:
                self._handoffs += 1
            self.m_handoff.labels("resumed").inc()
            return tokens
        with self._lock:
            self._counts["failed"] += 1
        self.m_requests.labels("failed").inc()
        self.m_handoff.labels("failed").inc()
        raise HandoffResumeError(
            f"handoff resume at {rep.name} failed: {last}")

    def _follow_handoff_stream(self, desc: dict,
                               deadline: Optional[float]):
        """Streaming twin of ``_follow_handoff``: attach to the decode
        replica's stream, retrying transient failures against THAT
        replica only until the first delta (attach is idempotent);
        after first byte a failure propagates (no replay — tokens left
        the building), and exhaustion/non-retryables convert to the
        terminal HandoffResumeError so the caller's retry arm can
        never re-dispatch a request whose KV already moved."""
        if self.resume_stream_transport is None:
            raise HandoffResumeError(
                "prefill replica answered with a handoff but the "
                "router has no resume_stream_transport configured")
        rep = self._resolve_target(desc.get("target"))
        last: Optional[Exception] = None
        for attempt in range(3):
            rem = None
            if deadline is not None:
                # NOT _remaining(): that self-accounts the deadline
                # outcome, but this raise lands in the caller's
                # ``except DeadlineExceeded`` arm which accounts it —
                # exactly once, like the transport-raised 504
                rem = deadline - self.clock()
                if rem <= 0:
                    raise DeadlineExceeded(
                        "request spent its deadline at the gateway "
                        "during the handoff stream attach")
            started = False
            try:
                for delta in self.resume_stream_transport(rep, desc,
                                                          rem):
                    if not started:
                        started = True
                        with self._lock:
                            self._handoffs += 1
                        self.m_handoff.labels("resumed").inc()
                    yield delta
                return
            except (ReplicaUnreachable, EngineRecovering,
                    TimeoutError) as e:
                if started:
                    raise       # first byte out: exactly-once forbids replay
                last = e
                self.sleep(self._backoff_s(e, attempt))
                continue
            except DeadlineExceeded:
                raise           # the caller accounts the deadline outcome
            except Exception as e:  # noqa: BLE001 — non-retryable
                if started:
                    raise
                last = e
                break
        # the caller's HandoffResumeError arm accounts the terminal
        # failed outcome AND the m_handoff failed sample — once
        raise HandoffResumeError(
            f"handoff stream resume at {rep.name} failed: {last}")

    @staticmethod
    def _retry_cause(e: Exception) -> str:
        return ("shed" if isinstance(e, QueueFull)
                else "unreachable" if isinstance(e, ReplicaUnreachable)
                else "recovering" if isinstance(e, EngineRecovering)
                else "error")

    def _raise_exhausted(self, last: Optional[Exception]):
        """Retry budget spent: one terminal ``failed`` outcome. When
        the LAST refusal was a capacity shed, re-raise it as QueueFull
        (reason preserved) so the HTTP layer answers 429 + Retry-After —
        pure fleet saturation must read as back-off-and-retry, never as
        a 502 server fault."""
        with self._lock:
            self._counts["failed"] += 1
        self.m_requests.labels("failed").inc()
        if isinstance(last, QueueFull):
            raise QueueFull(
                f"shed by every replica across {self.cfg.max_attempts} "
                f"attempts: {last}", reason=last.reason)
        raise RuntimeError(
            f"request failed after {self.cfg.max_attempts} attempts: "
            f"{last}")

    def stream(self, prompt: List[int], max_new_tokens: int,
               deadline_s: Optional[float] = None,
               tenant: Optional[str] = None, **sampling):
        """Streaming passthrough: retries attempts like ``dispatch``
        until the FIRST delta arrives, then yields deltas straight
        through — a failure after first-byte propagates (tokens already
        left the building; a transparent replay would double-deliver).
        Returns a generator; closing it mid-stream closes the replica
        stream (the serving loop accounts the cancel). ``tenant`` as
        in ``dispatch``."""
        if self.stream_transport is None \
                and self.resume_stream_transport is None:
            # a pure-disagg fleet streams via transport (phase 1 unary
            # to the prefill replica) + resume_stream_transport, so
            # either streaming path satisfies the guard
            raise RuntimeError("router has no stream transport")
        cfg = self.cfg
        t0 = self.clock()
        deadline = t0 + deadline_s if deadline_s else None
        key = prefix_key(prompt, cfg.block_size, cfg.affinity_blocks,
                         tenant=self._key_scope(tenant))
        samp = dict(sampling)
        if tenant is not None:
            samp["tenant"] = tenant

        def attempts(root):
            last: Optional[Exception] = None
            tried: set = set()
            tq_sheds = 0
            for attempt in range(cfg.max_attempts):
                rem = self._remaining(deadline)
                with self._lock:
                    if not self._admitting():
                        self._wait_for_replica(deadline, root)
                    self._admit(tenant)
                    rep = self._pick(key, tried)
                    if rep is None:
                        continue
                    self._inflight_delta(rep.name, +1)
                    offer = self._fabric_offer(rep, prompt, tenant)
                asp = tracing.start_span(
                    "gateway.attempt", component="gateway", parent=root,
                    attrs={"replica": rep.name, "attempt": attempt + 1})
                req = {"prompt": list(prompt),
                       "max_new_tokens": max_new_tokens,
                       "deadline_s": rem, "sampling": dict(samp)}
                if asp.recording:
                    req["traceparent"] = asp.context.encode()
                if offer is not None:
                    req["kv_sources"] = [offer]
                started = False
                released = False
                try:
                    if rep.role == "prefill":
                        # disaggregated stream: the prefill replica
                        # answers unary with a handoff descriptor, the
                        # token stream comes from the decode replica
                        # (phase 2 — once the descriptor is back the
                        # KV has moved, so no whole-request retry:
                        # _follow_handoff_stream retries the DECODE
                        # replica only and is terminal on exhaustion)
                        res = self.transport(rep, req)
                        if isinstance(res, dict) and "handoff" in res:
                            # prefill's work ended with the descriptor:
                            # release its inflight BEFORE the (long)
                            # phase-2 decode stream, like the unary
                            # path — or least-loaded routing would see
                            # a free prefill replica as busy for the
                            # whole downstream decode
                            with self._lock:
                                self._inflight_delta(rep.name, -1)
                            released = True
                            for delta in self._follow_handoff_stream(
                                    res["handoff"], deadline):
                                started = True
                                yield delta
                        else:
                            # completed at prefill (max_new_tokens 1):
                            # the generated tail is the single delta
                            toks = (res.get("tokens", res)
                                    if isinstance(res, dict) else res)
                            started = True
                            yield list(toks[len(prompt):])
                    else:
                        if self.stream_transport is None:
                            # pure-disagg wiring (resume-only) but
                            # discovery surfaced a colocated replica
                            # (e.g. mid-migration): retryable — the
                            # next attempt can land on a prefill
                            # replica this router CAN stream through
                            raise ReplicaUnreachable(
                                f"replica {rep.name} role={rep.role} "
                                "needs a stream_transport this router "
                                "was not configured with")
                        for delta in self.stream_transport(rep, req):
                            started = True
                            yield delta
                    asp.set_attr("outcome", "completed")
                    root.set_attr("replica", rep.name)
                    root.set_attr("attempts", attempt + 1)
                    with self._lock:
                        self._counts["completed"] += 1
                    self.m_requests.labels("completed").inc()
                    return
                except Infeasible as e:
                    asp.set_attr("outcome", "infeasible")
                    asp.set_error(str(e))
                    with self._lock:
                        self._counts["failed"] += 1
                    self.m_requests.labels("failed").inc()
                    raise
                except DeadlineExceeded as e:
                    asp.set_attr("outcome", "deadline")
                    asp.set_error(str(e))
                    with self._lock:
                        self._counts["deadline"] += 1
                    self.m_requests.labels("deadline").inc()
                    raise
                except HandoffResumeError as e:
                    # phase 2 failed before first byte: terminal — the
                    # KV already moved, re-dispatch would re-prefill
                    asp.set_attr("outcome", "handoff_failed")
                    asp.set_error(str(e))
                    with self._lock:
                        self._counts["failed"] += 1
                    self.m_requests.labels("failed").inc()
                    self.m_handoff.labels("failed").inc()
                    raise
                except (QueueFull, ReplicaUnreachable, TimeoutError,
                        RuntimeError) as e:
                    if started:
                        # first byte is out: exactly-once forbids replay
                        asp.set_attr("outcome", "failed_midstream")
                        asp.set_error(str(e))
                        with self._lock:
                            self._counts["failed"] += 1
                        self.m_requests.labels("failed").inc()
                        raise
                    cause = self._retry_cause(e)
                    asp.set_attr("outcome", cause)
                    asp.set_attr("backoff_reason", cause)
                    asp.set_error(str(e))
                    last = e
                    tried.add(rep.name)
                    with self._lock:
                        self._retries += 1
                    self.m_retries.labels(cause).inc()
                    if isinstance(e, QueueFull) \
                            and e.reason == REASON_TENANT:
                        # same tenant-aware retry cap as dispatch()
                        tq_sheds += 1
                        if tq_sheds >= cfg.tenant_quota_attempts:
                            self._raise_exhausted(e)
                    self.sleep(self._backoff_s(e, attempt))
                    continue
                finally:
                    asp.end()
                    if not released:
                        with self._lock:
                            self._inflight_delta(rep.name, -1)
            self._raise_exhausted(last)

        def gen():
            # a generator cannot hold a contextvar scope open across
            # yields, so the journey root is an EXPLICIT span ended in
            # the outer finally; attempts parent on it by reference —
            # retries land as siblings under this one root
            root = tracing.start_span(
                "gateway.request", component="gateway",
                attrs={"prompt_tokens": len(prompt),
                       "tenant": tenant or "",
                       "affinity_key": key or "",
                       "stream": True})
            try:
                yield from attempts(root)
            except GeneratorExit:
                # client hung up: a cancel, not a fault — don't pin
                root.set_attr("outcome", "cancelled")
                raise
            except BaseException as e:  # noqa: BLE001 — span bookkeeping
                root.set_error(str(e))
                raise
            finally:
                root.end()

        return gen()

    # -- introspection ---------------------------------------------------
    # -- fleet SLO roll-up (ISSUE 20) ------------------------------------
    def _slo_locked(self) -> dict:
        """Caller holds the lock. Merges the per-replica ``slo_budget``
        and ``chip_ledger`` /stats blocks from the discovery scrape into
        the fleet view ``GET /v1/slo`` serves: burn recomputed from
        summed window counts, chip-ms/KV byte-seconds summed per tenant,
        and the useful-work-per-chip-hour figure folding in harvested
        chip-seconds from the optional ``harvest_source`` feed."""
        blocks: List[dict] = []
        chip_ms: Dict[str, Dict[str, float]] = {}
        kv_bs: Dict[str, float] = {}
        wall_ms = busy_ms = 0.0
        ledger_replicas = 0
        for _name, r in sorted(self._replicas.items()):
            st = r.stats or {}
            blk = st.get("slo_budget")
            if blk:
                blocks.append(blk)
            led = st.get("chip_ledger")
            if not led:
                continue
            ledger_replicas += 1
            wall_ms += float(led.get("wall_ms", 0.0))
            for tenant, phases in (led.get("chip_ms") or {}).items():
                per = chip_ms.setdefault(tenant, {})
                for phase, ms in phases.items():
                    per[phase] = per.get(phase, 0.0) + ms
                    if tenant != IDLE_TENANT:
                        busy_ms += ms
            for tenant, bs in (led.get("kv_byte_seconds") or {}).items():
                kv_bs[tenant] = kv_bs.get(tenant, 0.0) + bs
        harvested_s = 0.0
        if self.harvest_source is not None:
            try:
                hs = self.harvest_source() or {}
            except Exception:
                hs = {}
            harvested_s = float(
                hs.get("harvested_chip_seconds", 0.0) or 0.0)
        busy_s, wall_s = busy_ms / 1e3, wall_ms / 1e3
        return {
            "burn_threshold": self.cfg.slo_burn_threshold,
            "objectives": aggregate_slo(
                blocks, self.cfg.slo_burn_threshold),
            "chip_ms": chip_ms,
            "kv_byte_seconds": kv_bs,
            "useful_work": {
                "serving_busy_chip_s": round(busy_s, 6),
                "serving_wall_chip_s": round(wall_s, 6),
                "harvested_chip_s": round(harvested_s, 6),
                "useful_work_per_chip_hour": (
                    round(3600.0 * (busy_s + harvested_s) / wall_s, 3)
                    if wall_s > 0 else None),
                "ledger_replicas": ledger_replicas,
            },
        }

    def slo(self) -> dict:
        """The fleet SLO/attribution roll-up ``GET /v1/slo`` serves."""
        with self._lock:
            return self._slo_locked()

    def stats(self) -> dict:
        """The gateway's /stats snapshot; the fleet controller's
        ``gateway_source`` reads ``door_queue`` as the scale-from-zero
        pressure signal."""
        with self._lock:
            admitting = set(self._admitting())
            return {
                "door_queue": len(self._door),
                "door_queue_peak": self._door_peak,
                "replicas": {
                    name: {
                        "ready": r.ready and not r.draining,
                        "draining": r.draining,
                        "role": r.role,
                        "inflight": r.inflight,
                        "load": r.load(),
                    } for name, r in sorted(self._replicas.items())
                },
                "ready_replicas": len(admitting),
                "handoffs": self._handoffs,
                "requests": dict(self._counts),
                "shed": dict(self._shed),
                "tenant_shed": dict(self._tenant_shed),
                "routes": dict(self._routes),
                "retries": self._retries,
                "ring": {"replicas": self._ring.nodes(),
                         "vnodes": self._ring.vnodes},
                "kv_fabric": dict(self._fleet_index.stats(),
                                  enabled=self.cfg.fabric,
                                  offered=self._fabric_offered),
                "slo": self._slo_locked(),
                "config": {
                    "block_size": self.cfg.block_size,
                    "affinity_blocks": self.cfg.affinity_blocks,
                    "max_imbalance": self.cfg.max_imbalance,
                    "admit_pending_per_replica":
                        self.cfg.admit_pending_per_replica,
                    "admit_hbm_frac": self.cfg.admit_hbm_frac,
                    "max_door_queue": self.cfg.max_door_queue,
                    "fabric": self.cfg.fabric,
                    "fabric_max_blocks": self.cfg.fabric_max_blocks,
                    "slo_burn_threshold": self.cfg.slo_burn_threshold,
                    "tenant_quota": (
                        self.cfg.tenant_config.echo()
                        if self.cfg.tenant_config is not None
                        else None),
                },
            }
