"""Fleet front door (ISSUE 11): the production gateway between clients
and the autoscaled serving fleet.

- ``ring``      — prefix-affinity consistent hashing: the prompt's
                  leading block-chain (``kvblocks`` block arithmetic)
                  hashed onto a ring over replicas, so shared system
                  prompts repeatedly land where their KV blocks already
                  live — PR 6's per-replica prefix cache, fleet-wide;
- ``router``    — the exactly-once retrying dispatch core (the
                  ``test_fleet_chaos`` fixture productionized): health/
                  drain-aware retry with reason-aware backoff, global
                  admission from scraped ``/stats``, deadline
                  propagation, and the scale-from-zero door queue whose
                  depth is the activation signal the fleet controller
                  consumes;
- ``discovery`` — the pod inventory (fleet label + pod IP +
                  drain/readiness), derived the same way the fleet
                  controller derives it.

The binary is ``nos-tpu-gateway`` (``nos_tpu/cmd/gateway.py``);
``fleet/sim.py`` shares the ring implementation so the sim's routing
policies and the production router cannot drift.
"""
from nos_tpu.gateway.discovery import PodDiscovery
from nos_tpu.gateway.ring import HashRing, affinity_pick, prefix_key
from nos_tpu.gateway.router import (
    GatewayRouter, HandoffResumeError, Replica, ReplicaUnreachable,
    RouterConfig,
)

__all__ = [
    "GatewayRouter", "HandoffResumeError", "HashRing", "PodDiscovery",
    "Replica", "ReplicaUnreachable", "RouterConfig", "affinity_pick",
    "prefix_key",
]
