"""Serving-plane exception types, deliberately jax-free.

``nos_tpu.cmd.server`` keeps jax out of module import (build_engine and
friends import it lazily) so the binary can parse config / print help in
a jax-less environment; exception types it catches must live in a module
with the same property.
"""


class QueueFull(RuntimeError):
    """Admission refused: the pending queue is at ``max_pending``. Its
    own type so the HTTP layer can answer 429 (shed load, retry) rather
    than a generic 500."""


__all__ = ["QueueFull"]
