"""Serving-plane exception types, deliberately jax-free.

``nos_tpu.cmd.server`` keeps jax out of module import (build_engine and
friends import it lazily) so the binary can parse config / print help in
a jax-less environment; exception types it catches must live in a module
with the same property.

The two admission-refusal types encode the permanent/transient split
the HTTP layer relies on: ``Infeasible`` means THIS request can never
be served by THIS server (HTTP 400 — retrying is useless), ``QueueFull``
means the server is out of capacity RIGHT NOW (HTTP 429 + Retry-After —
shed load and come back). Before the paged KV cache the two were easy
to conflate; with a block pool, "prompt needs more blocks than the
whole pool" (permanent) and "no free blocks this instant" (transient)
must travel different wires.

Every refusal also carries a machine-readable ``reason`` slug the HTTP
layer copies into the 429/400 body (``queue_full`` /
``deadline_unmeetable`` / ``hbm_admission`` / ``tenant_quota`` /
``infeasible``): the fleet controller must tell CAPACITY pressure
(shed because the fleet is undersized — scale up) from DEADLINE
pressure (shed because the client's budget was tight — scaling may not
help), MEMORY pressure (the KV pool or HBM, not slots, is the
bottleneck) and QUOTA pressure (one tenant exceeded its own
entitlement — scaling the fleet for it would starve the guaranteed
tenants the quota exists to protect) without parsing prose."""


class QueueFull(RuntimeError):
    """Admission refused on TRANSIENT capacity: the pending queue is at
    ``max_pending`` (or, under paged KV, the block pool cannot hold
    another waiting request right now). Its own type so the HTTP layer
    can answer 429 + Retry-After (shed load, retry) rather than a
    generic 500. ``reason`` refines the cause on the wire:
    ``queue_full`` (slots/queue exhausted) vs ``hbm_admission`` (free
    slots exist but KV-block/HBM headroom is blocking admission)."""

    reason = "queue_full"

    def __init__(self, *args, reason: str = None):
        super().__init__(*args)
        if reason is not None:
            self.reason = reason


class Infeasible(ValueError):
    """Admission refused PERMANENTLY: the request can never run on this
    server's configuration — prompt + max_new_tokens exceeds the cache
    length, or needs more KV blocks than the whole pool. Subclasses
    ValueError (the HTTP layer's 400 arm, and what library callers
    already catch); distinct so callers can tell "fix the request"
    from "retry later" without string-matching."""

    reason = "infeasible"


class EngineRecovering(RuntimeError):
    """Submission refused because the engine supervisor is mid-restart
    (captured requests are being restored into a rebuilt engine). As
    transient as QueueFull and travels the same wire shape — HTTP 503 +
    Retry-After — but its own type: 503 says "the SERVER is briefly
    degraded", 429 says "YOU are over capacity", and load balancers
    treat them differently."""


class DeadlineUnmeetable(QueueFull):
    """Admission refused because the request's deadline cannot be met:
    the serving loop's rolling TTFT/TPOT estimates put completion past
    ``deadline_s``, so the slot is shed EARLY instead of burning decode
    ticks on an answer the client will discard. Subclasses QueueFull —
    the same transient 429 + Retry-After wire shape — because backing
    off and retrying when load drops is exactly the right client move."""

    reason = "deadline_unmeetable"


class TenantQuotaExceeded(QueueFull):
    """Admission refused because the submitting TENANT is at/over its
    ``max`` token-rate while the engine (or, at the gateway, the fleet)
    is under contention — the last rung of the elastic-quota
    degradation ladder (borrow -> stop lending -> preempt -> shed).
    Subclasses QueueFull — the same transient 429 + Retry-After wire
    shape — because the right client move is to back off until its own
    window drains; scaling the fleet is NOT the answer (the
    ``tenant_quota`` reason is how the autoscaler and the gateway's
    retry policy tell this shed from genuine capacity pressure)."""

    reason = "tenant_quota"


class DeadlineExceeded(RuntimeError):
    """A submitted request's deadline expired before completion: it was
    cancelled at the next tick barrier (or while still queued) and
    accounted under the ``deadline`` terminal outcome. The HTTP layer
    answers 504 — the request was accepted but could not finish in
    time."""


__all__ = ["QueueFull", "Infeasible", "EngineRecovering",
           "DeadlineUnmeetable", "DeadlineExceeded",
           "TenantQuotaExceeded"]
