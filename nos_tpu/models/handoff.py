"""Prefill→decode KV-handoff wire format (deliberately jax-free).

A handoff state is the resumable request description the serving
engine's preemption/restart machinery already produces
(``DecodeServer._request_state`` + the ``_swap_payload`` KV snapshot:
quantized blocks plus their per-block scale planes under int8 — which
is why int8 arenas ship roughly half the bytes per request over DCN).
This module owns turning that dict into bytes and back for the
POST /v1/handoff hop between a prefill-role and a decode-role server,
plus the structural byte model the bench and the
``nos_tpu_serve_handoff_bytes`` histogram report.

Format: one uncompressed ``np.savez`` archive — deterministic bytes
for a deterministic state (the bench pins byte-identical reruns) —
holding the swap arrays under fixed keys and the jsonable metadata as
one UTF-8 plane. Uncompressed on purpose: the payload is int8/bf16 KV
(high-entropy), zip would burn CPU on the latency-critical handoff hop
for single-digit savings, and compressed sizes are not stable across
zlib builds.
"""
from __future__ import annotations

import io
import json
from typing import Dict

import numpy as np

__all__ = ["encode_handoff", "decode_handoff", "handoff_nbytes"]

#: the swap-payload array planes, in serialization order
_ARRAY_KEYS = ("k", "v", "k_scale", "v_scale")


def handoff_nbytes(state: dict) -> int:
    """Structural payload size of one handoff state: the swap arrays'
    bytes (KV planes + int8 scale planes). This is the number the
    ~0.5x int8-vs-bf16 claim is pinned on — array bytes, not wire
    framing, so it is independent of the transport."""
    swap = state.get("swap") or {}
    return sum(int(swap[k].nbytes) for k in _ARRAY_KEYS if k in swap)


def encode_handoff(state: dict) -> bytes:
    """Serialize one handoff state for the wire. ``state`` is the
    ``capture_resumable``/``pop_handoffs`` schema: jsonable fields plus
    a ``swap`` dict of numpy arrays. Arrays travel as raw bytes with
    (shape, dtype-name) metadata — ``np.save``'s own format cannot
    round-trip the ml_dtypes bfloat16 a bf16 arena swaps out, and raw
    bytes keep the encoding byte-deterministic for every dtype."""
    swap = dict(state.get("swap") or {})
    meta = {k: v for k, v in state.items() if k != "swap"}
    meta["swap_nblk"] = int(swap.get("nblk", 0))
    planes = {}
    arrays: Dict[str, np.ndarray] = {}
    for key in _ARRAY_KEYS:
        if key in swap:
            arr = np.asarray(swap[key])
            planes[key] = {"shape": list(arr.shape),
                           "dtype": str(arr.dtype)}
            arrays[key] = np.frombuffer(arr.tobytes(), dtype=np.uint8)
    meta["planes"] = planes
    arrays["meta"] = np.frombuffer(
        json.dumps(meta, sort_keys=True).encode(), dtype=np.uint8)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def _dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # registers bfloat16/float8 with numpy

        return np.dtype(getattr(ml_dtypes, name))


def decode_handoff(data: bytes) -> dict:
    """Inverse of ``encode_handoff``: bytes -> the state dict
    ``DecodeServer.restore`` adopts bit-exactly."""
    with np.load(io.BytesIO(data)) as z:
        meta = json.loads(bytes(z["meta"].tobytes()).decode())
        raw = {k: z[k] for k in _ARRAY_KEYS if k in z.files}
    state = dict(meta)
    planes = state.pop("planes", {})
    nblk = state.pop("swap_nblk", 0)
    if raw:
        swap = {}
        for key, buf in raw.items():
            spec = planes[key]
            swap[key] = np.frombuffer(
                buf.tobytes(), dtype=_dtype(spec["dtype"])
            ).reshape(spec["shape"])
        swap["nblk"] = int(nblk)
        state["swap"] = swap
    return state
