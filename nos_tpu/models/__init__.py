"""JAX workload models for the benchmark demo and gang-scheduling examples."""
