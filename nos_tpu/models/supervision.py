"""Self-healing serving: engine supervision + the serving-chaos seams.

Deliberately jax-free (the same property as ``models/errors.py``): the
serving binary's ServingLoop imports this at module load, and both the
jax-free HTTP-layer tests and the seeded chaos soak drive it over stub
engines.

Two halves:

``EngineSupervisor``
    The restart brain behind ``ServingLoop``'s recovery path. On engine
    failure the loop — instead of dying terminally — asks the
    supervisor whether a restart is still inside the budget, captures
    every live request's resumable state from the dead engine
    (``engine.capture_resumable()``: committed tokens, sampling params,
    and — paged engine with ``kv_swap`` — a best-effort swap-to-host KV
    snapshot), rebuilds the engine through the factory after an
    exponential-backoff-with-jitter delay, and restores the captured
    requests at the front of the fresh engine's queue
    (``engine.restore``). Both resume modes are the bit-exact
    primitives the paged-KV preemption path already proved out:
    byte-exact swap restore, and recompute re-prefill of
    ``prompt + out[:-1]`` (chunking-invariant). Jitter is drawn from a
    seeded ``random.Random`` so a chaos run's restart timeline is
    reproducible.

``FaultInjector`` / ``ChaosEngine``
    A deterministic, seeded fault schedule hooked into the engine's
    step seams by wrapping it in a transparent proxy
    (``injector.wrap(engine)``). Faults fire at loop-tick boundaries
    (one tick = one ``step``/``step_begin`` call):

    - ``error``        raise from the dispatch phase (``step_begin``) —
                       the XLA-OOM / device-loss stand-in
    - ``nofreeblocks`` raise ``kvblocks.NoFreeBlocks`` from dispatch —
                       the pool-sizing-error stand-in
    - ``hang``         sleep ``hang_s`` inside the blocking wait
                       (``step_wait``) — the stuck-tick the watchdog
                       must catch (recoverable only on split-protocol
                       engines: a hang inside a bare ``step()`` holds
                       the serving-loop lock)
    - ``slow``         sleep ``slow_s`` inside the wait, then proceed —
                       latency, not failure
    - ``hbm_spike``    pin the engine's admission-time HBM snapshot at
                       ~full for ``spike_s`` (paged engines only) so
                       memory-aware admission backs off

    The schedule is either explicit ``{tick_index: kind}`` (the bench
    harness replays a fixed one) or drawn per-tick from a seeded RNG
    with per-kind probabilities (the soak). Every injection is recorded
    in ``injected`` with its tick and wall time, so MTTR is measurable
    from the outside.
"""
from __future__ import annotations

import inspect
import random
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from nos_tpu.models.kvblocks import NoFreeBlocks

__all__ = ["EngineSupervisor", "FaultInjector", "ChaosEngine"]


class EngineSupervisor:
    """Restart policy + capture/restore orchestration for one serving
    loop. Thread-compatibility contract: the owning loop serializes
    every call (its condition lock choreographs capture/restore; the
    backoff/build phase runs on exactly one recovery thread at a time),
    so the supervisor itself keeps no lock."""

    def __init__(self, factory: Callable[[], object], *,
                 restart_budget: int = 2, backoff_s: float = 0.5,
                 backoff_max_s: float = 10.0, jitter_frac: float = 0.25,
                 seed: int = 0):
        if restart_budget < 0:
            raise ValueError(
                f"restart_budget must be >= 0, got {restart_budget}")
        if backoff_s < 0 or backoff_max_s < 0:
            raise ValueError("backoff delays must be >= 0")
        self.factory = factory
        self.restart_budget = restart_budget
        self.backoff_s = backoff_s
        self.backoff_max_s = backoff_max_s
        self.jitter_frac = jitter_frac
        self._rng = random.Random(seed)
        # counters the loop mirrors into metrics/stats
        self.attempts = 0           # build attempts consumed (<= budget)
        self.restarts = 0           # successful engine rebuilds
        self.resumed = {"swap": 0, "recompute": 0}
        self.lost = 0
        self.episodes: List[dict] = []

    # -- policy ---------------------------------------------------------
    def can_restart(self) -> bool:
        return self.attempts < self.restart_budget

    def note_attempt(self) -> int:
        """Consume one unit of restart budget; returns the attempt
        index (0-based) the backoff schedule keys on."""
        i = self.attempts
        self.attempts += 1
        return i

    def backoff_delay(self, attempt: int) -> float:
        """Exponential backoff with seeded jitter: base * 2^attempt,
        capped, +/- jitter_frac drawn from the supervisor's own RNG —
        deterministic for a given seed and attempt sequence."""
        base = min(self.backoff_s * (2 ** attempt), self.backoff_max_s)
        if base <= 0:
            return 0.0
        jitter = 1.0 + self.jitter_frac * (2.0 * self._rng.random() - 1.0)
        return base * jitter

    def build(self):
        """One factory call — a fresh engine (fresh compile). Raises
        whatever the factory raises; the caller decides whether budget
        remains for another try."""
        return self.factory()

    # -- capture / restore ---------------------------------------------
    def capture(self, engine, device_ok: bool = True) -> List[dict]:
        """Every live request's resumable state from a (likely dead)
        engine, in original arrival order. Guarded: an engine without
        ``capture_resumable`` (bare stubs) or one whose capture raises
        (host bookkeeping corrupted by the fault) yields [] — those
        requests are drained as ``failed``, never left dangling.
        ``device_ok=False`` (watchdog trips: the device is declared
        wedged, a blocking copy could hang) asks the engine to skip
        device reads — swap snapshots — and capture host state only;
        engines without the parameter (stubs) are called bare."""
        cap = getattr(engine, "capture_resumable", None)
        if cap is None:
            return []
        # signature inspection, NOT a TypeError retry: an internal
        # TypeError from a device_ok-aware capture must not be
        # mistaken for "unsupported kwarg" and retried with device
        # reads re-enabled — that would defeat the wedged-device
        # protection the flag exists for
        try:
            supports = "device_ok" in inspect.signature(cap).parameters
        except (TypeError, ValueError):
            supports = False
        try:
            return list(cap(device_ok=device_ok) if supports else cap())
        except Exception:
            return []

    def restore(self, engine, state: dict) -> Tuple[int, str]:
        """Re-admit one captured request into a fresh engine. Returns
        (new rid, mode) where mode is ``swap`` (byte-exact KV restore)
        or ``recompute`` (re-prefill from the tokens). Raises when the
        engine cannot take it (the loop accounts that request lost)."""
        rid = engine.restore(state)
        mode = "swap" if (state.get("swap") is not None
                          and getattr(engine, "paged", False)) \
            else "recompute"
        return rid, mode

    def note_recovered(self, cause: str, t_fail: float,
                       resumed: Dict[str, int], lost: int) -> None:
        """Record one completed restart episode (the chaos bench's MTTR
        source). ``t_fail`` is the monotonic instant the failure was
        detected; recovery ends now."""
        self.restarts += 1
        for mode, n in resumed.items():
            self.resumed[mode] += n
        self.lost += lost
        self.episodes.append({
            "cause": cause,
            "t_fail": t_fail,       # monotonic failure-detection stamp:
            #                         bench_chaos_serve correlates it
            #                         with the injector's event log to
            #                         split detection from recovery
            "mttr_s": max(0.0, time.monotonic() - t_fail),
            "resumed": dict(resumed),
            "lost": lost,
        })

    def stats(self) -> dict:
        return {
            "restart_budget": self.restart_budget,
            "attempts": self.attempts,
            "restarts": self.restarts,
            "resumed": dict(self.resumed),
            "lost": self.lost,
            "episodes": [dict(e) for e in self.episodes],
        }


class FaultInjector:
    """Deterministic seeded fault schedule for the serving-chaos
    harness. One tick = one serving-loop quantum (a ``step`` or
    ``step_begin`` call on the wrapped engine)."""

    KINDS = ("error", "nofreeblocks", "hang", "slow", "hbm_spike")

    def __init__(self, schedule: Optional[Dict[int, str]] = None, *,
                 seed: int = 0, p_error: float = 0.0,
                 p_hang: float = 0.0, p_slow: float = 0.0,
                 hang_s: float = 1.0, slow_s: float = 0.05,
                 spike_s: float = 0.5):
        if schedule:
            bad = {k for k in schedule.values() if k not in self.KINDS}
            if bad:
                raise ValueError(f"unknown fault kinds {sorted(bad)}; "
                                 f"choose from {self.KINDS}")
        self.schedule = dict(schedule or {})
        self._rng = random.Random(seed)
        self.p_error = p_error
        self.p_hang = p_hang
        self.p_slow = p_slow
        self.hang_s = hang_s
        self.slow_s = slow_s
        self.spike_s = spike_s
        self.tick = 0
        self.injected: List[dict] = []      # {"tick", "kind", "t"}
        self._pending_wait: Optional[str] = None
        self._lock = threading.Lock()

    def wrap(self, engine) -> "ChaosEngine":
        return ChaosEngine(engine, self)

    # -- seams (called by ChaosEngine) ---------------------------------
    def _decide(self) -> Optional[str]:
        kind = self.schedule.get(self.tick)
        if kind is None and (self.p_error or self.p_hang or self.p_slow):
            # one draw sequence per tick, independent of which faults
            # fire: keeps a seed's schedule stable across kinds
            r = self._rng.random()
            if r < self.p_error:
                kind = "error"
            elif r < self.p_error + self.p_hang:
                kind = "hang"
            elif r < self.p_error + self.p_hang + self.p_slow:
                kind = "slow"
        return kind

    def before_dispatch(self, inner) -> None:
        with self._lock:
            kind = self._decide()
            tick = self.tick
            self.tick += 1
            if kind is None:
                return
            self.injected.append({"tick": tick, "kind": kind,
                                  "t": time.monotonic()})
            if kind in ("hang", "slow"):
                self._pending_wait = kind
                return
        if kind == "error":
            raise RuntimeError(
                f"injected engine fault (chaos tick {tick})")
        if kind == "nofreeblocks":
            raise NoFreeBlocks(
                f"injected block-pool squeeze (chaos tick {tick})")
        if kind == "hbm_spike":
            # pin the paged engine's admission-time HBM snapshot near
            # the limit so memory-aware admission defers (guarded: a
            # slot-static engine has no such seam and just ignores it)
            if hasattr(inner, "hbm") and hasattr(inner, "_hbm_next"):
                inner.hbm = {"device": "chaos:0",
                             "in_use": 999, "limit": 1000}
                inner._hbm_next = time.perf_counter() + self.spike_s
                inner._hbm_dead = False

    def before_wait(self) -> None:
        with self._lock:
            kind, self._pending_wait = self._pending_wait, None
        if kind == "hang":
            time.sleep(self.hang_s)
        elif kind == "slow":
            time.sleep(self.slow_s)

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for e in self.injected:
            out[e["kind"]] = out.get(e["kind"], 0) + 1
        return out


class ChaosEngine:
    """Transparent engine proxy: every attribute delegates to the
    wrapped engine (so the serving loop's protocol sniffing — split
    step, cancel, ledger, paged — sees exactly the inner engine's
    surface), with the injector spliced into the tick seams."""

    def __init__(self, inner, injector: FaultInjector):
        object.__setattr__(self, "_inner", inner)
        object.__setattr__(self, "_injector", injector)

    def __setattr__(self, name, value):
        # writes delegate too: the serving loop ASSIGNS engine
        # attributes (e.g. ``engine.compile_events = []`` to drain the
        # compile ledger) — shadowing them on the proxy would silently
        # fork state from the wrapped engine
        setattr(self.__dict__["_inner"], name, value)

    def __getattr__(self, name):
        inner = self.__dict__["_inner"]
        inj = self.__dict__["_injector"]
        attr = getattr(inner, name)         # AttributeError propagates:
        if name == "step_begin":            # hasattr mirrors the inner
            def step_begin(*a, **kw):
                inj.before_dispatch(inner)
                return attr(*a, **kw)
            return step_begin
        if name == "step_wait":
            def step_wait(*a, **kw):
                inj.before_wait()
                return attr(*a, **kw)
            return step_wait
        if name == "step":
            def step(*a, **kw):
                # step-only engines: dispatch + wait seams collapse
                # into the one call (a hang here is unrecoverable by
                # design — the loop holds its lock through step())
                inj.before_dispatch(inner)
                inj.before_wait()
                return attr(*a, **kw)
            return step
        return attr
