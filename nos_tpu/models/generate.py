"""Autoregressive decoding with a KV cache, TPU-first.

The inference half of the decoder workload (training lives in
``transformer.py``; both share the same parameter pytree). Design per the
TPU brief:

- **Static shapes everywhere.** The cache is pre-allocated at
  ``max_len`` and written with ``lax.dynamic_update_slice``; the decode
  loop is a ``lax.scan`` over step indices, so the whole generation
  compiles to one XLA program — no per-token retrace, no dynamic shapes.
- **GQA-sized cache.** K/V are cached at ``kv_heads`` (never repeated to
  ``n_heads``): decode is HBM-bandwidth-bound on reading the cache, so a
  4x-grouped model reads 4x less. Query heads group in the einsum,
  exactly like ``ops.attention.xla_attention``.
- **One function for prefill and decode.** ``forward_with_cache`` handles
  any chunk length S >= 1 with absolute-position rope and a causal mask
  against the cache timeline, so prefill (S = prompt length) and decode
  (S = 1) are the same traced program at two shapes.
- Works under jit/pjit with the training param shardings (the cache
  follows the k/v head axis over tp).

Reference parity note: the reference repo is a K8s operator suite with no
generation path; this module exists because the TPU rebuild's workload
plane (SURVEY §2.7) owns the model stack end to end.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from nos_tpu.models.transformer import Params, TransformerConfig
from nos_tpu.ops.layers import (
    apply_rope, rms_norm, rope_frequencies, swiglu,
)
from nos_tpu.ops.quant import embed_lookup, qdot

Cache = Dict[str, jax.Array]


def init_cache(cfg: TransformerConfig, batch: int,
               max_len: Optional[int] = None, dtype=None,
               per_row_pos: bool = False) -> Cache:
    """Pre-allocated KV cache: k/v [L, B, Hkv, max_len, head_dim] plus the
    write position — a scalar (all rows in lockstep: generate/
    speculative) or, with ``per_row_pos``, a [B] vector so every row sits
    at its own depth (continuous-batching serving slots). bf16 by default
    (cfg.dtype)."""
    max_len = max_len or cfg.max_seq
    if max_len > cfg.max_seq:
        raise ValueError(
            f"cache max_len {max_len} exceeds the rope table "
            f"(cfg.max_seq {cfg.max_seq})")
    dtype = dtype or cfg.dtype
    shape = (cfg.n_layers, batch, cfg.kv_heads, max_len, cfg.head_dim)
    return {
        "k": jnp.zeros(shape, dtype),
        "v": jnp.zeros(shape, dtype),
        "pos": jnp.zeros((batch,) if per_row_pos else (), jnp.int32),
    }


def replicated_logits(step: jax.Array, mesh=None) -> jax.Array:
    """Canonicalize a logit row for a SAMPLING decision: f32, and —
    under a mesh — constrained replicated before any sort/softmax/
    categorical runs on it.

    Why (the triaged root cause of the seed-old sharded-sampling
    failures): logits leave the unembed matmul sharded over the vocab
    axis (`tp`), and GSPMD propagates that sharding BACKWARD into
    `jax.random.categorical`'s threefry program — whose partitioned
    lowering draws DIFFERENT gumbel bits than the replicated one, so
    the sharded engine sampled a different stream than the single-host
    engine even from bitwise-close logits (the sort/softmax/cumsum
    stages were verified bit-equal; only the in-categorical RNG
    diverged). Constraining the row replicated makes the whole
    decision pipeline — truncation thresholds, CDF boundaries, and the
    RNG — run the exact single-device program on every chip: same
    bits as an unsharded run, so sampled streams are invariant to the
    mesh. The remaining tp reduction-order ULPs in the logit VALUES
    are absorbed the same way greedy argmax absorbs them (O(1) gaps
    at every comparison, not O(ulp)). f32 is a no-op today (logits
    are already f32) but pins the contract against a lower-precision
    head. With ``mesh=None`` this is the identity on values —
    single-host streams are unchanged."""
    step = step.astype(jnp.float32)
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec
        step = jax.lax.with_sharding_constraint(
            step, NamedSharding(mesh, PartitionSpec()))
    return step


def cache_shardings(mesh, cfg: TransformerConfig,
                    per_row_pos: bool = False) -> Cache:
    """NamedShardings for an ``init_cache`` pytree on a serving mesh:
    K/V sharded across KV heads over ``tp`` (decode is bound by reading
    the cache from HBM, so the bandwidth splits across chips exactly
    like the attention heads do under ``param_shardings``); the slot/
    batch axis and ``pos`` replicated — slots are admitted and recycled
    individually by the host, which must see every row. Mesh axes the
    layout doesn't have are dropped, same contract as the param side."""
    from nos_tpu.parallel.mesh import logical_to_sharding
    if "tp" in mesh.axis_names:
        tp = mesh.shape["tp"]
        if cfg.kv_heads % tp:
            raise ValueError(
                f"kv_heads {cfg.kv_heads} not divisible by tp={tp}; the "
                f"cache head axis cannot shard evenly")
    kv = logical_to_sharding(mesh, None, None, "tp", None, None)
    pos = logical_to_sharding(mesh, *((None,) if per_row_pos else ()))
    return {"k": kv, "v": kv, "pos": pos}


def init_paged_cache(cfg: TransformerConfig, kv_blocks: int,
                     block_size: int, batch: int,
                     dtype=None, kv_dtype: str = "bf16") -> Cache:
    """Pooled paged KV arena: k/v ``[L, kv_blocks, Hkv, block_size,
    head_dim]`` — ONE HBM pool shared by every serving slot through
    per-slot block tables — plus the per-row write position ``pos``
    [batch]. Block 0 is the reserved null block (kvblocks.NULL_BLOCK):
    unassigned table entries point at it, so it is never valid data.
    Unlike ``init_cache`` the resident footprint scales with
    ``kv_blocks * block_size`` TOTAL tokens, not ``batch * max_len``
    worst-case tokens — the PagedAttention economics. The per-row
    LOGICAL timeline length is the block table's affair (the serving
    engine caps it at its ``max_len <= cfg.max_seq``, same rope-table
    bound as ``init_cache``).

    ``kv_dtype="int8"`` stores the arena quantized (symmetric int8, one
    f32 scale per (layer, block, head, token) living in the
    ``k_scale``/``v_scale`` planes — scales are indexed by PHYSICAL
    block, so they are freed/forked/COW'd in lockstep with their
    blocks): KV bytes per token drop ~2x vs bf16, which at a fixed HBM
    budget roughly doubles the block pool and therefore sustained
    paged concurrency. Writes quantize in ``paged_scatter_kv`` path,
    reads dequantize in the gather path — see ``forward_paged``."""
    dtype = dtype or cfg.dtype
    shape = (cfg.n_layers, kv_blocks, cfg.kv_heads, block_size,
             cfg.head_dim)
    if kv_dtype not in ("bf16", "int8"):
        raise ValueError(
            f"kv_dtype must be bf16|int8, got {kv_dtype!r}")
    if kv_dtype == "int8":
        return {
            "k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            "k_scale": jnp.zeros(shape[:-1], jnp.float32),
            "v_scale": jnp.zeros(shape[:-1], jnp.float32),
            "pos": jnp.zeros((batch,), jnp.int32),
        }
    return {
        "k": jnp.zeros(shape, dtype),
        "v": jnp.zeros(shape, dtype),
        "pos": jnp.zeros((batch,), jnp.int32),
    }


def paged_cache_shardings(mesh, cfg: TransformerConfig,
                          kv_dtype: str = "bf16") -> Cache:
    """NamedShardings for an ``init_paged_cache`` pytree on a serving
    mesh: the arena (and, for int8, its scale planes) shards across KV
    heads over ``tp`` — axis 2 of ``[L, NB, Hkv, bs, D]``, the same
    head axis ``cache_shardings`` splits, because paged decode is
    bound by reading the arena from HBM exactly like the slot-static
    cache. The BLOCK axis stays replicated: block ids are host control
    state (tables, allocator refcounts), and every host must be able
    to address any block. ``pos`` and block tables are host-written
    control rows — replicated, like the slot-static mesh convention
    (serving.py keeps them device_put replicated)."""
    from nos_tpu.parallel.mesh import logical_to_sharding
    if "tp" in mesh.axis_names:
        tp = mesh.shape["tp"]
        if cfg.kv_heads % tp:
            raise ValueError(
                f"kv_heads {cfg.kv_heads} not divisible by tp={tp}; the "
                f"paged arena's head axis cannot shard evenly")
    kv = logical_to_sharding(mesh, None, None, "tp", None, None)
    shd = {"k": kv, "v": kv,
           "pos": logical_to_sharding(mesh, None)}
    if kv_dtype == "int8":
        scale = logical_to_sharding(mesh, None, None, "tp", None)
        shd["k_scale"] = scale
        shd["v_scale"] = scale
    return shd


def _paged_kernel_sharded(q, ck, cv, table, pos, *, k_scale, v_scale,
                          scale, mesh):
    """``paged_decode_attention`` under a mesh: shard_map over the
    ``tp`` axis so each chip runs the Pallas kernel on ITS slice of
    the head axis (arena blocks arrive pre-sharded over Hkv; q over H;
    tables/pos are replicated control rows). The kernel grid is
    head-parallel — rows of different kv heads never share softmax
    state — so the per-shard program is the single-host kernel at
    Hkv/tp heads, and no collective (and no unsharded timeline) is
    needed. Meshes without a ``tp`` axis run the kernel replicated
    (every axis in the specs below degenerates to no partitioning)."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    tp = "tp" if "tp" in mesh.axis_names else None
    head = P(None, tp, None, None)      # q/out [B,H,S,D]; arena [NB,Hkv,bs,D]
    rep = P()
    in_specs = (head, head, head, rep, rep)
    args = [q, ck, cv, table, pos]
    if k_scale is not None:
        sc = P(None, tp, None)          # [NB, Hkv, bs]
        in_specs = in_specs + (sc, sc)
        args += [k_scale, v_scale]

    def local(q, ck, cv, table, pos, *scales):
        ks, vs = scales if scales else (None, None)
        from nos_tpu.ops.attention import paged_decode_attention
        return paged_decode_attention(q, ck, cv, table, pos,
                                      k_scale=ks, v_scale=vs,
                                      scale=scale)

    return shard_map(local, mesh=mesh, in_specs=in_specs,
                     out_specs=head, check_rep=False)(*args)


def forward_paged(
    params: Params, cfg: TransformerConfig, tokens: jax.Array,
    cache: Cache, table: jax.Array, *,
    paged_impl: Optional[str] = None, mesh=None,
) -> Tuple[jax.Array, Cache]:
    """``forward_with_cache`` over a paged arena: tokens [B, S] (the
    next S tokens after each row's ``cache['pos']``), per-slot block
    tables [B, nb] int32 -> (logits [B, S, vocab], updated cache).
    Per-position math is identical to the slot-static path — K/V writes
    scatter into the arena by block table
    (ops.attention.paged_scatter_kv) and attention runs over the
    gathered per-row timeline (paged_gather_kv) with the same causal
    ``pos`` mask — so greedy decode under paging is bit-identical to
    ``generate`` (tested). ``table`` is a plain input, never donated:
    the host mutates it between dispatches (growth, COW remaps) while
    the donated arena chains through the self-feeding decode program.

    An int8 arena (``init_paged_cache(kv_dtype="int8")`` — the cache
    carries ``k_scale``/``v_scale`` planes) quantizes each K/V write on
    the scatter (per-token symmetric scales stored per physical block)
    and dequantizes on the gather, so the per-position attention math
    downstream of the dequant is the SAME program — the int8
    self-consistency contract (serving == reference generate through
    the identical int8 KV path) holds because writer and reader share
    these exact quantize/dequantize ops.

    Every query shape dispatches the fused Pallas kernel when
    ``NOS_TPU_PAGED_KERNEL=1`` (``ops.attention.effective_paged_impl``):
    ``paged_decode_attention`` walks the block table in-kernel for the
    whole [B, S] query window — decode steps (S == 1), fused
    multi-step decode, speculative verify bursts, and paged suffix
    prefill alike — and fuses the int8 dequant into the attention
    inner loop, so neither the gathered timeline nor a dequantized
    bf16 copy is ever materialized. The kernel's per-row causal mask
    (query position ``pos + s_idx`` vs block-local key positions) plus
    dead-tail elision make a width-S window accumulate EXACTLY the
    online-softmax state S sequential decode steps would: rows whose
    causal frontier ends mid-window see only all-masked scores for
    later blocks, which underflow to exact zeros in the f32
    accumulator. That is what lets kernel decode and kernel verify
    commit identical tokens (the speculative greedy-equals-plain
    contract) — tested against the XLA gather oracle across the fuzz
    grid in tests/test_paged_kernel.py.

    ``paged_impl`` ("kernel" | "xla") overrides the env lookup: the
    serving engine passes the formulation it captured at build time so
    a later env change (another engine built in the same process)
    cannot silently flip what a not-yet-traced shape compiles to while
    /stats echoes the stale value. The two formulations agree
    token-for-token on every serving contract, but only within
    reassociation tolerance at the logit level (the kernel's online
    softmax reassociates), so one engine must never mix them across
    dispatches of the same stream.

    ``mesh`` (the serving engine's mesh, None single-host) only
    matters to the kernel formulation: Pallas cannot be auto-
    partitioned by GSPMD, so kernel decode steps on a mesh run under
    ``shard_map`` over the ``tp`` axis (head-parallel — each chip
    walks its own Hkv/tp slice of the arena; see
    ``_paged_kernel_sharded``). The XLA gather formulation needs no
    mesh plumb: GSPMD partitions the gather/scatter/attention ops
    itself, keeping the arena's head sharding through the gathered
    view — the mesh escape hatch."""
    from nos_tpu.ops.attention import (
        dequantize_kv, effective_paged_impl, paged_decode_attention,
        paged_gather_kv, paged_gather_scale, paged_scatter_kv,
        paged_scatter_scale, quantize_kv,
    )

    b, s = tokens.shape
    if paged_impl is None:
        paged_impl = effective_paged_impl(cfg.head_dim)
    use_kernel = paged_impl == "kernel"
    pos0 = cache["pos"]                                     # [B]
    int8_kv = "k_scale" in cache
    freqs = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
    positions = pos0[:, None] + jnp.arange(s)[None, :]      # [B, S]
    scale = cfg.head_dim ** -0.5

    x = embed_lookup(params["embed"], tokens, cfg.dtype)

    def layer_body(x, layer_and_cache):
        if int8_kv:
            layer, ck, cv, cks, cvs = layer_and_cache       # arena slices
        else:
            layer, ck, cv = layer_and_cache
            cks = cvs = None
        h = rms_norm(x, layer["attn_norm"])
        q = qdot(h, layer["wq"]).reshape(b, s, cfg.n_heads, cfg.head_dim)
        k = qdot(h, layer["wk"]).reshape(b, s, cfg.kv_heads, cfg.head_dim)
        v = qdot(h, layer["wv"]).reshape(b, s, cfg.kv_heads, cfg.head_dim)
        q, k = (apply_rope(t, freqs, positions) for t in (q, k))
        kt = k.transpose(0, 2, 1, 3)                        # [B, Hkv, S, D]
        vt = v.transpose(0, 2, 1, 3)
        # named phases so bench_profile traces attribute decode-step
        # time to the table-walk kernel vs the surrounding ops
        with jax.named_scope("paged_scatter"):
            if int8_kv:
                kq, ksc = quantize_kv(kt)
                vq, vsc = quantize_kv(vt)
                ck = paged_scatter_kv(ck, table, pos0, kq)
                cv = paged_scatter_kv(cv, table, pos0, vq)
                cks = paged_scatter_scale(cks, table, pos0, ksc)
                cvs = paged_scatter_scale(cvs, table, pos0, vsc)
            else:
                ck = paged_scatter_kv(ck, table, pos0,
                                      kt.astype(ck.dtype))
                cv = paged_scatter_kv(cv, table, pos0,
                                      vt.astype(cv.dtype))
        if use_kernel:
            with jax.named_scope("paged_attention_kernel"):
                if mesh is not None:
                    o = _paged_kernel_sharded(
                        q.transpose(0, 2, 1, 3), ck, cv, table, pos0,
                        k_scale=cks, v_scale=cvs, scale=scale,
                        mesh=mesh)
                else:
                    o = paged_decode_attention(
                        q.transpose(0, 2, 1, 3), ck, cv, table, pos0,
                        k_scale=cks, v_scale=cvs, scale=scale)
        else:
            with jax.named_scope("paged_gather"):
                if int8_kv:
                    gk = dequantize_kv(
                        paged_gather_kv(ck, table),
                        paged_gather_scale(cks, table), cfg.dtype)
                    gv = dequantize_kv(
                        paged_gather_kv(cv, table),
                        paged_gather_scale(cvs, table), cfg.dtype)
                else:
                    gk = paged_gather_kv(ck, table)
                    gv = paged_gather_kv(cv, table)
            with jax.named_scope("paged_attention"):
                o = _cached_attention(
                    q.transpose(0, 2, 1, 3), gk, gv, positions, scale)
        o = o.transpose(0, 2, 1, 3).reshape(b, s, cfg.d_model)
        x = x + qdot(o, layer["wo"])
        if cfg.n_experts > 0:
            from nos_tpu.ops.moe import moe_ffn

            h2 = rms_norm(x, layer["mlp_norm"])
            y, _aux = moe_ffn(
                h2, layer["w_router"], layer["w_gate"], layer["w_up"],
                layer["w_down"], cfg.expert_capacity_factor,
            )
            x = x + y
        else:
            h2 = rms_norm(x, layer["mlp_norm"])
            x = x + swiglu(h2, layer["w_gate"], layer["w_up"],
                           layer["w_down"])
        return x, ((ck, cv, cks, cvs) if int8_kv else (ck, cv))

    if int8_kv:
        x, (ks, vs, kss, vss) = jax.lax.scan(
            layer_body, x,
            (params["layers"], cache["k"], cache["v"],
             cache["k_scale"], cache["v_scale"]))
        out_cache = {"k": ks, "v": vs, "k_scale": kss, "v_scale": vss,
                     "pos": pos0 + s}
    else:
        x, (ks, vs) = jax.lax.scan(
            layer_body, x, (params["layers"], cache["k"], cache["v"]))
        out_cache = {"k": ks, "v": vs, "pos": pos0 + s}

    x = rms_norm(x, params["final_norm"])
    logits = qdot(x, params["unembed"]).astype(jnp.float32)
    return logits, out_cache


def generate_paged(
    params: Params,
    cfg: TransformerConfig,
    prompt: jax.Array,
    max_new_tokens: int,
    *,
    block_size: int,
    kv_dtype: str = "bf16",
    max_len: Optional[int] = None,
) -> jax.Array:
    """Reference GREEDY generation through the paged KV path: prompt
    [B, S] -> [B, S + max_new_tokens], decoding one token at a time
    over a paged arena with a dense identity-style block table (row i
    owns blocks [1 + i*nb, 1 + (i+1)*nb); block 0 stays the reserved
    null block). Exists as the oracle the serving engine is pinned
    against: with ``kv_dtype="bf16"`` it is bit-identical to
    ``generate`` (paged_gather/scatter preserve the timeline exactly),
    and with ``kv_dtype="int8"`` it IS the definition of correct int8
    decoding — the serving engine must match it token-for-token through
    the identical quantize-on-write / dequantize-on-read ops.

    Honors ``NOS_TPU_PAGED_KERNEL`` like every ``forward_paged``
    caller: with the fused kernel enabled, prefill AND decode steps
    here trace the SAME kernel programs serving traces, so serving ==
    this reference stays token-for-token — but the bf16 bit-identity
    to ``generate`` above is a property of the XLA formulation (the
    kernel's online softmax is tolerance-equivalent, not bit-equal;
    see tests/test_paged_kernel.py)."""
    b, s = prompt.shape
    if max_new_tokens <= 0:
        return prompt
    max_len = max_len or cfg.max_seq
    if s + max_new_tokens > max_len:
        raise ValueError(
            f"prompt ({s}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"cache length {max_len}")
    if max_len % block_size:
        raise ValueError(
            f"max_len {max_len} must be a multiple of block_size "
            f"{block_size}")
    nb = max_len // block_size
    cache = init_paged_cache(cfg, 1 + b * nb, block_size, b,
                             kv_dtype=kv_dtype)
    table = (1 + jnp.arange(b * nb, dtype=jnp.int32)).reshape(b, nb)
    logits, cache = forward_paged(params, cfg, prompt, cache, table)
    tok = jnp.argmax(logits[:, -1], axis=-1)
    out = [tok]
    for _ in range(max_new_tokens - 1):
        logits, cache = forward_paged(params, cfg, tok[:, None], cache,
                                      table)
        tok = jnp.argmax(logits[:, -1], axis=-1)
        out.append(tok)
    return jnp.concatenate([prompt, jnp.stack(out, axis=1)], axis=1)


def _cached_attention(q, ck, cv, positions, scale):
    """q: [B, H, S, D] (queries at absolute ``positions``); ck/cv:
    [B, Hkv, T, D] (full cache). Causal against the cache timeline:
    query at absolute position p attends to cache slots [0, p]. Query
    heads group per kv head — no K/V repeat."""
    b, h, s, d = q.shape
    h_kv = ck.shape[1]
    g = h // h_kv
    qg = q.reshape(b, h_kv, g, s, d)
    scores = jnp.einsum(
        "bhgqd,bhkd->bhgqk", qg, ck, preferred_element_type=jnp.float32
    ) * scale
    t = ck.shape[2]
    # positions: [S] (lockstep rows) or [B, S] (per-row depths)
    mask = jnp.arange(t) <= positions[..., None]    # [S, T] or [B, S, T]
    if mask.ndim == 2:
        mask = mask[None]
    scores = jnp.where(mask[:, None, None], scores,
                       jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhgqk,bhkd->bhgqd", probs, cv).reshape(b, h, s, d)


def forward_with_cache(
    params: Params, cfg: TransformerConfig, tokens: jax.Array, cache: Cache,
) -> Tuple[jax.Array, Cache]:
    """tokens [B, S] (the next S tokens after cache['pos']) -> (logits
    [B, S, vocab], updated cache). S is the prefill chunk length or 1 for
    single-token decode — same code, two compiled shapes. A [B]-vector
    ``pos`` (init_cache(per_row_pos=True)) lets every row sit at its own
    depth — the serving-slot case."""
    b, s = tokens.shape
    pos0 = cache["pos"]
    vector = getattr(pos0, "ndim", 0) == 1
    freqs = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
    positions = (pos0[:, None] + jnp.arange(s)[None, :] if vector
                 else pos0 + jnp.arange(s))
    scale = cfg.head_dim ** -0.5

    # params may be the training pytree or its int8-quantized twin
    # (models/quant.quantize_params): qdot/embed_lookup handle both
    x = embed_lookup(params["embed"], tokens, cfg.dtype)

    def layer_body(x, layer_and_cache):
        layer, ck, cv = layer_and_cache
        h = rms_norm(x, layer["attn_norm"])
        q = qdot(h, layer["wq"]).reshape(b, s, cfg.n_heads, cfg.head_dim)
        k = qdot(h, layer["wk"]).reshape(b, s, cfg.kv_heads, cfg.head_dim)
        v = qdot(h, layer["wv"]).reshape(b, s, cfg.kv_heads, cfg.head_dim)
        q, k = (apply_rope(t, freqs, positions) for t in (q, k))
        kt = k.transpose(0, 2, 1, 3).astype(ck.dtype)
        vt = v.transpose(0, 2, 1, 3).astype(cv.dtype)
        if vector:
            # per-row write offsets: one dynamic_update_slice per row
            write = jax.vmap(
                lambda c, u, p: jax.lax.dynamic_update_slice(c, u, (0, p, 0)))
            ck = write(ck, kt, pos0)
            cv = write(cv, vt, pos0)
        else:
            ck = jax.lax.dynamic_update_slice(ck, kt, (0, 0, pos0, 0))
            cv = jax.lax.dynamic_update_slice(cv, vt, (0, 0, pos0, 0))
        o = _cached_attention(q.transpose(0, 2, 1, 3), ck, cv, positions,
                              scale)
        o = o.transpose(0, 2, 1, 3).reshape(b, s, cfg.d_model)
        x = x + qdot(o, layer["wo"])
        if cfg.n_experts > 0:
            from nos_tpu.ops.moe import moe_ffn

            h2 = rms_norm(x, layer["mlp_norm"])
            y, _aux = moe_ffn(
                h2, layer["w_router"], layer["w_gate"], layer["w_up"],
                layer["w_down"], cfg.expert_capacity_factor,
            )
            x = x + y
        else:
            h2 = rms_norm(x, layer["mlp_norm"])
            x = x + swiglu(h2, layer["w_gate"], layer["w_up"],
                           layer["w_down"])
        return x, (ck, cv)

    x, (ks, vs) = jax.lax.scan(
        layer_body, x, (params["layers"], cache["k"], cache["v"]))

    x = rms_norm(x, params["final_norm"])
    logits = qdot(x, params["unembed"]).astype(jnp.float32)
    return logits, {"k": ks, "v": vs, "pos": pos0 + s}


def _truncate_logits(logits: jax.Array, top_k: int, top_p: float) -> jax.Array:
    """Mask logits outside the top-k set and/or the top-p nucleus of the
    distribution ``softmax(logits)`` — callers pass ALREADY-TEMPERED
    logits so the nucleus covers the distribution actually sampled from.
    Scalar ``top_k``/``top_p`` shared by every row; thin shape adapter
    over ``_truncate_logits_rows`` (ONE implementation of the sequential
    top-k-then-nucleus semantics). No-op when both are unset."""
    do_k = 0 < top_k < logits.shape[-1]
    do_p = 0.0 < top_p < 1.0
    if not (do_k or do_p):
        return logits
    shape = logits.shape
    flat = logits.reshape(-1, shape[-1])
    b = flat.shape[0]
    out = _truncate_logits_rows(
        flat, jnp.full((b,), top_k, jnp.int32),
        jnp.full((b,), top_p, jnp.float32))
    return out.reshape(shape)


def _truncate_logits_rows(logits: jax.Array, top_k: jax.Array,
                          top_p: jax.Array) -> jax.Array:
    """Per-ROW top-k/top-p truncation: ``top_k`` [B] int32 (0 = off) and
    ``top_p`` [B] float (outside (0,1) = off) vary by row — the
    continuous-batching case, where every slot carries its own sampling
    params but must share ONE compiled decode program. Same sequential
    semantics as ``_truncate_logits`` (top-k first, then the nucleus of
    what's left); rows with both filters off pass through unchanged."""
    b, v = logits.shape
    neg = jnp.finfo(logits.dtype).min
    k_eff = jnp.where((top_k > 0) & (top_k < v), top_k, v)      # [B]
    # off-rows get threshold 2.0 (not 1.0): cumsum float error must
    # never drop the least-likely token of an untruncated row
    p_eff = jnp.where((top_p > 0.0) & (top_p < 1.0), top_p, 2.0)
    sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
    sorted_desc = jnp.where(
        jnp.arange(v)[None, :] < k_eff[:, None], sorted_desc, neg)
    kth = jnp.take_along_axis(sorted_desc, k_eff[:, None] - 1, axis=-1)
    logits = jnp.where(logits >= kth, logits, neg)
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = jnp.concatenate(
        [jnp.zeros_like(cum[..., :1]), cum[..., :-1]], axis=-1) \
        < p_eff[:, None]
    cutoff = jnp.min(
        jnp.where(keep, sorted_desc, jnp.finfo(logits.dtype).max),
        axis=-1, keepdims=True)
    return jnp.where(logits >= cutoff, logits, neg)


def generate(
    params: Params,
    cfg: TransformerConfig,
    prompt: jax.Array,
    max_new_tokens: int,
    *,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 0.0,
    rng: Optional[jax.Array] = None,
    max_len: Optional[int] = None,
    mesh=None,
) -> jax.Array:
    """Greedy (temperature 0) or temperature sampling, optionally
    truncated to the ``top_k`` most likely tokens and/or the smallest
    ``top_p``-mass nucleus. prompt [B, S] -> [B, S + max_new_tokens].
    One prefill pass over the prompt, then a ``lax.scan`` of single-token
    decode steps — jit the whole call.

    ``max_len`` bounds the cache (default cfg.max_seq); the caller must
    keep S + max_new_tokens <= max_len.

    ``mesh``: pass the device mesh when ``params`` are tp-sharded and
    ``temperature > 0`` — every sampling decision then runs on a
    replicated f32 logit row (``replicated_logits``), which pins the
    sampled stream bit-equal to the single-device run across mesh
    shapes (greedy needs no mesh: argmax is layout-exact already).
    The serving engine passes its own mesh automatically."""
    b, s = prompt.shape
    if max_new_tokens <= 0:
        return prompt
    max_len = max_len or cfg.max_seq
    if s + max_new_tokens > max_len:
        raise ValueError(
            f"prompt ({s}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"cache length {max_len}")
    if temperature > 0 and rng is None:
        raise ValueError("temperature sampling needs an rng key")
    if temperature <= 0 and (top_k or top_p):
        raise ValueError(
            "top_k/top_p only apply when sampling — set temperature > 0 "
            "(greedy decoding ignores truncation)")
    if top_k < 0 or not (0.0 <= top_p <= 1.0):
        raise ValueError(
            f"top_k must be >= 0 and top_p in [0, 1] (a probability, "
            f"not a percent): got top_k={top_k}, top_p={top_p}")

    cache = init_cache(cfg, b, max_len)
    logits, cache = forward_with_cache(params, cfg, prompt, cache)

    def pick(step_logits, key):
        if temperature > 0:
            # temperature FIRST, truncation second: the nucleus must
            # cover the distribution actually sampled from. The row is
            # canonicalized (replicated f32) BEFORE any decision op so
            # the whole pipeline — including categorical's RNG — runs
            # the single-device program whatever the params' sharding
            step_logits = replicated_logits(step_logits, mesh)
            return jax.random.categorical(
                key,
                _truncate_logits(step_logits / temperature, top_k, top_p),
                axis=-1)
        return jnp.argmax(step_logits, axis=-1)

    keys = (jax.random.split(rng, max_new_tokens) if rng is not None
            else jnp.zeros((max_new_tokens, 2), jnp.uint32))
    first = pick(logits[:, -1], keys[0])

    def step(carry, key):
        tok, cache = carry
        logits, cache = forward_with_cache(params, cfg, tok[:, None], cache)
        nxt = pick(logits[:, -1], key)
        return (nxt, cache), tok

    (last, _), toks = jax.lax.scan(step, (first, cache), keys[1:])
    # toks: [max_new_tokens-1, B] of the tokens *fed* at each step, i.e.
    # generated tokens 0..n-2; append the final one
    out = jnp.concatenate(
        [toks.swapaxes(0, 1), last[:, None]], axis=1)
    return jnp.concatenate([prompt, out], axis=1)
