"""Paged KV-cache block accounting — the host half of the paged
serving engine (ISSUE 6 tentpole), deliberately jax-free.

The device side is a single pooled KV arena in HBM
(``generate.init_paged_cache``: k/v ``[L, kv_blocks, Hkv,
kv_block_size, D]``) plus a per-slot block table threaded through the
attention path (``ops.attention.paged_gather_kv``). This module owns
everything the host decides about that arena:

- ``BlockAllocator``: a refcounted free list over the physical blocks.
  Block 0 is RESERVED as the null/scratch block: unassigned block-table
  entries point at it, so in-graph writes by inactive rows (and
  pipeline over-decode past a request's true length) land somewhere
  harmless instead of corrupting a neighbour's KV. It is never
  allocated and never freed.
- copy-on-write discipline: ``fork`` bumps refcounts (an n>1 sampling
  fork or a shared system prompt costs table entries, not HBM);
  ``writable`` says whether a block may be mutated in place (refcount
  1). A holder about to write a shared block allocates a fresh block,
  device-copies the contents, and drops its reference — the same COW
  discipline the PR 1 scheduler snapshot proved out, restated over KV.
- ``PrefixBlockIndex``: block-granular prefix reuse replacing the
  whole-prompt device-array prefix cache — full blocks of a published
  prompt are shared by refcount with every later request whose prompt
  starts with the same tokens, LRU-evicted under a block budget.

Being jax-free keeps it importable from the error-path modules and
lets the allocator property tests (tests/test_cache_properties.py)
fuzz thousands of alloc/free/fork/write sequences per second.
"""
from __future__ import annotations

from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Sequence, Tuple

__all__ = ["BlockAllocator", "PrefixBlockIndex", "ScaleLedger",
           "NoFreeBlocks", "NULL_BLOCK", "blocks_for"]

# physical block 0: the reserved null/scratch block every unassigned
# block-table entry points at (see module docstring)
NULL_BLOCK = 0


def blocks_for(tokens: int, block_size: int) -> int:
    """Blocks needed to hold ``tokens`` KV entries (ceil division)."""
    return -(-max(0, tokens) // block_size)


class NoFreeBlocks(RuntimeError):
    """The pool has no free block to hand out RIGHT NOW — a transient
    condition the caller resolves by flushing deferred frees, evicting
    prefix blocks, or preempting a slot (never by crashing)."""


class BlockAllocator:
    """Refcounted free-list allocator over ``num_blocks`` physical KV
    blocks of ``block_size`` tokens each. Block ``NULL_BLOCK`` is
    reserved and never enters the free list.

    Invariants (property-tested):
    - every referenced block has refcount >= 1, every free block 0;
    - free + referenced + reserved == num_blocks (no lost blocks);
    - decref below zero (double free) raises;
    - a block is ``writable`` iff exactly one holder references it.
    """

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError(
                f"kv_blocks must be >= 2 (one reserved null block plus "
                f"at least one usable), got {num_blocks}")
        if block_size < 1:
            raise ValueError(f"kv_block_size must be >= 1, got {block_size}")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self._refs: List[int] = [0] * num_blocks
        self._free: Deque[int] = deque(range(1, num_blocks))
        # blocks at refcount > 1, maintained incrementally: the gauge
        # mirror reads this per request under the serving-loop lock,
        # so it must not scan a production-sized pool
        self._shared = 0
        # int8-KV engines attach a ScaleLedger so per-block
        # quantization-scale bookkeeping drops in LOCKSTEP with block
        # frees — whoever decrefs the last reference (slot teardown,
        # prefix eviction, preemption), the scale entry dies with the
        # block, never from a parallel code path that could drift
        self.scale_ledger: Optional["ScaleLedger"] = None

    # -- core ----------------------------------------------------------
    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return (self.num_blocks - 1) - len(self._free)

    @property
    def capacity(self) -> int:
        """Usable blocks (the reserved null block excluded)."""
        return self.num_blocks - 1

    def ref(self, block: int) -> int:
        return self._refs[block]

    def alloc(self) -> int:
        """One fresh block at refcount 1, or NoFreeBlocks."""
        if not self._free:
            raise NoFreeBlocks(
                f"all {self.capacity} KV blocks referenced")
        b = self._free.popleft()
        assert self._refs[b] == 0
        self._refs[b] = 1
        return b

    def alloc_many(self, n: int) -> List[int]:
        """``n`` fresh blocks, all-or-nothing (a partial allocation
        would leak on the error path)."""
        if n > len(self._free):
            raise NoFreeBlocks(
                f"need {n} KV blocks, {len(self._free)} free "
                f"(of {self.capacity})")
        return [self.alloc() for _ in range(n)]

    def incref(self, block: int) -> None:
        if block == NULL_BLOCK:
            raise ValueError("the reserved null block cannot be referenced")
        if self._refs[block] < 1:
            raise ValueError(f"incref of unreferenced block {block}")
        self._refs[block] += 1
        if self._refs[block] == 2:
            self._shared += 1

    def decref(self, block: int) -> bool:
        """Drop one reference; True when this freed the block."""
        if block == NULL_BLOCK:
            raise ValueError("the reserved null block cannot be freed")
        if self._refs[block] < 1:
            raise ValueError(f"double free of block {block}")
        self._refs[block] -= 1
        if self._refs[block] == 1:
            self._shared -= 1
        elif self._refs[block] == 0:
            self._free.append(block)
            if self.scale_ledger is not None:
                self.scale_ledger.note_free(block)
            return True
        return False

    # -- COW -----------------------------------------------------------
    def fork(self, blocks: Sequence[int]) -> List[int]:
        """Share ``blocks`` with a second holder: refcount bump per
        block, no data movement — the returned table is the caller's
        own (COW: a holder must ``writable``-check before mutating)."""
        for b in blocks:
            self.incref(b)
        return list(blocks)

    def writable(self, block: int) -> bool:
        """True iff exactly one holder references ``block`` — the COW
        gate: a shared block must be copied before its first write."""
        return self._refs[block] == 1

    def shared_count(self) -> int:
        """Blocks currently referenced by more than one holder — the
        COW-sharing win the ``nos_tpu_serve_kv_blocks_cow_shared``
        gauge reports (each such block would otherwise be a copy).
        O(1): maintained in incref/decref."""
        return self._shared


class PrefixBlockIndex:
    """Block-granular prefix reuse: full KV blocks of published prompts,
    keyed by their token content, shared by refcount with any request
    whose prompt starts with the same tokens.

    An entry is a CHAIN: the ordered full blocks of one published
    prompt, stored as (token tuple, block ids). ``match`` returns the
    longest block-aligned common head over all chains — block j of a
    chain is only valid together with blocks 0..j-1 (its KV attends to
    them), so sharing is always a chain prefix, never a mid-chain
    block. The index holds one reference per block per chain
    (``allocator.fork`` on publish); eviction is LRU whole-chain under
    ``max_blocks``. Capacity pressure from live slots calls
    ``evict_lru`` before any slot is preempted — cached prefixes are
    the cheapest memory to reclaim.

    Chains are SCOPE-partitioned (ISSUE 13 satellite): ``match`` /
    ``publish`` take an opaque ``scope`` (the serving engine passes
    the request's tenant), and a chain only ever matches prompts in
    its own scope. Cross-tenant KV block sharing is a timing
    side-channel (an adversary probing whether another tenant's prompt
    is cached by watching its own TTFT) and an isolation hole once a
    shared block is COW-relied on — two tenants publishing identical
    prompts therefore get DISJOINT chains unless the operator opts
    into sharing (``TenantQuotaConfig.share_prefix``), which collapses
    every scope to the default ``None``."""

    def __init__(self, allocator: BlockAllocator, max_blocks: int):
        self.alloc = allocator
        self.max_blocks = max_blocks
        # insertion-ordered LRU: (scope, full token tuple) -> block ids
        self._chains: Dict[tuple, List[int]] = {}
        self.hits = 0
        self.tokens_saved = 0
        # eviction observability + the KV-fabric demotion hook (ISSUE
        # 17): every evicted chain counts under exactly one tier —
        # "demote" when ``on_evict`` (the engine's host-tier capture,
        # called with the chain's key and block ids BEFORE the
        # refcounts drop, so the arena bytes are still live to read)
        # accepted it, "drop" otherwise (no hook, hook refused, or
        # hook failed). evict_lru dropped chains silently before this.
        self.evicted = {"drop": 0, "demote": 0}
        self.on_evict: Optional[
            Callable[[tuple, Tuple[int, ...]], bool]] = None

    @property
    def block_count(self) -> int:
        return sum(len(c) for c in self._chains.values())

    def match(self, prompt: Sequence[int], cap: int,
              scope: Optional[str] = None
              ) -> Tuple[int, Optional[tuple]]:
        """(m, chain_key) for the longest block-aligned common head
        between ``prompt`` and any chain IN ``scope``, with m <= cap
        (the caller passes plen-1: at least one suffix token must run
        to produce logits). (0, None) when nothing matches. Pure
        lookup — the caller decides whether the match is used before
        ``take`` moves refcounts and LRU order. Linear scan over
        chains: the index is operator-capped small (system prompts,
        not pages)."""
        bs = self.alloc.block_size
        best, best_key = 0, None
        for key in self._chains:
            if key[0] != scope:
                continue        # another tenant's chain: invisible
            m = 0
            for a, b in zip(key[1], prompt):
                if a != b:
                    break
                m += 1
            m = (min(m, cap) // bs) * bs
            if m > best:
                best, best_key = m, key
        return best, best_key

    def take(self, key: tuple, m: int) -> List[int]:
        """Claim the first ``m`` tokens' blocks of chain ``key`` for a
        new holder: refcount bump per block (COW share), LRU refresh.
        Returns the shared block ids in logical order."""
        bs = self.alloc.block_size
        assert m % bs == 0
        chain = self._chains.pop(key)       # pop-then-set: LRU refresh
        self._chains[key] = chain
        shared = self.alloc.fork(chain[:m // bs])
        self.hits += 1
        self.tokens_saved += m
        return shared

    def publish(self, prompt: Sequence[int], blocks: Sequence[int],
                scope: Optional[str] = None) -> None:
        """Register ``prompt``'s full blocks as a reusable chain in
        ``scope`` (the holder keeps its own references; the index
        takes one more per block), then LRU-evict past the block
        budget."""
        bs = self.alloc.block_size
        full = len(prompt) // bs
        if full == 0 or self.max_blocks <= 0:
            return
        key = (scope, tuple(prompt[:full * bs]))
        if key in self._chains:
            self._chains[key] = self._chains.pop(key)   # LRU refresh
            return
        self._chains[key] = self.alloc.fork(list(blocks[:full]))
        while self.block_count > self.max_blocks and len(self._chains) > 1:
            self._evict_one()
        # a single over-budget chain stays: evicting the chain we just
        # published would make cache_prefix a silent no-op

    def _evict_one(self) -> int:
        key = next(iter(self._chains))
        chain = self._chains.pop(key)
        tier = "drop"
        if self.on_evict is not None:
            # a failed demotion must degrade to the pre-fabric drop,
            # never abort pressure relief mid-flight
            try:
                if self.on_evict(key, tuple(chain)):
                    tier = "demote"
            except Exception:
                tier = "drop"
        self.evicted[tier] += 1
        freed = 0
        for b in chain:
            if self.alloc.decref(b):
                freed += 1
        return freed

    def evict_lru(self, need_blocks: int) -> int:
        """Free chains (oldest first) until >= ``need_blocks`` blocks
        were actually returned to the pool (a still-shared block frees
        nothing) or the index is empty. Returns blocks freed."""
        freed = 0
        while self._chains and freed < need_blocks:
            freed += self._evict_one()
        return freed

    def clear(self) -> None:
        while self._chains:
            self._evict_one()

    def chain_items(self) -> List[Tuple[tuple, List[int]]]:
        """(key, block ids) snapshot in LRU order, oldest first — the
        KV-fabric export/snapshot surface (read-only by contract)."""
        return list(self._chains.items())

    def stats(self) -> dict:
        return {"chains": len(self._chains),
                "blocks": self.block_count,
                "capacity_blocks": self.max_blocks,
                "hits": self.hits,
                "tokens_saved": self.tokens_saved,
                "evicted": dict(self.evicted)}


class ScaleLedger:
    """Host mirror of the int8 arena's per-block quantization scales:
    which PHYSICAL blocks currently carry valid scale entries, and a
    monotone data version per block so the property tests can prove
    the lockstep lifecycle the device arrays rely on:

    - a WRITE into a block stamps (or re-stamps) its scale version —
      the device-side quantize-on-scatter writes data and scale in one
      program, so host bookkeeping treats them as one event;
    - a COW copy duplicates the source's version onto the fresh block
      (``_cow_block`` device-copies data AND scale planes together);
    - a FORK shares the block id itself, so the scale entry is shared
      by construction — nothing to track;
    - the block's FREE drops the entry, driven by the allocator's
      decref (``BlockAllocator.scale_ledger``), so a reused block can
      never present a stale scale as fresh data's.

    Pure host accounting (jax-free): the engine keeps it for /stats
    (``scaled_blocks``) and the invariants live in
    tests/test_cache_properties.py's fuzz."""

    def __init__(self) -> None:
        self._ver: Dict[int, int] = {}      # physical block -> version
        self._next = 0

    def note_write(self, block: int) -> None:
        """Data (and therefore scales) written into ``block``."""
        self._ver[block] = self._next
        self._next += 1

    def note_copy(self, src: int, dst: int) -> None:
        """COW: ``dst`` now holds a byte-copy of ``src``'s data and
        scale planes — same version, distinct block."""
        if src in self._ver:
            self._ver[dst] = self._ver[src]

    def note_free(self, block: int) -> None:
        self._ver.pop(block, None)

    def version(self, block: int) -> Optional[int]:
        return self._ver.get(block)

    @property
    def count(self) -> int:
        """Blocks currently carrying valid scales (the /stats
        ``scaled_blocks`` figure)."""
        return len(self._ver)
