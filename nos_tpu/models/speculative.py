"""Speculative decoding: a small draft model proposes, the target model
verifies k tokens per forward pass.

Decode is HBM-bandwidth-bound — each target step re-reads every weight to
produce ONE token. Verification flips the economics: the target runs one
forward over k drafted tokens (same weight traffic as one decode step,
k x the MXU work, which was idle anyway) and accepts the longest
matching prefix, so accepted tokens cost ~1/k of a target pass each
while the first rejected position still yields the target's own token —
output is EXACTLY what plain greedy decoding of the target would
produce, just cheaper when the draft is any good.

Greedy-only by design: greedy acceptance (`draft token == target
argmax`) keeps the equivalence bit-exact and testable; the
rejection-sampling generalization for temperature > 0 is out of scope.

Batched rounds advance UNIFORMLY by the minimum acceptance across rows
(plus the verified correction token): rows that matched further simply
re-propose those tokens next round and get the identical result — the
single scalar ``cache['pos']`` then stays valid for every row. Rolling
back speculation is just resetting ``pos``: entries beyond it are masked
out of attention and overwritten by later writes
(models/generate._cached_attention).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from nos_tpu.models.generate import forward_with_cache, init_cache
from nos_tpu.models.transformer import Params, TransformerConfig

__all__ = ["speculative_generate"]


@functools.lru_cache(maxsize=None)
def _jitted_step(cfg: TransformerConfig):
    """One compiled forward per (config, shape) across ALL calls —
    speculative_generate is the serving hot path and must not re-trace
    per request (TransformerConfig is a frozen dataclass, so it keys the
    cache)."""
    return jax.jit(
        lambda p, t, c: forward_with_cache(p, cfg, t, c))


def speculative_generate(
    params: Params,
    cfg: TransformerConfig,
    draft_params: Params,
    draft_cfg: TransformerConfig,
    prompt: jax.Array,
    max_new_tokens: int,
    *,
    n_draft: int = 4,
    max_len: Optional[int] = None,
) -> jax.Array:
    """Greedy speculative decoding. prompt [B, S] ->
    [B, S + max_new_tokens], bit-identical to
    ``generate(params, cfg, prompt, max_new_tokens)``."""
    b, s = prompt.shape
    if max_new_tokens <= 0:
        return prompt
    max_len = max_len or min(cfg.max_seq, draft_cfg.max_seq)
    # headroom: a round may write up to k speculative positions past the
    # accepted prefix before rolling back
    k = max(1, min(n_draft, max_new_tokens))
    if s + max_new_tokens + k > max_len:
        raise ValueError(
            f"prompt ({s}) + max_new_tokens ({max_new_tokens}) + draft "
            f"window ({k}) exceeds cache length {max_len}")

    t_step = _jitted_step(cfg)
    d_step = _jitted_step(draft_cfg)

    # invariant between rounds: both caches have processed sequence[:-1],
    # `last` [B, 1] is the newest token, not yet fed
    t_cache = init_cache(cfg, b, max_len)
    d_cache = init_cache(draft_cfg, b, max_len)
    if s > 1:
        _, t_cache = t_step(params, prompt[:, :-1], t_cache)
        _, d_cache = d_step(draft_params, prompt[:, :-1], d_cache)
    last = prompt[:, -1:]

    pieces = []
    produced = 0
    while produced < max_new_tokens:
        base = int(t_cache["pos"])

        # 1. draft proposes k tokens autoregressively from `last`
        drafts = []
        tok = last
        for _ in range(k):
            logits, d_cache = d_step(draft_params, tok, d_cache)
            tok = jnp.argmax(logits[:, -1], axis=-1)[:, None]
            drafts.append(tok)
        proposed = jnp.concatenate(drafts, axis=1)          # [B, k]

        # 2. target verifies in ONE pass: greedy[:, i] is the target's
        # token after feed[:, i], i.e. its verdict on proposed[:, i]
        feed = jnp.concatenate([last, proposed[:, :-1]], axis=1)
        logits, t_cache = t_step(params, feed, t_cache)
        greedy = jnp.argmax(logits, axis=-1)                # [B, k]

        # 3. uniform advance: min over rows of the longest matching
        # prefix, plus the verified token at that position (for rows that
        # matched further, proposed == greedy there, so the "correction"
        # is their accepted token — every emitted token is target-greedy)
        match = proposed == greedy
        accepted = jnp.argmin(
            jnp.concatenate([match, jnp.zeros((b, 1), bool)], axis=1),
            axis=1)
        min_a = int(jnp.min(accepted))
        if min_a == k:                                      # full accept
            new = proposed
            last = proposed[:, -1:]
            # caches processed exactly feed = seq[:-1]: invariant holds
        else:
            new = jnp.concatenate(
                [proposed[:, :min_a], greedy[:, min_a:min_a + 1]], axis=1)
            last = greedy[:, min_a:min_a + 1]
            # roll speculation back to the accepted prefix: positions
            # base..base+min_a hold [last, d1..d_min_a] — all part of the
            # new sequence[:-1] — so processed count is base + min_a + 1
            t_cache = {**t_cache, "pos": jnp.int32(base + min_a + 1)}
            d_cache = {**d_cache, "pos": jnp.int32(base + min_a + 1)}
        pieces.append(new)
        produced += new.shape[1]

    tail = jnp.concatenate(pieces, axis=1)[:, :max_new_tokens]
    return jnp.concatenate([prompt, tail], axis=1)
