"""Speculative decoding: a small draft model proposes, the target model
verifies k tokens per forward pass.

Decode is HBM-bandwidth-bound — each target step re-reads every weight to
produce ONE token. Verification flips the economics: the target runs one
forward over k drafted tokens (same weight traffic as one decode step,
k x the MXU work, which was idle anyway) and accepts the longest
matching prefix, so accepted tokens cost ~1/k of a target pass each
while the first rejected position still yields the target's own token —
output is EXACTLY what plain greedy decoding of the target would
produce, just cheaper when the draft is any good.

Two acceptance regimes, both EXACT w.r.t. the target model:

- **Greedy** (temperature 0): accept while ``draft token == target
  argmax`` — output is bit-identical to plain greedy decoding.
- **Speculative sampling** (temperature > 0): the standard
  accept-reject rule — accept draft token x_i with probability
  ``min(1, p_i(x_i) / q_i(x_i))`` (p = target, q = draft, both
  tempered and top-k/top-p-truncated the same way ``generate`` does);
  on the first rejection, emit a sample from the normalized residual
  ``max(p_i - q_i, 0)``. Each committed token is distributed exactly
  as target-only sampling (property-tested against the analytically
  computed target distribution).

Batched rounds advance UNIFORMLY by the minimum acceptance across rows
(plus the verified correction token): rows that matched further simply
re-propose those tokens next round and get the identical result — the
single scalar ``cache['pos']`` then stays valid for every row. Rolling
back speculation is just resetting ``pos``: entries beyond it are masked
out of attention and overwritten by later writes
(models/generate._cached_attention).

This module is the library/batch API (one call, lockstep rows). The
SERVING twin — per-row independent advance, pipelined draft/verify
dispatches, fused rounds, paged/int8 KV — is
``models/spec_serving.SpeculativeDecodeServer``; it restates the same
accept-reject math per slot (``_row_dist`` there mirrors ``_dist``
here), so the two stay the exactness oracle for each other.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from nos_tpu.models.generate import (
    _truncate_logits, forward_with_cache, init_cache,
)
from nos_tpu.models.transformer import Params, TransformerConfig

__all__ = ["speculative_generate"]


def _dist(logits: jax.Array, temperature: float, top_k: int,
          top_p: float) -> jax.Array:
    """Tempered + truncated sampling distribution [..., vocab] — the
    distribution ``generate`` actually samples from, applied identically
    to draft and target so the accept-reject identity holds."""
    return jax.nn.softmax(
        _truncate_logits(logits / temperature, top_k, top_p), axis=-1)


def _sample_rows(key: jax.Array, probs: jax.Array) -> jax.Array:
    """Categorical over explicit probabilities [B, vocab] -> [B]."""
    logp = jnp.where(probs > 0, jnp.log(jnp.maximum(probs, 1e-38)),
                     -jnp.inf)
    return jax.random.categorical(key, logp, axis=-1)


@functools.lru_cache(maxsize=None)
def _jitted_step(cfg: TransformerConfig):
    """One compiled forward per (config, shape) across ALL calls —
    speculative_generate is the serving hot path and must not re-trace
    per request (TransformerConfig is a frozen dataclass, so it keys the
    cache)."""
    return jax.jit(
        lambda p, t, c: forward_with_cache(p, cfg, t, c))


def speculative_generate(
    params: Params,
    cfg: TransformerConfig,
    draft_params: Params,
    draft_cfg: TransformerConfig,
    prompt: jax.Array,
    max_new_tokens: int,
    *,
    n_draft: int = 4,
    max_len: Optional[int] = None,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 0.0,
    rng: Optional[jax.Array] = None,
) -> jax.Array:
    """Speculative decoding. prompt [B, S] -> [B, S + max_new_tokens].
    Temperature 0 (default): bit-identical to
    ``generate(params, cfg, prompt, max_new_tokens)``. Temperature > 0:
    accept-reject speculative sampling — every emitted token is
    distributed exactly as ``generate(..., temperature, top_k, top_p)``
    samples it (see module docstring)."""
    b, s = prompt.shape
    if max_new_tokens <= 0:
        return prompt
    if temperature > 0 and rng is None:
        raise ValueError("temperature sampling needs an rng key")
    if temperature <= 0 and (top_k or top_p):
        raise ValueError(
            "top_k/top_p only apply when sampling — set temperature > 0 "
            "(greedy decoding ignores truncation)")
    if top_k < 0 or not (0.0 <= top_p <= 1.0):
        raise ValueError(
            f"top_k must be >= 0 and top_p in [0, 1]: got "
            f"top_k={top_k}, top_p={top_p}")
    sampling = temperature > 0
    max_len = max_len or min(cfg.max_seq, draft_cfg.max_seq)
    # headroom: a round may write up to k speculative positions past the
    # accepted prefix before rolling back
    k = max(1, min(n_draft, max_new_tokens))
    if s + max_new_tokens + k > max_len:
        raise ValueError(
            f"prompt ({s}) + max_new_tokens ({max_new_tokens}) + draft "
            f"window ({k}) exceeds cache length {max_len}")

    t_step = _jitted_step(cfg)
    d_step = _jitted_step(draft_cfg)

    # invariant between rounds: both caches have processed sequence[:-1],
    # `last` [B, 1] is the newest token, not yet fed
    t_cache = init_cache(cfg, b, max_len)
    d_cache = init_cache(draft_cfg, b, max_len)
    if s > 1:
        _, t_cache = t_step(params, prompt[:, :-1], t_cache)
        _, d_cache = d_step(draft_params, prompt[:, :-1], d_cache)
    last = prompt[:, -1:]

    pieces = []
    produced = 0
    while produced < max_new_tokens:
        base = int(t_cache["pos"])
        if sampling:
            rng, kd, kacc, kres = jax.random.split(rng, 4)
            dkeys = jax.random.split(kd, k)

        # 1. draft proposes k tokens autoregressively from `last`
        # (argmax when greedy; a draw from q_i = tempered+truncated
        # draft distribution when sampling, with q_i recorded)
        drafts, qs = [], []
        tok = last
        for i in range(k):
            logits, d_cache = d_step(draft_params, tok, d_cache)
            if sampling:
                q = _dist(logits[:, -1], temperature, top_k, top_p)
                tok = _sample_rows(dkeys[i], q)[:, None]
                qs.append(q)
            else:
                tok = jnp.argmax(logits[:, -1], axis=-1)[:, None]
            drafts.append(tok)
        proposed = jnp.concatenate(drafts, axis=1)          # [B, k]

        # 2. target verifies in ONE pass: position i of the output is
        # the target's distribution after feed[:, i], i.e. its verdict
        # on proposed[:, i]
        feed = jnp.concatenate([last, proposed[:, :-1]], axis=1)
        logits, t_cache = t_step(params, feed, t_cache)

        # 3. per-row, per-position acceptance:
        #    greedy:   accept while proposed == target argmax
        #    sampling: accept x_i w.p. min(1, p_i(x_i)/q_i(x_i))
        if sampling:
            p = _dist(logits, temperature, top_k, top_p)    # [B, k, V]
            q = jnp.stack(qs, axis=1)                       # [B, k, V]
            px = jnp.take_along_axis(p, proposed[..., None], -1)[..., 0]
            qx = jnp.take_along_axis(q, proposed[..., None], -1)[..., 0]
            u = jax.random.uniform(kacc, (b, k))
            accept = u * qx < px        # u < px/qx, div-free
        else:
            greedy = jnp.argmax(logits, axis=-1)            # [B, k]
            accept = proposed == greedy

        # 4. uniform advance: min over rows of the longest accepted
        # prefix, plus a correction token at that position — the
        # target's own token (greedy) or a residual draw (sampling);
        # rows that accepted further commit their accepted token there
        # and simply re-propose the discarded tail next round
        accepted = jnp.argmin(
            jnp.concatenate([accept, jnp.zeros((b, 1), bool)], axis=1),
            axis=1)
        min_a = int(jnp.min(accepted))
        if min_a == k:                                      # full accept
            new = proposed
            last = proposed[:, -1:]
            # caches processed exactly feed = seq[:-1]: invariant holds
        else:
            if sampling:
                # first rejection → sample the normalized residual
                # max(p - q, 0); if p ≡ q (residual empty — can only be
                # approached numerically, rejection prob → 0) fall back
                # to p itself
                resid = jnp.maximum(p[:, min_a] - q[:, min_a], 0.0)
                norm = jnp.sum(resid, axis=-1, keepdims=True)
                resid = jnp.where(norm > 0, resid / norm, p[:, min_a])
                corr = jnp.where(accept[:, min_a], proposed[:, min_a],
                                 _sample_rows(kres, resid))[:, None]
            else:
                corr = greedy[:, min_a:min_a + 1]
            new = jnp.concatenate([proposed[:, :min_a], corr], axis=1)
            last = corr
            # roll speculation back to the accepted prefix: positions
            # base..base+min_a hold [last, d1..d_min_a] — all part of the
            # new sequence[:-1] — so processed count is base + min_a + 1
            t_cache = {**t_cache, "pos": jnp.int32(base + min_a + 1)}
            d_cache = {**d_cache, "pos": jnp.int32(base + min_a + 1)}
        pieces.append(new)
        produced += new.shape[1]

    tail = jnp.concatenate(pieces, axis=1)[:, :max_new_tokens]
    return jnp.concatenate([prompt, tail], axis=1)
