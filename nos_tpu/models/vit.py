"""Vision Transformer (ViT-small) — the benchmark workload.

The reference's only published benchmark runs YOLOS-small (a ViT-small
detection variant, ~22M backbone params) under N pods sharing one GPU
(demos/gpu-sharing-comparison/README.md; BASELINE.md). This is the same
backbone scale as a TPU-first inference program: patchify as reshape +
one projection matmul, encoder blocks of flash attention + GELU MLP, all
bf16, static shapes.

ViT-small/16: d=384, 12 layers, 6 heads, mlp 1536, patch 16, 224x224 input
-> 196 tokens + cls.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict

import jax
import jax.numpy as jnp

from nos_tpu.ops.attention import attention
from nos_tpu.ops.layers import gelu_mlp, layer_norm, patchify

Params = Dict[str, Any]


@dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch: int = 16
    d_model: int = 384
    n_layers: int = 12
    n_heads: int = 6
    d_ff: int = 1536
    n_classes: int = 1000
    dtype: Any = jnp.bfloat16

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch) ** 2

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def dense_init(key, shape, fan_in, dtype):
    return (jax.random.normal(key, shape, jnp.float32) * fan_in ** -0.5
            ).astype(dtype)


def init_encoder(rng: jax.Array, cfg: ViTConfig) -> Params:
    """Stacked encoder blocks + final layer norm — the backbone shared by
    the classifier head here and the YOLOS detection head (yolos.py)."""
    keys = jax.random.split(rng, cfg.n_layers)

    def block(key):
        ks = jax.random.split(key, 4)
        d, f = cfg.d_model, cfg.d_ff
        return {
            "ln1_scale": jnp.ones((d,), jnp.float32),
            "ln1_bias": jnp.zeros((d,), jnp.float32),
            "wqkv": dense_init(ks[0], (d, 3 * d), d, cfg.dtype),
            "wo": dense_init(ks[1], (d, d), d, cfg.dtype),
            "ln2_scale": jnp.ones((d,), jnp.float32),
            "ln2_bias": jnp.zeros((d,), jnp.float32),
            "w_in": dense_init(ks[2], (d, f), d, cfg.dtype),
            "b_in": jnp.zeros((f,), cfg.dtype),
            "w_out": dense_init(ks[3], (f, d), f, cfg.dtype),
            "b_out": jnp.zeros((d,), cfg.dtype),
        }

    blocks = jax.tree.map(
        lambda *xs: jnp.stack(xs), *[block(k) for k in keys]
    )
    return {
        "blocks": blocks,
        "final_ln_scale": jnp.ones((cfg.d_model,), jnp.float32),
        "final_ln_bias": jnp.zeros((cfg.d_model,), jnp.float32),
    }


def encode(params: Params, cfg: ViTConfig, x: jax.Array) -> jax.Array:
    """Run the encoder over embedded tokens x [B, S, D] -> [B, S, D]
    (final layer norm applied). ``params`` needs the init_encoder keys."""
    b, seq = x.shape[0], x.shape[1]

    def block_body(x, blk):
        h = layer_norm(x, blk["ln1_scale"], blk["ln1_bias"])
        qkv = jnp.dot(h, blk["wqkv"]).reshape(b, seq, 3, cfg.n_heads, cfg.head_dim)
        q, k, v = (qkv[:, :, i].transpose(0, 2, 1, 3) for i in range(3))
        o = attention(q, k, v, causal=False)
        o = o.transpose(0, 2, 1, 3).reshape(b, seq, cfg.d_model)
        x = x + jnp.dot(o, blk["wo"])
        h = layer_norm(x, blk["ln2_scale"], blk["ln2_bias"])
        x = x + gelu_mlp(h, blk["w_in"], blk["b_in"], blk["w_out"], blk["b_out"])
        return x, None

    x, _ = jax.lax.scan(block_body, x, params["blocks"])
    return layer_norm(x, params["final_ln_scale"], params["final_ln_bias"])


def init_params(rng: jax.Array, cfg: ViTConfig) -> Params:
    keys = jax.random.split(rng, 4)
    patch_dim = cfg.patch * cfg.patch * 3
    return {
        "patch_proj": dense_init(keys[0], (patch_dim, cfg.d_model), patch_dim,
                                 cfg.dtype),
        "cls_token": jnp.zeros((1, 1, cfg.d_model), cfg.dtype),
        "pos_embed": (jax.random.normal(keys[1], (1, cfg.n_patches + 1, cfg.d_model),
                                        jnp.float32) * 0.02).astype(cfg.dtype),
        **init_encoder(keys[3], cfg),
        "head": dense_init(keys[2], (cfg.d_model, cfg.n_classes), cfg.d_model,
                           cfg.dtype),
    }


def forward(params: Params, cfg: ViTConfig, images: jax.Array) -> jax.Array:
    """images [B, H, W, 3] -> logits [B, n_classes]."""
    b = images.shape[0]
    x = patchify(images.astype(cfg.dtype), cfg.patch)
    x = jnp.dot(x, params["patch_proj"])
    cls = jnp.broadcast_to(params["cls_token"], (b, 1, cfg.d_model))
    x = jnp.concatenate([cls, x], axis=1) + params["pos_embed"]
    x = encode(params, cfg, x)
    return jnp.dot(x[:, 0], params["head"]).astype(jnp.float32)


def param_count(params: Params) -> int:
    return sum(p.size for p in jax.tree.leaves(params))
