"""Param-tree quantization for the decode path.

``quantize_params`` knows the transformer parameter layout
(models/transformer.init_params) and converts the dense matmul weights
to int8 ``QuantLinear``s (ops/quant.py). Norms stay fp32, the MoE expert
stacks stay bf16 (the MoE einsum path doesn't route through ``qdot``),
and training/prefill-quality paths are untouched — this feeds
``models.generate`` only (the classic weight-only inference split).
"""
from __future__ import annotations

from typing import Any

from nos_tpu.ops.quant import quantize_array

__all__ = ["quantize_params"]

_DENSE_FFN_KEYS = ("w_gate", "w_up", "w_down")
_ATTN_KEYS = ("wq", "wk", "wv", "wo")


def quantize_params(params: Any, *, quantize_embed: bool = True) -> Any:
    """Return a params pytree where the decoder's matmul weights are
    QuantLinear (int8 + per-channel scales). Plugs directly into
    ``generate.forward_with_cache``."""
    out = dict(params)
    layers = dict(params["layers"])
    for k in _ATTN_KEYS:
        layers[k] = quantize_array(layers[k])
    if "w_router" not in layers:        # dense FFN only; experts stay bf16
        for k in _DENSE_FFN_KEYS:
            layers[k] = quantize_array(layers[k])
    out["layers"] = layers
    out["unembed"] = quantize_array(params["unembed"])
    if quantize_embed:
        # per-ROW scales: a rare token's small row must not quantize
        # against the whole column's max (embed is a gather, not a matmul)
        out["embed"] = quantize_array(params["embed"], axis=-1)
    return out
