"""Param-tree quantization for the decode path.

``quantize_params`` knows the transformer parameter layout
(models/transformer.init_params) and converts the dense matmul weights
to int8 ``QuantLinear``s (ops/quant.py). Norms stay fp32, the MoE expert
stacks stay bf16 (the MoE einsum path doesn't route through ``qdot``),
and training/prefill-quality paths are untouched — this feeds
``models.generate`` only (the classic weight-only inference split).
"""
from __future__ import annotations

from typing import Any

from nos_tpu.ops.quant import QuantLinear, quantize_array

__all__ = ["quantize_params", "quant_param_shardings"]

_DENSE_FFN_KEYS = ("w_gate", "w_up", "w_down")
_ATTN_KEYS = ("wq", "wk", "wv", "wo")


def quantize_params(params: Any, *, quantize_embed: bool = True) -> Any:
    """Return a params pytree where the decoder's matmul weights are
    QuantLinear (int8 + per-channel scales). Plugs directly into
    ``generate.forward_with_cache``."""
    out = dict(params)
    layers = dict(params["layers"])
    for k in _ATTN_KEYS:
        layers[k] = quantize_array(layers[k])
    if "w_router" not in layers:        # dense FFN only; experts stay bf16
        for k in _DENSE_FFN_KEYS:
            layers[k] = quantize_array(layers[k])
    out["layers"] = layers
    out["unembed"] = quantize_array(params["unembed"])
    if quantize_embed:
        # per-ROW scales: a rare token's small row must not quantize
        # against the whole column's max (embed is a gather, not a matmul)
        out["embed"] = quantize_array(params["embed"], axis=-1)
    return out


def quant_param_shardings(mesh, cfg, *, quantize_embed: bool = True):
    """Shardings for a ``quantize_params`` tree under tensor parallelism
    (the int8 twin of transformer.param_shardings). Derived, not
    restated: each QuantLinear's ``q`` keeps the dense weight's layout
    and ``scale`` is that layout with the quantized axis dropped — so
    the structure below mirrors ``quantize_params`` key-for-key and the
    Megatron layout itself has exactly one source of truth
    (transformer.param_shardings)."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from nos_tpu.models.transformer import param_shardings

    def ql_from(dense_sh, axis):
        spec = list(dense_sh.spec)
        while len(spec) < -axis:        # implied trailing replication
            spec.append(None)
        del spec[axis]
        return QuantLinear(q=dense_sh, scale=NamedSharding(mesh, P(*spec)))

    out = dict(param_shardings(mesh, cfg))
    layers = dict(out["layers"])
    for k in _ATTN_KEYS:
        layers[k] = ql_from(layers[k], -2)
    if "w_router" not in layers:        # dense FFN only; experts stay bf16
        for k in _DENSE_FFN_KEYS:
            layers[k] = ql_from(layers[k], -2)
    out["layers"] = layers
    out["unembed"] = ql_from(out["unembed"], -2)
    if quantize_embed:
        out["embed"] = ql_from(out["embed"], -1)
    return out
