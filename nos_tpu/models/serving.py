"""Continuous-batching decode serving (slot-based, static shapes).

The TPU-native serving pattern: ONE compiled decode program over a fixed
[max_batch, 1] token window runs every step; requests occupy rows
("slots") of a shared KV cache whose ``pos`` is a per-row vector
(models/generate.init_cache(per_row_pos=True)), so a long request and a
freshly-admitted short one decode in the same batch at different depths.
A finished slot is recycled by simply resetting its pos — no
reallocation, no shape change, no retrace. Prefill runs per request over
a scratch cache sized to the power-of-two prompt bucket (a handful of
compiled shapes, attention cost proportional to the request, not to
max_len) and is installed into the shared cache by a donated jitted
update, so admission never copies the multi-GB cache on the host.

Hot-loop economics: the decode step donates the cache (updates in place,
no second full-cache allocation per token), corrects inactive rows' pos
in-graph, and the host syncs ONE small array per tick.

This is deliberately an in-process engine, not an RPC server: the
operator stack schedules pods; what runs inside a serving pod is this
loop. Every slot carries its own sampling params (temperature / top-k /
top-p / seed) as per-row vectors through the ONE compiled decode
program; a request's sample stream is keyed by (seed, absolute
position), so what a request generates is INDEPENDENT of batch
composition — sampled alone or wedged between seven neighbours, same
seed gives the same tokens (tested). Greedy rows (temperature 0) stay
bit-identical to ``generate``.
"""
from __future__ import annotations

import functools
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from nos_tpu.models.generate import (
    Cache, _truncate_logits_rows, cache_shardings, forward_with_cache,
    init_cache,
)
from nos_tpu.models.transformer import Params, TransformerConfig


from nos_tpu.models.errors import QueueFull  # noqa: F401 — canonical home
                                             # is jax-free (see errors.py)

__all__ = ["DecodeServer", "QueueFull"]


def _bucket(n: int) -> int:
    b = 8
    while b < n:
        b *= 2
    return b


class _Ledger:
    """Per-request lifecycle stamps on the host monotonic clock
    (time.perf_counter): submitted -> admitted-to-slot -> prefill
    start/end -> first token observed -> per-arrival token batches ->
    done. The serving loop turns a finished ledger into the TTFT / TPOT
    / queue / e2e histograms, so the stamps measure what the USER
    experiences — a completion observed ``pipeline_depth`` ticks late
    is stamped at observation, because that is when its tokens become
    visible to the client.

    TPOT bookkeeping is lazy: one clock read per consumed arrival (not
    per token), stored as ``(gap_s, n_tokens)`` pairs — an arrival that
    lands ``n`` tokens at once attributes ``gap/n`` to each. Because
    ``t_last`` only ever advances and tokens are attributed exactly at
    the arrival that appended them, a pipeline rollback (over-decoded
    ticks whose tokens are never appended) can produce neither negative
    nor duplicate samples by construction."""

    __slots__ = ("t_submit", "t_admit", "t_prefill_start", "t_prefill_end",
                 "t_first", "t_last", "t_done", "outcome", "tpot")

    def __init__(self, now: float):
        self.t_submit = now
        self.t_admit = 0.0
        self.t_prefill_start = 0.0
        self.t_prefill_end = 0.0
        self.t_first = 0.0          # first token observed on the host
        self.t_last = 0.0           # most recent token observation
        self.t_done = 0.0
        self.outcome: Optional[str] = None
        self.tpot: List[Tuple[float, int]] = []     # (gap_s, tokens)

    def note_tokens(self, n: int, now: float) -> None:
        """Attribute ``n`` tokens observed at host instant ``now``. The
        first token (prefill) only arms ``t_last`` — TPOT is the
        inter-token series with the first token excluded."""
        last = self.t_last
        if last:
            self.tpot.append((max(0.0, now - last), n))
        self.t_last = now

    def snapshot(self, req: "_Request") -> dict:
        """The finished-request record the serving loop and benches
        read. ``ttft_s`` is None for a request that never produced a
        token (cancelled while pending)."""
        admitted = self.t_admit > 0.0
        return {
            "rid": req.rid,
            "outcome": self.outcome or "finished",
            "prompt_tokens": len(req.prompt),
            "output_tokens": min(len(req.out), req.max_new_tokens),
            "queue_s": (self.t_admit if admitted else self.t_done)
            - self.t_submit,
            "prefill_s": (self.t_prefill_end - self.t_prefill_start
                          if self.t_prefill_end else None),
            "ttft_s": (self.t_first - self.t_submit
                       if self.t_first else None),
            "e2e_s": self.t_done - self.t_submit,
            "tpot": list(self.tpot),
        }


@dataclass
class _Request:
    rid: int
    prompt: List[int]
    max_new_tokens: int
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 0.0
    seed: int = 0
    out: List[int] = field(default_factory=list)
    slot: int = -1
    cache_prefix: bool = False
    stop_tokens: tuple = ()
    led: Optional[_Ledger] = None

    def note_token(self) -> None:
        """Called after each appended token: a stop token terminates the
        request (the stop token IS included in the output — the HF EOS
        convention) by truncating max_new_tokens to what was produced."""
        if self.stop_tokens and self.out[-1] in self.stop_tokens:
            self.max_new_tokens = len(self.out)

    @property
    def done(self) -> bool:
        return len(self.out) >= self.max_new_tokens


class _InFlight:
    """One dispatched-but-unconsumed decode tick: device handles for its
    outputs, the slots it decoded for, and (once ``step_wait`` ran) the
    fetched host arrays. Arrivals are consumed strictly in dispatch
    order; ``consumed`` makes consumption idempotent so a pipeline flush
    racing a ``step_finish`` holder processes each tick exactly once."""

    __slots__ = ("payload", "slots", "host", "consumed")

    def __init__(self, payload: Tuple[jax.Array, ...], slots: Tuple[int, ...]):
        self.payload = payload
        self.slots = slots
        self.host: Optional[tuple] = None
        self.consumed = False


class DecodeServer:
    """Continuous-batching engine over ``max_batch`` cache slots.

    ``submit`` enqueues a request (admitted to a free slot immediately or
    when one frees); ``step`` decodes one token for every active slot;
    ``drain`` runs to completion and returns {request_id: full token
    list} for the requests completed since the last drain (and clears
    them — a long-lived serving pod must not accumulate results).
    Greedy requests (temperature 0, the default) are bit-identical to
    ``generate(params, cfg, prompt, max_new_tokens)``; sampled requests
    carry per-slot temperature/top-k/top-p/seed through the shared
    decode program, with a (seed, position)-keyed stream that is
    invariant to batch composition.

    Dispatch economics knobs (both preserve the exactness contracts
    above for every setting — tested):

    - ``pipeline_depth=k``: up to k decode ticks in flight before the
      host blocks on a token fetch; completions observed late roll back
      by pos-reset, batch-composition changes barrier-flush the window.
    - ``decode_steps=T``: T decode steps fused into one compiled
      dispatch ([B, T] tokens per device->host sync), amortizing
      per-dispatch overhead in decode-bound phases. Streaming
      granularity coarsens to ~k*T tokens per arrival.
    """

    def __init__(self, params: Params, cfg: TransformerConfig,
                 max_batch: int = 8, max_len: Optional[int] = None,
                 prefix_cache_size: int = 0, mesh=None,
                 prefill_chunk: int = 0, max_pending: int = 0,
                 pipeline_depth: int = 1, decode_steps: int = 1):
        if prefill_chunk and (prefill_chunk < 8
                              or prefill_chunk & (prefill_chunk - 1)):
            raise ValueError(
                f"prefill_chunk must be 0 or a power of two >= 8, got "
                f"{prefill_chunk} (chunks are compiled shapes; the final "
                f"partial chunk pads to a power-of-two bucket that must "
                f"not exceed the chunk)")
        if pipeline_depth < 1:
            raise ValueError(
                f"pipeline_depth must be >= 1, got {pipeline_depth}")
        if decode_steps < 1:
            raise ValueError(
                f"decode_steps must be >= 1, got {decode_steps}")
        self.params = params
        self.cfg = cfg
        self.max_batch = max_batch
        self.max_len = max_len or cfg.max_seq
        # tensor-parallel serving: with a mesh, the engine places its KV
        # cache with the heads axis over ``tp`` (cache_shardings) to
        # match params sharded by transformer.param_shardings — ONE
        # decode program spans the chips, host control flow unchanged.
        # Tokens are invariant to the mesh (tested): sharding splits the
        # matmuls/cache reads, not the math.
        self.mesh = mesh
        self._row_shd = None
        self.cache = init_cache(cfg, max_batch, self.max_len,
                                per_row_pos=True)
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            shd = cache_shardings(mesh, cfg, per_row_pos=True)
            self.cache = jax.device_put(self.cache, shd)
            self._row_shd = shd["k"]
            self._rep = NamedSharding(mesh, PartitionSpec())
        # admission bound (0 = unbounded): beyond max_batch active slots,
        # at most this many requests may WAIT — past it, submit raises
        # QueueFull so callers shed load (HTTP 429) instead of growing
        # an unbounded backlog whose tail would time out anyway
        self.max_pending = max_pending
        self._free: Deque[int] = deque(range(max_batch))
        self._active: Dict[int, _Request] = {}      # slot -> request
        self._pending: Deque[_Request] = deque()
        self._done: Dict[int, _Request] = {}
        # pipelined dispatch: up to ``pipeline_depth`` decode ticks may be
        # in flight (dispatched, tokens not yet fetched) before the host
        # blocks — the compiled program feeds itself (``new_last``), so
        # tick N+1 never needs tick N's tokens on the host. Any batch-
        # composition change (admission install, cancel) is a barrier
        # that flushes the window first (``_flush``). ``decode_steps``
        # fuses that many decode steps inside ONE compiled dispatch
        # (lax.scan), emitting [B, T] tokens per arrival.
        self.pipeline_depth = pipeline_depth
        self.decode_steps = decode_steps
        self._inflight: Deque[_InFlight] = deque()
        self._keep_masks: dict = {}     # active-slot tuple -> device mask
        self._flush_emitted = 0        # tokens consumed outside step()
        # dispatch-economics counters (bench_serve and the serving
        # loop's histograms read these):
        # - dispatch_gap_s: wall time the engine had NO decode tick in
        #   flight while decodable slots existed — the accelerator
        #   sitting host-blocked behind bookkeeping. At depth 1 every
        #   tick pays the consume->redispatch gap; at depth >= 2 the
        #   window only empties at barriers, so this drops by
        #   construction on every backend.
        # - host_block_s: wall time on the device-interaction path —
        #   compiled dispatch calls (which absorb any input-readiness
        #   stall the runtime imposes on a donated self-feeding chain)
        #   plus blocking device->host token fetches.
        self.dispatch_gap_s = 0.0
        self.host_block_s = 0.0
        self.ticks_dispatched = 0
        self.pipeline_flushes = 0
        self.tokens_emitted = 0
        self._idle_since: Optional[float] = None
        # request-level latency ledger (see _Ledger): always stamps the
        # per-REQUEST milestones (submit/admit/prefill/first/done — a
        # handful of clock reads per request); ``ledger_enabled`` gates
        # only the per-ARRIVAL TPOT stamping on the hot tick path, so
        # the overhead guard can compare the instrumented tick path
        # against the bare one. Finished ledgers park in ``_ledgers``
        # (FIFO-capped: a library caller that never reads them must not
        # leak) until pop_ledger/drain_ledgers collects them.
        self.ledger_enabled = True
        self.ledger_cap = 4096
        self._ledgers: Dict[int, dict] = {}
        # first-dispatch-per-shape compile accounting: the first call
        # into a jitted program at a new shape key traces + compiles
        # synchronously, so timing that call isolates XLA compile cost
        # (an admission storm hitting cold prefill buckets shows up
        # here, not as mystery tick latency). ``compile_events`` holds
        # individual durations until the serving loop drains them into
        # nos_tpu_serve_compile_seconds.
        self._compiled: set = set()
        self.compiles = 0
        self.compile_s = 0.0
        self.compile_events: List[float] = []
        # chunked prefill (prefill_chunk > 0): a long prompt's prefill
        # runs as fixed-size chunks interleaved with decode ticks — one
        # chunk per step() — so admitting a 32k-token request delays the
        # other slots' next token by ONE bounded chunk forward, not one
        # whole-prompt forward (head-of-line latency). Entries:
        # {"req", "row" (scratch cache mid-prefill), "todo" (remaining
        # token chunks)}. The request holds its slot while prefilling.
        self._prefill_chunk = prefill_chunk
        self._prefilling: Deque[dict] = deque()
        # prefix cache: token-tuple -> (k_rows, v_rows) of the prefix's
        # KV (device arrays, [L, 1, Hkv, len, D]), LRU-capped at
        # ``prefix_cache_size`` entries (0 = off). Requests submitted
        # with cache_prefix=True publish their prompt's KV; every submit
        # reuses the longest cached prefix of its prompt, prefilling
        # only the suffix. KV rows hold absolute-position RoPE, and a
        # prefix occupies the same absolute positions in every request
        # that shares it, so reuse is exact.
        self._prefix_max = prefix_cache_size
        self._prefixes: Dict[tuple, tuple] = {}     # insertion-ordered LRU
        self.prefix_hits = 0
        self.prefix_tokens_saved = 0
        self._last = jnp.zeros((max_batch, 1), jnp.int32)
        self._next_rid = 0
        # per-slot sampling params, rows of the compiled decode program
        self._temp = jnp.zeros((max_batch,), jnp.float32)
        self._topk = jnp.zeros((max_batch,), jnp.int32)
        self._topp = jnp.zeros((max_batch,), jnp.float32)
        self._seed = jnp.zeros((max_batch,), jnp.uint32)
        if mesh is not None:
            # host-written control rows live replicated on the mesh so
            # every jitted program sees consistently-placed inputs
            self._last, self._temp, self._topk, self._topp, self._seed = \
                jax.device_put(
                    (self._last, self._temp, self._topk, self._topp,
                     self._seed), self._rep)

        T = self.decode_steps

        def decode_one(p, toks, cache, keep, temp, topk, topp, seeds,
                       sampling: bool):
            # one fused step: forward, per-row sample-or-argmax,
            # inactive rows' pos frozen, next feed tokens. ``sampling``
            # is static: a greedy-only tick (every active slot at
            # temperature 0 — the host knows) compiles WITHOUT the
            # vocab-wide sort/softmax/RNG machinery
            pos0 = cache["pos"]
            logits, cache = forward_with_cache(p, cfg, toks, cache)
            cache["pos"] = jnp.where(keep, cache["pos"], pos0)
            step = logits[:, -1]                            # [B, vocab]
            nxt = jnp.argmax(step, axis=-1)
            if sampling:
                # the token being produced sits at absolute index
                # pos0 + 1: (seed, index) keys the stream, so a slot's
                # samples don't depend on who else is in the batch —
                # and, because pos advances inside the fused scan, not
                # on how many steps one dispatch fuses
                keys = jax.vmap(
                    lambda s, i: jax.random.fold_in(
                        jax.random.PRNGKey(s), i)
                )(seeds, pos0 + 1)
                trunc = _truncate_logits_rows(
                    step / jnp.maximum(temp, 1e-6)[:, None], topk, topp)
                sampled = jax.vmap(jax.random.categorical)(keys, trunc)
                nxt = jnp.where(temp > 0, sampled, nxt)
            new_last = jnp.where(keep[:, None], nxt[:, None], toks)
            return nxt, new_last, cache

        def decode(p, toks, cache, keep, temp, topk, topp, seeds,
                   sampling: bool):
            # cache donated. T == 1 keeps the unscanned program (no scan
            # wrapper in the hot graph); T > 1 fuses T decode steps into
            # ONE dispatch via lax.scan — per-step ops identical to the
            # T == 1 program, so greedy stays bit-exact at any T. Tokens
            # come back [B, T] per sync.
            if T == 1:
                nxt, new_last, cache = decode_one(
                    p, toks, cache, keep, temp, topk, topp, seeds,
                    sampling)
                return nxt[:, None], new_last, cache

            def body(carry, _):
                toks, cache = carry
                nxt, new_last, cache = decode_one(
                    p, toks, cache, keep, temp, topk, topp, seeds,
                    sampling)
                return (new_last, cache), nxt

            (last, cache), steps = jax.lax.scan(
                body, (toks, cache), None, length=T)
            return steps.swapaxes(0, 1), last, cache        # [B, T]

        self._decode = jax.jit(decode, donate_argnums=(2,),
                               static_argnums=(8,))

        def prefill(p, toks, row_cache):
            return forward_with_cache(p, cfg, toks, row_cache)

        self._prefill = jax.jit(prefill)

        def install(cache, rk, rv, slot, plen, first, last):
            # donated shared-cache update: write the prefilled bucket
            # rows, set the slot's pos and feed token
            cache["k"] = jax.lax.dynamic_update_slice(
                cache["k"], rk, (0, slot, 0, 0, 0))
            cache["v"] = jax.lax.dynamic_update_slice(
                cache["v"], rv, (0, slot, 0, 0, 0))
            cache["pos"] = cache["pos"].at[slot].set(plen)
            last = last.at[slot, 0].set(first)
            return cache, last

        self._install = jax.jit(install, donate_argnums=(0,))

    # ------------------------------------------------------------------
    def submit(self, prompt: List[int], max_new_tokens: int, *,
               temperature: float = 0.0, top_k: int = 0,
               top_p: float = 0.0, seed: Optional[int] = None,
               cache_prefix: bool = False,
               stop_tokens: Optional[List[int]] = None) -> int:
        """Enqueue a request. ``temperature`` 0 = greedy (bit-identical to
        ``generate``); > 0 samples, optionally truncated per-request by
        ``top_k``/``top_p``. ``seed`` keys the request's sample stream
        (default: the request id) — same (prompt, params, seed) always
        yields the same tokens, whatever else shares the batch."""
        if not prompt:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if len(prompt) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds cache length {self.max_len}")
        if temperature <= 0 and (top_k or top_p):
            raise ValueError(
                "top_k/top_p only apply when sampling — set temperature "
                "> 0 (greedy decoding ignores truncation)")
        if top_k < 0 or not (0.0 <= top_p <= 1.0):
            raise ValueError(
                f"top_k must be >= 0 and top_p in [0, 1]: got "
                f"top_k={top_k}, top_p={top_p}")
        if self.max_pending and not self._free \
                and len(self._pending) >= self.max_pending:
            raise QueueFull(
                f"{len(self._pending)} requests already waiting "
                f"(max_pending={self.max_pending}); shed load and retry")
        rid = self._next_rid
        self._next_rid += 1
        self._pending.append(_Request(
            rid, list(prompt), max_new_tokens,
            temperature=float(temperature), top_k=int(top_k),
            top_p=float(top_p),
            seed=(rid if seed is None else int(seed)) & 0xFFFFFFFF,
            cache_prefix=bool(cache_prefix) and self._prefix_max > 0,
            stop_tokens=tuple(int(t) for t in stop_tokens or ()),
            led=_Ledger(time.perf_counter())))
        self._admit()
        return rid

    def _admit(self) -> None:
        if self._pending and self._free:
            # pipeline barrier: an admission install changes batch
            # composition, and un-consumed in-flight arrivals still
            # reference the OLD slot->request binding — flush them
            # before _install writes the new request's rows
            self._flush()
        while self._pending and self._free:
            req = self._pending.popleft()
            slot = self._free.popleft()
            req.slot = slot
            self._active[slot] = req
            # admitted-to-slot: prefill starts immediately (one-shot or
            # the first chunk of a chunked admission)
            req.led.t_admit = req.led.t_prefill_start = time.perf_counter()
            self._prefill_slot(req)

    def _timed_dispatch(self, key: tuple, fn, *args):
        """Run ``fn`` and, on its FIRST call per shape ``key``, time it
        as a compile event: a jitted program traces + compiles
        synchronously inside that call, so the duration isolates XLA
        compile cost from steady-state dispatch. Steady-state calls pay
        one set lookup — nothing else."""
        if key in self._compiled:
            return fn(*args)
        t0 = time.perf_counter()
        out = fn(*args)
        dt = time.perf_counter() - t0
        self._compiled.add(key)
        self.compiles += 1
        self.compile_s += dt
        self.compile_events.append(dt)
        return out

    def _run_prefill(self, toks, row):
        """Prefill forward with compile accounting keyed by the shapes
        XLA keys on: (token bucket, scratch row length)."""
        return self._timed_dispatch(
            ("prefill", toks.shape[1], row["k"].shape[3]),
            self._prefill, self.params, toks, row)

    @functools.lru_cache(maxsize=None)      # noqa: B019 — engine-lived
    def _row_zeros(self, bucket: int):
        shape = list(self.cache["k"].shape)
        shape[1], shape[3] = 1, bucket
        z = jnp.zeros(tuple(shape), self.cache["k"].dtype)
        if self._row_shd is not None:
            # scratch rows carry the same head sharding as the shared
            # cache: prefill runs sharded and _install never gathers
            z = jax.device_put(z, self._row_shd)
        return z

    def _prefix_match(self, prompt: List[int]):
        """Pure lookup: (m, entry_key) for the longest common HEAD
        between ``prompt`` and any cached entry — a partial entry match
        reuses the entry's first m KV rows (valid on their own: they are
        exactly positions 0..m), so an identical prompt resubmit reuses
        plen-1 of itself and a longer cached prompt still serves its
        shared head. Capped at plen-1: at least one suffix token must run
        to produce the next token's logits. No side effects — the caller
        decides whether the match is actually USED (fit + profitability)
        before stats and LRU order move. Linear scan: the cache is
        operator-capped small (system prompts, not pages)."""
        cap = len(prompt) - 1
        best, best_key = 0, None
        for key in self._prefixes:
            m = 0
            for a, b in zip(key, prompt[:cap]):
                if a != b:
                    break
                m += 1
            if m > best:
                best, best_key = m, key
        return best, best_key

    def _publish_prefix(self, prompt: List[int], rk, rv) -> None:
        """Store this prompt's KV rows as a reusable prefix (trimmed to
        the exact prompt length), evicting least-recently-used entries
        past the cap."""
        key = tuple(prompt)
        plen = len(prompt)
        # pop-then-set: dict assignment to an existing key keeps its OLD
        # insertion position, and a just-republished hot prefix must not
        # sit first in line for eviction
        self._prefixes.pop(key, None)
        self._prefixes[key] = (rk[:, :, :, :plen, :], rv[:, :, :, :plen, :])
        while len(self._prefixes) > self._prefix_max:
            self._prefixes.pop(next(iter(self._prefixes)))

    def _prefill_slot(self, req: _Request) -> None:
        """Prefill the prompt over a bucket-sized scratch cache (cost
        proportional to the request), then install the rows + position
        into the shared cache in one donated jitted update. A cached
        prefix skips its share of the forward: its KV rows are written
        into the scratch cache and only the suffix tokens run. With
        ``prefill_chunk`` set and a suffix longer than one chunk, the
        forwards are deferred to step() one chunk at a time instead
        (_start_chunked_prefill) — admission costs the host only the
        scratch allocation."""
        plen = len(req.prompt)
        m, mkey = (self._prefix_match(req.prompt) if self._prefixes
                   else (0, None))
        if self._prefill_chunk and self._start_chunked_prefill(
                req, m, mkey):
            return
        # fit: the suffix's padded bucket must land inside max_len after
        # the prefix (forward_with_cache writes the whole bucket at pos
        # m, and dynamic_update_slice CLAMPS an overrunning start — which
        # would silently overwrite the prefix KV). Shrink m instead of
        # discarding the match: a 400-token reuse trimmed to 384 beats
        # zero. _bucket(plen - m) grows as m shrinks, so iterate.
        while m > 0 and m + _bucket(plen - m) > self.max_len:
            m = max(0, self.max_len - _bucket(plen - m))
        # profitability: reuse must make the suffix forward strictly
        # cheaper than full prefill (fewer query tokens per bucket tier),
        # or a trivial shared head (e.g. a lone BOS token) would route
        # every request through the prefix path — extra copies, same
        # compute — while the metrics report savings
        if m > 0 and _bucket(plen - m) >= _bucket(plen):
            m = 0
        sbucket = _bucket(plen - m)
        if m > 0:
            self._prefixes[mkey] = self._prefixes.pop(mkey)   # LRU refresh
            self.prefix_hits += 1
            self.prefix_tokens_saved += m
        else:
            mkey = None
        # scratch sized so prefix + padded suffix both fit (≥ the plen
        # bucket: _install expects rows at least plen long)
        bucket = min(_bucket(max(plen, m + sbucket)), self.max_len)
        row = {
            "k": self._row_zeros(bucket),
            "v": self._row_zeros(bucket),
            "pos": jnp.zeros((), jnp.int32),
        }
        if m > 0:
            pk, pv = self._prefixes[mkey]
            row["k"] = jax.lax.dynamic_update_slice(
                row["k"], pk[:, :, :, :m, :], (0, 0, 0, 0, 0))
            row["v"] = jax.lax.dynamic_update_slice(
                row["v"], pv[:, :, :, :m, :], (0, 0, 0, 0, 0))
            row["pos"] = jnp.int32(m)
            suffix = req.prompt[m:]
            toks = jnp.asarray(
                [suffix + [0] * (sbucket - len(suffix))], jnp.int32)
            logits, row = self._run_prefill(toks, row)
            step = logits[0, len(suffix) - 1]
        else:
            # pad to the row length (not the raw bucket): _bucket can
            # round past max_len and the write must fit the scratch
            toks = jnp.asarray(
                [req.prompt + [0] * (bucket - plen)], jnp.int32)
            logits, row = self._run_prefill(toks, row)
            step = logits[0, plen - 1]
        self._finish_prefill(req, row, step)

    def _start_chunked_prefill(self, req: _Request, m: int,
                               mkey) -> bool:
        """Queue ``req`` for chunk-at-a-time prefill (step() drives it).
        Returns False to fall back to the one-shot path when chunking
        buys nothing (suffix fits one chunk) or the chunk-padded span
        cannot fit ``max_len`` (non-power-of-two max_len edge)."""
        chunk = self._prefill_chunk
        plen = len(req.prompt)

        def span(m_: int) -> int:
            # last chunk pads to its own bucket (<= chunk: both are
            # powers of two), full chunks are exact
            full, rem = divmod(plen - m_, chunk)
            return m_ + full * chunk + (_bucket(rem) if rem else 0)

        # profitability (same invariant as the one-shot path): the reuse
        # must save at least one chunk forward, or a trivial shared head
        # does extra copies for the same compute while the metrics
        # report savings. Checked before fit-shrink: shrinking only
        # lowers m, which never makes an unprofitable match profitable.
        if m > 0 and -(-(plen - m) // chunk) >= -(-plen // chunk):
            m = 0
        # fit: same contract as the one-shot path — a clamped
        # dynamic_update_slice must never overwrite prefix KV
        guard = 0
        while m > 0 and span(m) > self.max_len and guard < 64:
            m = max(0, self.max_len - (span(m) - m))
            guard += 1
        if plen - m <= chunk or span(m) > self.max_len:
            return False
        if m > 0:
            self._prefixes[mkey] = self._prefixes.pop(mkey)   # LRU refresh
            self.prefix_hits += 1
            self.prefix_tokens_saved += m
        bucket = min(_bucket(max(plen, span(m))), self.max_len)
        row = {
            "k": self._row_zeros(bucket),
            "v": self._row_zeros(bucket),
            "pos": jnp.int32(m),
        }
        if m > 0:
            pk, pv = self._prefixes[mkey]
            row["k"] = jax.lax.dynamic_update_slice(
                row["k"], pk[:, :, :, :m, :], (0, 0, 0, 0, 0))
            row["v"] = jax.lax.dynamic_update_slice(
                row["v"], pv[:, :, :, :m, :], (0, 0, 0, 0, 0))
        suffix = req.prompt[m:]
        todo = deque(suffix[i:i + chunk]
                     for i in range(0, len(suffix), chunk))
        self._prefilling.append({"req": req, "row": row, "todo": todo})
        return True

    def _prefill_tick(self) -> int:
        """Advance the head prefilling request by one tick; when its
        chunks are exhausted, finish admission (first token + install).
        Returns tokens emitted (1 on completion, else 0)."""
        ent = self._prefilling[0]
        if not self._prefill_advance(ent):
            return 0
        self._prefilling.popleft()
        self._finish_prefill(ent["req"], ent["row"], ent["step"])
        return 1

    def _prefill_advance(self, ent: dict) -> bool:
        """Run ONE chunk forward for ``ent``; on the final chunk, store
        the last real position's logits in ``ent["step"]`` and return
        True (entry fully prefilled). Subclasses extend this to advance
        sibling caches (speculative draft) in the same tick."""
        toks_list = ent["todo"].popleft()
        rem = len(toks_list)
        rbucket = _bucket(rem) if not ent["todo"] else rem
        toks = jnp.asarray([toks_list + [0] * (rbucket - rem)], jnp.int32)
        logits, ent["row"] = self._run_prefill(toks, ent["row"])
        if ent["todo"]:
            return False
        ent["step"] = logits[0, rem - 1]
        return True

    def _finish_prefill(self, req: _Request, row: Cache,
                        step: jax.Array) -> None:
        """Shared admission tail: publish the prefix, pick the first
        token from the final-position logits, set the slot's sampling
        rows, and install the prefilled KV into the shared cache."""
        plen = len(req.prompt)
        if req.cache_prefix:
            self._publish_prefix(req.prompt, row["k"], row["v"])
        if req.temperature > 0:
            # token at absolute index plen: same (seed, index) keying as
            # the decode program, so prefill vs decode is seamless
            key = jax.random.fold_in(
                jax.random.PRNGKey(jnp.uint32(req.seed)), plen)
            trunc = _truncate_logits_rows(
                (step / max(req.temperature, 1e-6))[None, :],
                jnp.asarray([req.top_k], jnp.int32),
                jnp.asarray([req.top_p], jnp.float32))
            first = int(jax.random.categorical(key, trunc[0]))
        else:
            first = int(jnp.argmax(step))
        s = req.slot
        self._temp = self._temp.at[s].set(req.temperature)
        self._topk = self._topk.at[s].set(req.top_k)
        self._topp = self._topp.at[s].set(req.top_p)
        self._seed = self._seed.at[s].set(req.seed)
        # padding garbage past plen stays masked until overwritten: only
        # pos decides what exists
        self.cache, self._last = self._install(
            self.cache, row["k"], row["v"], jnp.int32(req.slot),
            jnp.int32(plen), jnp.int32(first), self._last)
        req.out.append(first)
        req.note_token()
        # the first token is observed HERE (the argmax/sample above was
        # a host sync): TTFT's far stamp, and the TPOT clock's arm
        req.led.t_prefill_end = req.led.t_first = req.led.t_last = \
            time.perf_counter()
        self._finish_if_done(req)

    def _finish_if_done(self, req: _Request, admit: bool = True) -> None:
        """Completion + slot recycling. Resetting the slot's per-row pos
        is the pipeline ROLLBACK: a completion observed up to
        pipeline_depth ticks late (or mid-way through a fused
        decode_steps burst) has over-decoded past the true length, but
        only pos decides what exists — the truncated host output plus
        this reset discard the overrun by construction. ``admit=False``
        is the arrival-consumption path: admission is a pipeline barrier
        and must not re-enter the flush that is consuming this arrival —
        the caller admits once, after the window drains."""
        if req.done and req.slot >= 0:
            s = req.slot
            del self._active[s]
            self.cache["pos"] = self.cache["pos"].at[s].set(0)
            self._free.append(s)
            req.slot = -1
            self._done[req.rid] = req
            self._record_ledger(req)
            if not self._active:
                # nothing left to decode: stop the dispatch-gap clock —
                # an idle engine is not host-blocked, and a stale mark
                # would book the whole idle period against the next
                # serving burst's first dispatch
                self._idle_since = None
            if admit:
                self._admit()

    def _record_ledger(self, req: _Request,
                       outcome: Optional[str] = None) -> None:
        """Close the request's ledger and park the snapshot for
        pop_ledger/drain_ledgers. FIFO-capped: a caller that never
        collects ledgers (library use, benches between fences) must not
        grow the engine unboundedly."""
        led = req.led
        if outcome is not None and led.outcome is None:
            led.outcome = outcome
        led.t_done = time.perf_counter()
        self._ledgers[req.rid] = led.snapshot(req)
        while len(self._ledgers) > self.ledger_cap:
            del self._ledgers[next(iter(self._ledgers))]

    def pop_ledger(self, rid: int) -> Optional[dict]:
        """The finished request's latency ledger (see _Ledger.snapshot),
        handed out exactly once — the serving loop pops it alongside
        pop_result to feed the TTFT/TPOT/queue/e2e histograms. None
        while the request is still running (or already popped)."""
        return self._ledgers.pop(rid, None)

    def drain_ledgers(self) -> List[dict]:
        """All uncollected finished-request ledgers, cleared — the
        bench-harness bulk read."""
        out = list(self._ledgers.values())
        self._ledgers.clear()
        return out

    # ------------------------------------------------------------------
    # pipelined decode: step() == step_begin (dispatch) + step_wait
    # (block on the oldest arrival) + step_finish (host bookkeeping).
    # The serving loop calls the three phases separately so the blocking
    # wait runs OUTSIDE its condition lock; library callers and tests
    # keep calling step().
    # ------------------------------------------------------------------
    def step(self) -> int:
        """One scheduling quantum: dispatch decode ticks until the
        in-flight window is full, consume the oldest arrival, advance
        ONE prefill chunk for the head admitting request (chunked
        prefill); returns the number of tokens emitted. Inactive slots
        ride along in each dispatch (their output discarded, their pos
        frozen in-graph — same compiled program every tick); slots
        mid-prefill are excluded from the decode batch (their cache rows
        aren't installed yet). With pipeline_depth k > 1 a completion is
        observed up to k ticks late; _finish_if_done's pos reset rolls
        the overrun back."""
        handle = self.step_begin()
        self.step_wait(handle)
        return self.step_finish(handle)

    def _active_slots(self) -> List[int]:
        pre = {ent["req"].slot for ent in self._prefilling}
        return sorted(s for s in self._active if s not in pre)

    def step_begin(self) -> Optional[_InFlight]:
        """Dispatch phase: enqueue compiled decode ticks back-to-back
        until the in-flight window holds ``pipeline_depth`` entries (the
        program computes its own next feed tokens on-device, so tick N+1
        never waits for tick N's tokens), each with a non-blocking
        device->host token fetch already started. Returns the oldest
        unconsumed arrival to wait on (None when idle). Cheap host work
        only — safe to call while holding a serving-loop lock."""
        active = self._active_slots()
        while active and len(self._inflight) < self.pipeline_depth:
            self._dispatch_tick(active)
        return self._inflight[0] if self._inflight else None

    def step_wait(self, ent: Optional[_InFlight]) -> None:
        """Block until ``ent``'s tokens are on the host (no-op for None
        or an entry a barrier flush already consumed). This is the ONLY
        place the pipelined hot loop blocks on the device; callers that
        split the phases run it outside their locks."""
        if ent is None or ent.consumed:
            return
        self._fetch(ent)

    def _fetch(self, ent: _InFlight) -> None:
        if ent.host is not None:
            return
        t0 = time.perf_counter()
        ent.host = tuple(np.asarray(a) for a in ent.payload)
        self.host_block_s += time.perf_counter() - t0

    def step_finish(self, ent: Optional[_InFlight]) -> int:
        """Host bookkeeping phase: consume ``ent`` (append tokens,
        retire completions), run one prefill chunk, and re-admit into
        any freed slots. Returns tokens emitted, including any consumed
        by barrier flushes since the last step_finish (so throughput
        accounting never loses the flushed ticks)."""
        emitted = self._flush_emitted
        self._flush_emitted = 0
        if ent is not None and not ent.consumed:
            # arrivals are consumed strictly in dispatch order; ent is
            # the window head unless a flush got there first
            assert self._inflight and self._inflight[0] is ent
            self._inflight.popleft()
            emitted += self._consume(ent)
        if self._prefilling:
            emitted += self._prefill_tick()
        self._admit()       # fill slots freed by completions (barriers)
        self._note_window_empty()
        return emitted

    def _note_window_empty(self) -> None:
        """Start the dispatch-gap clock when the in-flight window runs
        empty with decodable slots still present: from here until the
        next decode dispatch, the accelerator is host-blocked. Called
        only at the END of step_finish, after the prefill chunk and
        admission forwards have run — those are real device work, not
        gap, and must not be booked against the clock. (Every
        mid-prefill request holds a slot in _active, so the decodable
        count is the difference.)"""
        if not self._inflight and self._idle_since is None \
                and len(self._active) > len(self._prefilling):
            self._idle_since = time.perf_counter()

    def reset_dispatch_stats(self) -> None:
        """Zero the dispatch-economics counters and the gap clock —
        bench measurement windows call this at their timing fence."""
        self.dispatch_gap_s = 0.0
        self.host_block_s = 0.0
        self.ticks_dispatched = 0
        self.pipeline_flushes = 0
        self._idle_since = None

    def _dispatch_tick(self, active: List[int]) -> None:
        """Enqueue ONE compiled decode dispatch for ``active`` slots and
        start the async token fetch; no host sync."""
        keep = self._keep_mask(tuple(active))
        sampling = any(self._active[s].temperature > 0 for s in active)
        t0 = time.perf_counter()
        if self._idle_since is not None:
            # the dispatch gap ends the moment a tick is in flight again
            self.dispatch_gap_s += t0 - self._idle_since
            self._idle_since = None
        payload = self._timed_dispatch(("decode", sampling),
                                       self._dispatch, active, keep,
                                       sampling)
        self.ticks_dispatched += 1
        for a in payload:
            copy = getattr(a, "copy_to_host_async", None)
            if copy is not None:
                copy()
        self.host_block_s += time.perf_counter() - t0
        self._inflight.append(_InFlight(payload, tuple(active)))

    def _keep_mask(self, active: Tuple[int, ...]) -> jax.Array:
        """Device keep-mask for an active-slot tuple, memoized per
        instance: active sets repeat for whole decode phases, and
        rebuilding the mask was a measurable per-dispatch host cost
        (~1ms on the CPU smoke shape). Bounded: at most 2^max_batch
        distinct sets, and the dict dies with the engine (a class-level
        lru_cache would pin every engine — and its device KV cache —
        for the life of the process)."""
        keep = self._keep_masks.get(active)
        if keep is None:
            keep = jnp.zeros((self.max_batch,), bool).at[
                jnp.asarray(active, jnp.int32)].set(True)
            if self.mesh is not None:
                keep = jax.device_put(keep, self._rep)
            self._keep_masks[active] = keep
        return keep

    def _dispatch(self, active: List[int], keep: jax.Array,
                  sampling: bool) -> Tuple[jax.Array, ...]:
        """One compiled decode dispatch for ``active`` slots; returns
        the device handles the matching ``_consume_payload`` will read.
        The template owns the shared scaffolding (window management,
        keep mask, sampling flag, async fetch, ordered consumption) so
        engine subclasses override only this pair."""
        toks, self._last, self.cache = self._decode(
            self.params, self._last, self.cache, keep,
            self._temp, self._topk, self._topp, self._seed, sampling)
        return (toks,)                                  # [B, T]

    def _consume(self, ent: _InFlight) -> int:
        """Process one arrival's host tokens in order. Idempotent via
        ``ent.consumed``: a step_finish holder racing a barrier flush
        processes each tick exactly once."""
        if ent.consumed:
            return 0
        ent.consumed = True
        self._fetch(ent)        # usually a no-op: fetch already landed
        # ONE clock read per arrival (not per token) stamps every token
        # this arrival lands — the ledger's hot-path cost in full
        now = time.perf_counter() if self.ledger_enabled else 0.0
        emitted = self._consume_payload(ent, ent.host, now)
        self.tokens_emitted += emitted
        ent.payload = ()        # drop device refs promptly
        return emitted

    def _consume_payload(self, ent: _InFlight, host: tuple,
                         now: float = 0.0) -> int:
        """Append one tick's tokens ([B, T]) to its requests. A slot
        whose request already finished (observed in an EARLIER arrival,
        or mid-burst below) contributes nothing — its late tokens are
        the pipeline overrun the pos-reset rollback discards; because
        they are never appended, they also never earn a ledger stamp
        (no duplicate TPOT samples from rollbacks by construction)."""
        (toks,) = host
        emitted = 0
        for s in ent.slots:
            req = self._active.get(s)
            if req is None or req.done:
                continue
            n = 0
            for j in range(toks.shape[1]):
                req.out.append(int(toks[s, j]))
                req.note_token()
                emitted += 1
                n += 1
                if req.done:
                    break
            if n and now:
                req.led.note_tokens(n, now)
            self._finish_if_done(req, admit=False)
        return emitted

    def _flush(self) -> int:
        """Pipeline barrier: consume every in-flight arrival in dispatch
        order. Called before any batch-composition change (admission
        install, cancel) — un-consumed arrivals reference the old
        slot->request binding and must land first. Tokens emitted here
        are credited to the next step_finish via _flush_emitted."""
        emitted = 0
        if self._inflight:
            self.pipeline_flushes += 1
        while self._inflight:
            emitted += self._consume(self._inflight.popleft())
        self._flush_emitted += emitted
        return emitted

    def pop_result(self, rid: int) -> Optional[List[int]]:
        """The finished sequence for ``rid`` (prompt + generated), or None
        while it is still pending/active. Popping forgets it — each
        result is handed out exactly once (the HTTP server's contract)."""
        req = self._done.pop(rid, None)
        if req is None:
            return None
        return req.prompt + req.out[:req.max_new_tokens]

    def cancel(self, rid: int) -> bool:
        """Stop decoding a request NOW: a pending request is dropped from
        the queue; an active one is truncated at its current output and
        its slot recycled (the serving loop calls this when a streaming
        client disconnects — without it an abandoned 480-token request
        would burn its remaining ticks while queued requests wait). The
        request lands in the done-table (possibly with a partial output)
        for the caller to pop. False for an unknown/finished rid."""
        for i, req in enumerate(self._pending):
            if req.rid == rid:
                del self._pending[i]
                self._done[rid] = req        # empty output; poppable
                self._record_ledger(req, outcome="cancelled")
                return True
        # pipeline barrier: cancel mutates the slot->request binding; in-
        # flight arrivals for the old binding must land first (this may
        # even FINISH the request — then it is already done-table'd and
        # the scans below correctly find nothing). Unknown/finished rids
        # change no binding, so they must not collapse the window — the
        # serving loop cancels unconditionally on every client timeout
        if not any(req.rid == rid for req in self._active.values()) \
                and not any(e["req"].rid == rid for e in self._prefilling):
            return False
        if self._inflight:
            self._flush()
        for i, ent in enumerate(self._prefilling):
            if ent["req"].rid == rid:
                # drop the chunk queue FIRST: the slot frees below, and
                # a later _prefill_tick must never install into it
                del self._prefilling[i]
                break
        for req in self._active.values():
            if req.rid == rid:
                req.max_new_tokens = len(req.out)
                req.led.outcome = "cancelled"
                self._finish_if_done(req)    # frees the slot, admits next
                return True
        return False

    def progress(self, rid: int) -> Optional[tuple]:
        """(generated tokens so far, done) for a submitted request —
        the streaming read. None for an unknown (or already-popped) rid.
        Unlike ``pop_result`` this never forgets: a finished request
        stays readable until popped, so a streamer can observe the tail
        and THEN pop. O(slots + pending) scan — both are small by
        construction."""
        req = self._done.get(rid)
        if req is not None:
            return list(req.out[:req.max_new_tokens]), True
        for req in self._active.values():
            if req.rid == rid:
                return list(req.out), False
        for req in self._pending:
            if req.rid == rid:
                return [], False
        return None

    def occupancy(self) -> tuple:
        """(active slots, waiting requests) — the live load view the
        serving loop mirrors into gauges."""
        return len(self._active), len(self._pending)

    def stats(self) -> dict:
        """Live introspection snapshot (the /stats endpoint's engine
        half): per-slot request state, pending-queue depth and oldest
        wait, pipeline-window occupancy, prefix-cache and compile
        accounting. Host dict reads only — safe to call between ticks
        under the serving loop's lock."""
        now = time.perf_counter()
        prefilling = {e["req"].rid for e in self._prefilling}
        slots = []
        for s in sorted(self._active):
            req = self._active[s]
            slots.append({
                "slot": s,
                "rid": req.rid,
                "age_s": round(now - (req.led.t_admit
                                      or req.led.t_submit), 6),
                "pos": len(req.prompt) + len(req.out),
                "tokens_out": len(req.out),
                "max_new_tokens": req.max_new_tokens,
                "prefilling": req.rid in prefilling,
                "sampling": {"temperature": req.temperature,
                             "top_k": req.top_k, "top_p": req.top_p,
                             "seed": req.seed},
            })
        oldest = (now - self._pending[0].led.t_submit
                  if self._pending else 0.0)
        return {
            "engine": type(self).__name__,
            "max_batch": self.max_batch,
            "max_len": self.max_len,
            "slots": slots,
            "pending": {"depth": len(self._pending),
                        "oldest_wait_s": round(oldest, 6)},
            "pipeline": {"depth": self.pipeline_depth,
                         "decode_steps": self.decode_steps,
                         "in_flight": len(self._inflight),
                         "flushes": self.pipeline_flushes,
                         "ticks_dispatched": self.ticks_dispatched},
            "prefix_cache": {"capacity": self._prefix_max,
                             "entries": len(self._prefixes),
                             "hits": self.prefix_hits,
                             "tokens_saved": self.prefix_tokens_saved},
            "compiles": {"count": self.compiles,
                         "seconds": round(self.compile_s, 6)},
            "tokens_emitted": self.tokens_emitted,
        }

    def has_work(self) -> bool:
        return bool(self._active or self._pending)

    def drain(self) -> Dict[int, List[int]]:
        """Run until every submitted request completes; returns
        {request_id: prompt + generated tokens} for requests finished
        since the last drain, and forgets them."""
        while self._active or self._pending:
            if not self._active:       # pending but no free slot: bug
                raise RuntimeError("pending requests with no active slots")
            self.step()
        # the last completion can leave over-decoded arrivals in flight
        # (every request already done): drain them so no device handles
        # linger between serving bursts
        self._flush()
        self._flush_emitted = 0
        out = {r.rid: r.prompt + r.out[:r.max_new_tokens]
               for r in self._done.values()}
        self._done.clear()
        return out
