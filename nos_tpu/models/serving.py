"""Continuous-batching decode serving (slot-based, static shapes).

The TPU-native serving pattern: ONE compiled decode program over a fixed
[max_batch, 1] token window runs every step; requests occupy rows
("slots") of a shared KV cache whose ``pos`` is a per-row vector
(models/generate.init_cache(per_row_pos=True)), so a long request and a
freshly-admitted short one decode in the same batch at different depths.
A finished slot is recycled by simply resetting its pos — no
reallocation, no shape change, no retrace. Prefill runs per request over
a scratch cache sized to the power-of-two prompt bucket (a handful of
compiled shapes, attention cost proportional to the request, not to
max_len) and is installed into the shared cache by a donated jitted
update, so admission never copies the multi-GB cache on the host.

Hot-loop economics: the decode step donates the cache (updates in place,
no second full-cache allocation per token), corrects inactive rows' pos
in-graph, and the host syncs ONE small array per tick.

This is deliberately an in-process engine, not an RPC server: the
operator stack schedules pods; what runs inside a serving pod is this
loop. Every slot carries its own sampling params (temperature / top-k /
top-p / seed) as per-row vectors through the ONE compiled decode
program; a request's sample stream is keyed by (seed, absolute
position), so what a request generates is INDEPENDENT of batch
composition — sampled alone or wedged between seven neighbours, same
seed gives the same tokens (tested). Greedy rows (temperature 0) stay
bit-identical to ``generate``.
"""
from __future__ import annotations

import functools
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from nos_tpu.models.generate import (
    Cache, _truncate_logits_rows, cache_shardings, forward_paged,
    forward_with_cache, init_cache, init_paged_cache,
    paged_cache_shardings, replicated_logits,
)
from nos_tpu.models.handoff import handoff_nbytes
from nos_tpu.kvfabric.codec import chain_digest, decode_chain, encode_chain
from nos_tpu.models.kvblocks import (
    BlockAllocator, NoFreeBlocks, PrefixBlockIndex, ScaleLedger,
    blocks_for,
)
from nos_tpu.ops.attention import (
    dequantize_kv, effective_paged_impl, quantize_kv,
)
from nos_tpu.obs.slo import ChipLedger
from nos_tpu.models.tenantquota import (
    DEFAULT_TENANT, TenantQuotaConfig, TenantScheduler,
)
from nos_tpu.models.transformer import Params, TransformerConfig


from nos_tpu.models.errors import (  # noqa: F401 — canonical home is
    Infeasible, QueueFull,           # jax-free (see errors.py)
    TenantQuotaExceeded,
)

__all__ = ["DecodeServer", "QueueFull", "Infeasible",
           "TenantQuotaExceeded"]


def _bucket(n: int) -> int:
    b = 8
    while b < n:
        b *= 2
    return b


class _Ledger:
    """Per-request lifecycle stamps on the host monotonic clock
    (time.perf_counter): submitted -> admitted-to-slot -> prefill
    start/end -> first token observed -> per-arrival token batches ->
    done. The serving loop turns a finished ledger into the TTFT / TPOT
    / queue / e2e histograms, so the stamps measure what the USER
    experiences — a completion observed ``pipeline_depth`` ticks late
    is stamped at observation, because that is when its tokens become
    visible to the client.

    TPOT bookkeeping is lazy: one clock read per consumed arrival (not
    per token), stored as ``(gap_s, n_tokens)`` pairs — an arrival that
    lands ``n`` tokens at once attributes ``gap/n`` to each. Because
    ``t_last`` only ever advances and tokens are attributed exactly at
    the arrival that appended them, a pipeline rollback (over-decoded
    ticks whose tokens are never appended) can produce neither negative
    nor duplicate samples by construction."""

    __slots__ = ("t_submit", "t_admit", "t_prefill_start", "t_prefill_end",
                 "t_first", "t_last", "t_done", "outcome", "tpot")

    def __init__(self, now: float):
        self.t_submit = now
        self.t_admit = 0.0
        self.t_prefill_start = 0.0
        self.t_prefill_end = 0.0
        self.t_first = 0.0          # first token observed on the host
        self.t_last = 0.0           # most recent token observation
        self.t_done = 0.0
        self.outcome: Optional[str] = None
        self.tpot: List[Tuple[float, int]] = []     # (gap_s, tokens)

    def note_tokens(self, n: int, now: float) -> None:
        """Attribute ``n`` tokens observed at host instant ``now``. The
        first token (prefill) only arms ``t_last`` — TPOT is the
        inter-token series with the first token excluded."""
        last = self.t_last
        if last:
            self.tpot.append((max(0.0, now - last), n))
        self.t_last = now

    def snapshot(self, req: "_Request") -> dict:
        """The finished-request record the serving loop and benches
        read. ``ttft_s`` is None for a request that never produced a
        token (cancelled while pending)."""
        admitted = self.t_admit > 0.0
        return {
            "rid": req.rid,
            "tenant": req.tenant,
            "outcome": self.outcome or "finished",
            "prompt_tokens": len(req.prompt),
            "output_tokens": min(len(req.out), req.max_new_tokens),
            "queue_s": (self.t_admit if admitted else self.t_done)
            - self.t_submit,
            "prefill_s": (self.t_prefill_end - self.t_prefill_start
                          if self.t_prefill_end else None),
            "ttft_s": (self.t_first - self.t_submit
                       if self.t_first else None),
            "e2e_s": self.t_done - self.t_submit,
            "tpot": list(self.tpot),
        }


@dataclass
class _Request:
    rid: int
    prompt: List[int]
    max_new_tokens: int
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 0.0
    seed: int = 0
    out: List[int] = field(default_factory=list)
    slot: int = -1
    cache_prefix: bool = False
    stop_tokens: tuple = ()
    led: Optional[_Ledger] = None
    # paged-KV state: admission ordering under memory pressure (higher
    # priority preempted later), swap-out payload of a preempted slot
    # (host copies of its KV blocks), and the resume marker that routes
    # _admit to the restore/recompute path instead of fresh prefill
    priority: int = 0
    # request-level elastic quota: the tenant this request's tokens,
    # sheds and preemptions are accounted to (DEFAULT_TENANT for
    # unlabeled traffic); also the prefix-cache scope unless sharing
    # is enabled
    tenant: str = DEFAULT_TENANT
    preempted: bool = False
    swap_state: Optional[dict] = None
    # paged admission plumbing: prefix blocks claimed for this request
    # (refcounts already bumped) and, for chunked prefill, the full
    # block table reserved at admission
    shared_blocks: List[int] = field(default_factory=list)
    reserved_blocks: Optional[List[int]] = None
    # SLO plumbing (budgeted chunked prefill): absolute completion
    # deadline on the engine's slack clock, None = no deadline. Set at
    # submit from the serving loop's remaining deadline budget; the
    # budgeted prefill scheduler orders chunk work by the slack left
    # against it and clamps prefill when a decode slot's runs out.
    deadline: Optional[float] = None

    def note_token(self) -> None:
        """Called after each appended token: a stop token terminates the
        request (the stop token IS included in the output — the HF EOS
        convention) by truncating max_new_tokens to what was produced."""
        if self.stop_tokens and self.out[-1] in self.stop_tokens:
            self.max_new_tokens = len(self.out)

    @property
    def done(self) -> bool:
        return len(self.out) >= self.max_new_tokens


class _InFlight:
    """One dispatched-but-unconsumed decode tick: device handles for its
    outputs, the slots it decoded for, and (once ``step_wait`` ran) the
    fetched host arrays. Arrivals are consumed strictly in dispatch
    order; ``consumed`` makes consumption idempotent so a pipeline flush
    racing a ``step_finish`` holder processes each tick exactly once."""

    __slots__ = ("payload", "slots", "host", "consumed")

    def __init__(self, payload: Tuple[jax.Array, ...], slots: Tuple[int, ...]):
        self.payload = payload
        self.slots = slots
        self.host: Optional[tuple] = None
        self.consumed = False


class DecodeServer:
    """Continuous-batching engine over ``max_batch`` cache slots.

    ``submit`` enqueues a request (admitted to a free slot immediately or
    when one frees); ``step`` decodes one token for every active slot;
    ``drain`` runs to completion and returns {request_id: full token
    list} for the requests completed since the last drain (and clears
    them — a long-lived serving pod must not accumulate results).
    Greedy requests (temperature 0, the default) are bit-identical to
    ``generate(params, cfg, prompt, max_new_tokens)``; sampled requests
    carry per-slot temperature/top-k/top-p/seed through the shared
    decode program, with a (seed, position)-keyed stream that is
    invariant to batch composition.

    Dispatch economics knobs (both preserve the exactness contracts
    above for every setting — tested):

    - ``pipeline_depth=k``: up to k decode ticks in flight before the
      host blocks on a token fetch; completions observed late roll back
      by pos-reset, batch-composition changes barrier-flush the window.
    - ``decode_steps=T``: T decode steps fused into one compiled
      dispatch ([B, T] tokens per device->host sync), amortizing
      per-dispatch overhead in decode-bound phases. Streaming
      granularity coarsens to ~k*T tokens per arrival.

    Paged KV (``kv_blocks > 0``): slots stop owning ``[max_len]`` cache
    rows — KV lives in one pooled arena of ``kv_blocks`` x
    ``kv_block_size`` tokens mapped per slot by block tables, with
    refcounted COW sharing (block-granular prefix reuse, ``fork`` for
    n>1 sampling), memory-aware admission (free-block headroom + the
    HBM gauges), and preempt-by-swap-or-recompute under pressure
    (``preempt``/``kv_swap``). Every exactness contract above holds
    under paging — including across a fork and a preempt-and-resume
    (tested)."""

    def __init__(self, params: Params, cfg: TransformerConfig,
                 max_batch: int = 8, max_len: Optional[int] = None,
                 prefix_cache_size: int = 0, mesh=None,
                 prefill_chunk: int = 0, max_pending: int = 0,
                 pipeline_depth: int = 1, decode_steps: int = 1,
                 kv_block_size: int = 0, kv_blocks: int = 0,
                 kv_swap: bool = True, hbm_admit_frac: float = 0.0,
                 kv_dtype: str = "bf16",
                 tenant_quota: Optional[TenantQuotaConfig] = None,
                 tenant_clock=None, role: str = "colocated",
                 host_tier=None, prefill_budget: int = 0,
                 slack_clock=None):
        if prefill_budget < 0:
            raise ValueError(
                f"prefill_budget must be >= 0, got {prefill_budget}")
        if prefill_chunk and (prefill_chunk < 8
                              or prefill_chunk & (prefill_chunk - 1)):
            raise ValueError(
                f"prefill_chunk must be 0 or a power of two >= 8, got "
                f"{prefill_chunk} (chunks are compiled shapes; the final "
                f"partial chunk pads to a power-of-two bucket that must "
                f"not exceed the chunk)")
        if pipeline_depth < 1:
            raise ValueError(
                f"pipeline_depth must be >= 1, got {pipeline_depth}")
        if decode_steps < 1:
            raise ValueError(
                f"decode_steps must be >= 1, got {decode_steps}")
        self.params = params
        self.cfg = cfg
        self.max_batch = max_batch
        self.max_len = max_len or cfg.max_seq
        # paged KV (kv_blocks > 0): slots stop owning [max_len] cache
        # rows — KV lives in ONE pooled arena of kv_blocks x
        # kv_block_size tokens, mapped per slot by a block table the
        # decode program gathers/scatters through. Concurrency is then
        # bound by TOKENS IN USE, not slots x worst-case length.
        self.paged = kv_blocks > 0
        self.kv_block_size = kv_block_size if self.paged else 0
        self.kv_swap = bool(kv_swap)
        self.hbm_admit_frac = float(hbm_admit_frac or 0.0)
        # int8 KV (paged only): the arena stores quantized K/V with
        # per-block scale planes — ~2x fewer KV bytes per token, so a
        # fixed HBM budget holds ~2x the blocks and sustains ~2x the
        # concurrency. Prefill/attention math still runs in cfg.dtype:
        # writes quantize on the paged scatter, reads dequantize on the
        # gather (ops/attention.quantize_kv / dequantize_kv).
        if kv_dtype not in ("bf16", "int8"):
            raise ValueError(
                f"kv_dtype must be bf16|int8, got {kv_dtype!r}")
        if kv_dtype == "int8" and not self.paged:
            raise ValueError(
                "kv_dtype=int8 requires the paged KV cache (set "
                "kv_blocks/kv_block_size): the slot-static engine has "
                "no per-block scale storage — run bf16, or enable "
                "paging to use int8 KV")
        self.kv_dtype = kv_dtype if self.paged else "bf16"
        # which paged decode-attention formulation this engine's
        # programs trace: NOS_TPU_PAGED_KERNEL captured ONCE at build
        # and passed explicitly into every forward_paged trace, so a
        # later env change (another engine built in this process) can
        # neither flip a not-yet-compiled shape's formulation nor make
        # the /stats echo lie. One formulation for every query shape:
        # decode steps, speculative verify bursts and in-arena suffix
        # prefill all trace the same choice (the kernel's S>1 causal
        # window makes verify bit-consistent with sequential kernel
        # decode — see forward_paged).
        self.paged_kernel = (effective_paged_impl(cfg.head_dim)
                             if self.paged else None)
        if self.paged:
            bs = kv_block_size
            if self.max_len > cfg.max_seq:
                raise ValueError(
                    f"cache max_len {self.max_len} exceeds the rope "
                    f"table (cfg.max_seq {cfg.max_seq})")
            if bs < 8 or bs & (bs - 1):
                raise ValueError(
                    f"kv_block_size must be a power of two >= 8, got "
                    f"{bs} (blocks are compiled copy shapes, and "
                    f"power-of-two sizes keep them bucket-aligned)")
            if self.max_len % bs:
                raise ValueError(
                    f"max_len {self.max_len} must be a multiple of "
                    f"kv_block_size {bs}: the gathered per-row timeline "
                    f"(blocks_per_slot x block_size) must equal max_len "
                    f"exactly so paged attention stays bit-identical to "
                    f"the slot-static program")
            if mesh is not None and "tp" in mesh.axis_names \
                    and cfg.kv_heads % mesh.shape["tp"]:
                raise ValueError(
                    f"paged KV on this mesh: kv_heads {cfg.kv_heads} "
                    f"not divisible by tp={mesh.shape['tp']} — the "
                    f"block arena shards its head axis over tp "
                    f"(paged_cache_shardings) and cannot split a head; "
                    f"use a tp that divides kv_heads or run kv_blocks=0")
        # prefill/decode disaggregation role: "colocated" (the default
        # — prefill and decode in one engine), "prefill" (requests
        # leave after their first token as a KV handoff payload, see
        # pop_handoffs), "decode" (a colocated engine that mainly
        # adopts handoffs via restore; identical engine behavior, the
        # label is for validation + the /stats config echo)
        if role not in ("colocated", "prefill", "decode"):
            raise ValueError(
                f"role must be colocated|prefill|decode, got {role!r}")
        if role != "colocated" and not self.paged:
            raise ValueError(
                f"role={role} requires the paged KV cache (set "
                f"kv_blocks/kv_block_size): the handoff payload is the "
                f"swap format — quantized blocks + per-block scales — "
                f"which only the paged engine stores")
        self.role = role
        # tensor-parallel serving: with a mesh, the engine places its KV
        # cache with the heads axis over ``tp`` (cache_shardings) to
        # match params sharded by transformer.param_shardings — ONE
        # decode program spans the chips, host control flow unchanged.
        # Tokens are invariant to the mesh (tested): sharding splits the
        # matmuls/cache reads, not the math.
        self.mesh = mesh
        self._row_shd = None
        if self.paged:
            self._nbs = self.max_len // kv_block_size
            self._alloc = BlockAllocator(kv_blocks, kv_block_size)
            self.cache = init_paged_cache(cfg, kv_blocks, kv_block_size,
                                          max_batch,
                                          kv_dtype=self.kv_dtype)
            self._scales: Optional[ScaleLedger] = None
            if self.kv_dtype == "int8":
                # per-block scale lifecycle rides the allocator: frees
                # drop the ledger entry in the same decref that frees
                # the block, wherever that decref comes from
                self._scales = ScaleLedger()
                self._alloc.scale_ledger = self._scales
            self._table = jnp.zeros((max_batch, self._nbs), jnp.int32)
            self._tables: List[List[int]] = [[] for _ in range(max_batch)]
            self._pindex = (PrefixBlockIndex(self._alloc,
                                             prefix_cache_size)
                            if prefix_cache_size > 0 else None)
        else:
            self.cache = init_cache(cfg, max_batch, self.max_len,
                                    per_row_pos=True)
            self._scales = None
        # KV fabric (ISSUE 17): ``host_tier`` is a kvfabric
        # HostTierStore — the host-RAM tier under the HBM arena.
        # With it attached, prefix-chain eviction DEMOTES the LRU
        # chain's swap payload into the store (the PrefixBlockIndex
        # on_evict hook) instead of dropping it, and a prefix miss
        # that matches a stored chain PROMOTES it back via the batched
        # restore scatter, bit-exact. Independent of the tier, a paged
        # engine with a prefix index can export chains by digest
        # (export_chain) and adopt a peer replica's payload
        # (ingest_chain) — the cross-replica migration half.
        if host_tier is not None and (not self.paged
                                      or self._pindex is None):
            raise ValueError(
                "host_tier requires the paged KV cache with a prefix "
                "index (kv_blocks/kv_block_size + prefix_cache_size): "
                "the tier stores demoted prefix chains, which only the "
                "paged prefix index produces")
        self._host_tier = host_tier
        self._fabric = {"demote": 0, "promote": 0,
                        "ingest": 0, "ingest_rejected": 0}
        self._digests: Dict[tuple, str] = {}    # chain key -> digest
        self._blk_nbytes: Optional[int] = None
        if self._host_tier is not None:
            self._pindex.on_evict = self._demote_chain
        # blocks freed while decode ticks are still in flight park here
        # until the next barrier/window-drain: an in-flight tick's
        # in-graph writes still target the freeing slot's OLD blocks,
        # so handing them to a new owner before the window drains would
        # cross-corrupt KV. Preemption accounting rides alongside.
        self._deferred: List[int] = []
        self.preempts = {"swap": 0, "recompute": 0}
        # prefill/decode disaggregation (role="prefill"): requests that
        # finished prefill park HERE as resumable handoff states (the
        # swap-payload format — see _handoff_slot) until the serving
        # loop ships them to a decode-role engine. Insertion-ordered:
        # pop_handoffs hands them out in admission order. The counters
        # feed nos_tpu_serve_handoff_* and the bench's byte model.
        self._handoffs: Dict[int, dict] = {}
        self.handoffs = 0
        self.handoff_payload_bytes = 0
        self.handoff_capture_s = 0.0
        # quota-reclaim preemptions (a subset of preempts): slots
        # vacated because a guaranteed tenant was waiting, not because
        # the block pool ran dry
        self.tenant_reclaims = 0
        self.hbm: Optional[dict] = None
        self._hbm_dead = False
        self._hbm_next = 0.0
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            self._rep = NamedSharding(mesh, PartitionSpec())
            if self.paged:
                # the arena (and int8 scale planes) shard their KV-head
                # axis over tp — same convention as the slot-static
                # cache; block ids stay replicated (they are host
                # control state). Scratch prefill rows carry the same
                # head sharding, so prefill runs sharded and the
                # block installs never gather.
                shd = paged_cache_shardings(mesh, cfg,
                                            kv_dtype=self.kv_dtype)
                self.cache = jax.device_put(self.cache, shd)
                self._row_shd = cache_shardings(
                    mesh, cfg, per_row_pos=True)["k"]
                # the device block table is a host-written control row
                # like _last/_temp below: replicated, so every jitted
                # program sees consistently-placed inputs
                self._table = jax.device_put(self._table, self._rep)
            else:
                shd = cache_shardings(mesh, cfg, per_row_pos=True)
                self.cache = jax.device_put(self.cache, shd)
                self._row_shd = shd["k"]
        # admission bound (0 = unbounded): beyond max_batch active slots,
        # at most this many requests may WAIT — past it, submit raises
        # QueueFull so callers shed load (HTTP 429) instead of growing
        # an unbounded backlog whose tail would time out anyway
        self.max_pending = max_pending
        # request-level elastic quota (tenant_quota set = on): the
        # admission queue stops being FIFO — a jax-free weighted
        # scheduler (models/tenantquota.py) picks the next admitted
        # request by tenant token-rate vs min/max, guaranteed tenants
        # first, borrowed capacity proportional to the SAME
        # guaranteed_overquotas math the pod-level quota layer runs.
        # When a guaranteed tenant waits with no headroom, the engine
        # reclaims by preempting the most-over-quota tenant's youngest
        # slot through the bit-exact preemption machinery (paged only).
        # ``tenant_clock`` injects the rate clock for deterministic
        # benches/tests; production uses the host monotonic clock.
        self._tq = (TenantScheduler(tenant_quota)
                    if tenant_quota is not None else None)
        self._tq_clock = tenant_clock or time.perf_counter
        self._prefix_scoped = (tenant_quota is not None
                               and not tenant_quota.share_prefix)
        # per-tenant chip-second attribution (ISSUE 20): ON only when
        # the tenant config carries slo objectives — every hot-path
        # hook below is a single ``self.chip is None`` check when off
        # (the acceptance bar: unconfigured == zero new per-tick work).
        # ``_chip_work`` accumulates this quantum's structural token
        # weights ((tenant, phase) -> tokens); chip_note_quantum drains
        # it into the ledger with the quantum's existing clock reads.
        self.chip = (ChipLedger()
                     if tenant_quota is not None
                     and tenant_quota.slo_enabled() else None)
        self._chip_work: Dict[Tuple[str, str], int] = {}
        # True while _admit last broke on the paged memory-headroom
        # check with free slots available: the queue is blocked on
        # KV-blocks/HBM, not slots — submit sheds with
        # reason="hbm_admission" so operators (and the fleet
        # controller) can tell memory pressure from slot scarcity
        self._admit_blocked = False
        self._free: Deque[int] = deque(range(max_batch))
        self._active: Dict[int, _Request] = {}      # slot -> request
        self._pending: Deque[_Request] = deque()
        self._done: Dict[int, _Request] = {}
        # pipelined dispatch: up to ``pipeline_depth`` decode ticks may be
        # in flight (dispatched, tokens not yet fetched) before the host
        # blocks — the compiled program feeds itself (``new_last``), so
        # tick N+1 never needs tick N's tokens on the host. Any batch-
        # composition change (admission install, cancel) is a barrier
        # that flushes the window first (``_flush``). ``decode_steps``
        # fuses that many decode steps inside ONE compiled dispatch
        # (lax.scan), emitting [B, T] tokens per arrival.
        self.pipeline_depth = pipeline_depth
        self.decode_steps = decode_steps
        self._inflight: Deque[_InFlight] = deque()
        self._keep_masks: dict = {}     # active-slot tuple -> device mask
        self._flush_emitted = 0        # tokens consumed outside step()
        # dispatch-economics counters (bench_serve and the serving
        # loop's histograms read these):
        # - dispatch_gap_s: wall time the engine had NO decode tick in
        #   flight while decodable slots existed — the accelerator
        #   sitting host-blocked behind bookkeeping. At depth 1 every
        #   tick pays the consume->redispatch gap; at depth >= 2 the
        #   window only empties at barriers, so this drops by
        #   construction on every backend.
        # - host_block_s: wall time on the device-interaction path —
        #   compiled dispatch calls (which absorb any input-readiness
        #   stall the runtime imposes on a donated self-feeding chain)
        #   plus blocking device->host token fetches.
        self.dispatch_gap_s = 0.0
        self.host_block_s = 0.0
        self.ticks_dispatched = 0
        self.pipeline_flushes = 0
        self.tokens_emitted = 0
        self._idle_since: Optional[float] = None
        # tick-phase seam for the serving loop's profiler: the last
        # step_begin's host time split into assembly (block discipline,
        # admission, batch composition) vs device dispatch — derived
        # from the perf_counter reads _dispatch_tick already takes
        # plus one pair at step_begin's edges
        self.last_assemble_s = 0.0
        self._begin_dispatch_s = 0.0
        # request-level latency ledger (see _Ledger): always stamps the
        # per-REQUEST milestones (submit/admit/prefill/first/done — a
        # handful of clock reads per request); ``ledger_enabled`` gates
        # only the per-ARRIVAL TPOT stamping on the hot tick path, so
        # the overhead guard can compare the instrumented tick path
        # against the bare one. Finished ledgers park in ``_ledgers``
        # (FIFO-capped: a library caller that never reads them must not
        # leak) until pop_ledger/drain_ledgers collects them.
        self.ledger_enabled = True
        self.ledger_cap = 4096
        self._ledgers: Dict[int, dict] = {}
        # first-dispatch-per-shape compile accounting: the first call
        # into a jitted program at a new shape key traces + compiles
        # synchronously, so timing that call isolates XLA compile cost
        # (an admission storm hitting cold prefill buckets shows up
        # here, not as mystery tick latency). ``compile_events`` holds
        # individual durations until the serving loop drains them into
        # nos_tpu_serve_compile_seconds.
        self._compiled: set = set()
        self.compiles = 0
        self.compile_s = 0.0
        self.compile_events: List[float] = []
        # chunked prefill (prefill_chunk > 0): a long prompt's prefill
        # runs as fixed-size chunks interleaved with decode ticks — one
        # chunk per step() — so admitting a 32k-token request delays the
        # other slots' next token by ONE bounded chunk forward, not one
        # whole-prompt forward (head-of-line latency). Entries:
        # {"req", "row" (scratch cache mid-prefill), "todo" (remaining
        # token chunks)}. The request holds its slot while prefilling.
        self._prefill_chunk = prefill_chunk
        self._prefilling: Deque[dict] = deque()
        # per-tick prefill budget (prefill_budget > 0): each step()
        # spends at most this many prompt tokens on chunk forwards,
        # choosing WHICH chunked admissions advance by deadline slack
        # (EDF on estimated TTFT) instead of the unconditional
        # head-of-line one-chunk-per-tick rule. Budget left unspent on
        # a light tick accrues as credit (capped) so a chunk larger
        # than the budget still advances every few ticks; when any
        # decode slot's TPOT slack goes negative the budget clamps to
        # zero for the tick so decode drains first; a prefill whose
        # TTFT slack is inside one tick may overdraw the budget once
        # per tick (credit goes negative and pays back). Scheduling
        # only changes WHEN a chunk runs — never its contents — so
        # every budget schedule is token-identical to the unbudgeted
        # run (tested). 0 = the legacy unconditional rule.
        self.prefill_budget = prefill_budget
        self._prefill_credit = 0.0
        # slack clock: all deadline arithmetic (submit stamps, EDF
        # order, clamp checks) reads this — injectable so benches and
        # tests schedule deterministically on a fake clock
        self._slack_clock = slack_clock or time.monotonic
        # rolling cost model measured on THIS engine: seconds per
        # prefill prompt-token (sampled around each chunk forward) and
        # seconds per decode tick (fed by the serving loop's
        # tick-phase profiler via note_tick_seconds; plain step()
        # callers self-measure on compile-free ticks). The *_hint
        # attrs pin the estimates for deterministic scheduling tests.
        self._chunk_tok_s: Deque[float] = deque(maxlen=64)
        self._tick_s: Deque[float] = deque(maxlen=64)
        self.prefill_tok_s_hint: Optional[float] = None
        self.tick_s_hint: Optional[float] = None
        # budgeted-scheduler accounting (stats + the loop's counters)
        self.prefill_chunk_tokens = 0    # all chunk-forward tokens
        self.prefill_budget_spent = 0    # tokens charged to a budget
        self.prefill_budget_clamped = 0  # ticks clamped for TPOT slack
        self.prefill_budget_overrides = 0   # TTFT-critical overdraws
        # prefix cache: token-tuple -> (k_rows, v_rows) of the prefix's
        # KV (device arrays, [L, 1, Hkv, len, D]), LRU-capped at
        # ``prefix_cache_size`` entries (0 = off). Requests submitted
        # with cache_prefix=True publish their prompt's KV; every submit
        # reuses the longest cached prefix of its prompt, prefilling
        # only the suffix. KV rows hold absolute-position RoPE, and a
        # prefix occupies the same absolute positions in every request
        # that shares it, so reuse is exact.
        self._prefix_max = prefix_cache_size
        self._prefixes: Dict[tuple, tuple] = {}     # insertion-ordered LRU
        self.prefix_hits = 0
        self.prefix_tokens_saved = 0
        self._last = jnp.zeros((max_batch, 1), jnp.int32)
        self._next_rid = 0
        # per-slot sampling params, rows of the compiled decode program
        self._temp = jnp.zeros((max_batch,), jnp.float32)
        self._topk = jnp.zeros((max_batch,), jnp.int32)
        self._topp = jnp.zeros((max_batch,), jnp.float32)
        self._seed = jnp.zeros((max_batch,), jnp.uint32)
        if mesh is not None:
            # host-written control rows live replicated on the mesh so
            # every jitted program sees consistently-placed inputs
            self._last, self._temp, self._topk, self._topp, self._seed = \
                jax.device_put(
                    (self._last, self._temp, self._topk, self._topp,
                     self._seed), self._rep)

        T = self.decode_steps

        def decode_one(fwd, toks, cache, keep, temp, topk, topp, seeds,
                       sampling: bool):
            # one fused step: forward, per-row sample-or-argmax,
            # inactive rows' pos frozen, next feed tokens. ``sampling``
            # is static: a greedy-only tick (every active slot at
            # temperature 0 — the host knows) compiles WITHOUT the
            # vocab-wide sort/softmax/RNG machinery. ``fwd`` closes
            # over params and (for paged mode) the block table, so the
            # per-step ops here are IDENTICAL between the slot-static
            # and paged programs — the bit-exactness contract.
            pos0 = cache["pos"]
            logits, cache = fwd(toks, cache)
            cache["pos"] = jnp.where(keep, cache["pos"], pos0)
            step = logits[:, -1]                             # [B, vocab]
            if sampling:
                # the decision row is canonicalized (replicated f32
                # under a mesh) BEFORE argmax/truncation/categorical:
                # sharded engines then run the exact single-device
                # sampling program — same RNG bits, same thresholds —
                # so tokens stay invariant to the mesh on the SAMPLED
                # path too (see generate.replicated_logits for the
                # triaged root cause). Greedy-only ticks skip it:
                # argmax is layout-exact already, and the hottest path
                # must not pay a per-step [B, vocab] all-gather
                step = replicated_logits(step, mesh)
            nxt = jnp.argmax(step, axis=-1)
            if sampling:
                # the token being produced sits at absolute index
                # pos0 + 1: (seed, index) keys the stream, so a slot's
                # samples don't depend on who else is in the batch —
                # and, because pos advances inside the fused scan, not
                # on how many steps one dispatch fuses
                keys = jax.vmap(
                    lambda s, i: jax.random.fold_in(
                        jax.random.PRNGKey(s), i)
                )(seeds, pos0 + 1)
                trunc = _truncate_logits_rows(
                    step / jnp.maximum(temp, 1e-6)[:, None], topk, topp)
                sampled = jax.vmap(jax.random.categorical)(keys, trunc)
                nxt = jnp.where(temp > 0, sampled, nxt)
            new_last = jnp.where(keep[:, None], nxt[:, None], toks)
            return nxt, new_last, cache

        def decode_core(fwd, toks, cache, keep, temp, topk, topp, seeds,
                        sampling: bool):
            # cache donated by the jit wrappers below. T == 1 keeps the
            # unscanned program (no scan wrapper in the hot graph);
            # T > 1 fuses T decode steps into ONE dispatch via lax.scan
            # — per-step ops identical to the T == 1 program, so greedy
            # stays bit-exact at any T. Tokens come back [B, T] per sync.
            if T == 1:
                nxt, new_last, cache = decode_one(
                    fwd, toks, cache, keep, temp, topk, topp, seeds,
                    sampling)
                return nxt[:, None], new_last, cache

            def body(carry, _):
                toks, cache = carry
                nxt, new_last, cache = decode_one(
                    fwd, toks, cache, keep, temp, topk, topp, seeds,
                    sampling)
                return (new_last, cache), nxt

            (last, cache), steps = jax.lax.scan(
                body, (toks, cache), None, length=T)
            return steps.swapaxes(0, 1), last, cache        # [B, T]

        def decode(p, toks, cache, keep, temp, topk, topp, seeds,
                   sampling: bool):
            return decode_core(
                lambda t, c: forward_with_cache(p, cfg, t, c),
                toks, cache, keep, temp, topk, topp, seeds, sampling)

        def decode_paged(p, toks, cache, table, keep, temp, topk, topp,
                         seeds, sampling: bool):
            # inactive rows' table entries zero out to the reserved
            # null block: their in-graph writes (pos frozen afterwards,
            # output discarded) land somewhere no active row ever
            # reads, instead of a freed block a new request may own
            table = jnp.where(keep[:, None], table, 0)
            return decode_core(
                lambda t, c: forward_paged(p, cfg, t, c, table,
                                           paged_impl=self.paged_kernel,
                                           mesh=mesh),
                toks, cache, keep, temp, topk, topp, seeds, sampling)

        if self.paged:
            self._decode = jax.jit(decode_paged, donate_argnums=(2,),
                                   static_argnums=(9,))
            # 1-row decode twin for kernel-formulation recompute
            # resume (_replay_committed) and in-arena suffix prefill
            # (_paged_prefill_in_arena — jit re-specializes per window
            # width, so one callable serves both the [1,1] replay and
            # the bucketed S>1 windows): same forward_paged, same
            # formulation, no keep/sampling machinery. Undonated: the
            # replay threads the live arena through without
            # surrendering it.
            self._replay_step = jax.jit(
                lambda p, t, c, tab: forward_paged(
                    p, cfg, t, c, tab, paged_impl=self.paged_kernel,
                    mesh=mesh))
        else:
            self._decode = jax.jit(decode, donate_argnums=(2,),
                                   static_argnums=(8,))

        def prefill(p, toks, row_cache):
            return forward_with_cache(p, cfg, toks, row_cache)

        self._prefill = jax.jit(prefill)

        def install(cache, rk, rv, slot, plen, first, last):
            # donated shared-cache update: write the prefilled bucket
            # rows, set the slot's pos and feed token
            cache["k"] = jax.lax.dynamic_update_slice(
                cache["k"], rk, (0, slot, 0, 0, 0))
            cache["v"] = jax.lax.dynamic_update_slice(
                cache["v"], rv, (0, slot, 0, 0, 0))
            cache["pos"] = cache["pos"].at[slot].set(plen)
            last = last.at[slot, 0].set(first)
            return cache, last

        self._install = jax.jit(install, donate_argnums=(0,))

        if self.paged:
            bs = self.kv_block_size

            def blk_shape(arr):
                return (arr.shape[0], 1, arr.shape[2], bs, arr.shape[4])

            def scale_blk(arr):
                # the [L, NB, Hkv, bs] scale-plane slice of one block
                return (arr.shape[0], 1, arr.shape[2], bs)

            def install_block(cache, rk, rv, phys, start):
                # one block of a prefilled scratch row (token offset
                # ``start``) -> physical arena block ``phys``; traced
                # scalars, so admission compiles ONE program per
                # scratch bucket, not per block index. An int8 arena
                # quantizes here — the scratch row stays cfg.dtype so
                # prefill math is dtype-invariant.
                bk = jax.lax.dynamic_slice(
                    rk, (0, 0, 0, start, 0), blk_shape(rk))
                bv = jax.lax.dynamic_slice(
                    rv, (0, 0, 0, start, 0), blk_shape(rv))
                if "k_scale" in cache:
                    # the SAME per-token symmetric rule the decode
                    # scatter applies (quantize_kv is shape-generic
                    # over leading axes) — ONE implementation, so
                    # prefill-installed and decode-written positions
                    # dequantize identically
                    bk, sk = quantize_kv(bk)
                    bv, sv = quantize_kv(bv)
                    cache["k_scale"] = jax.lax.dynamic_update_slice(
                        cache["k_scale"], sk, (0, phys, 0, 0))
                    cache["v_scale"] = jax.lax.dynamic_update_slice(
                        cache["v_scale"], sv, (0, phys, 0, 0))
                else:
                    bk = bk.astype(cache["k"].dtype)
                    bv = bv.astype(cache["v"].dtype)
                cache["k"] = jax.lax.dynamic_update_slice(
                    cache["k"], bk, (0, phys, 0, 0, 0))
                cache["v"] = jax.lax.dynamic_update_slice(
                    cache["v"], bv, (0, phys, 0, 0, 0))
                return cache

            self._install_block = jax.jit(install_block,
                                          donate_argnums=(0,))

            def scratch_from_block(rk, rv, cache, phys, start):
                # arena block -> scratch-row token offset: seeds the
                # suffix prefill with a shared prefix's KV (no
                # donation: rk may be the memoized _row_zeros array).
                # int8 arenas dequantize back to the scratch dtype so
                # the suffix forward attends to the SAME values a
                # decode-path gather would read.
                bk = jax.lax.dynamic_slice(
                    cache["k"], (0, phys, 0, 0, 0),
                    blk_shape(cache["k"]))
                bv = jax.lax.dynamic_slice(
                    cache["v"], (0, phys, 0, 0, 0),
                    blk_shape(cache["v"]))
                if "k_scale" in cache:
                    sk = jax.lax.dynamic_slice(
                        cache["k_scale"], (0, phys, 0, 0),
                        scale_blk(cache["k_scale"]))
                    sv = jax.lax.dynamic_slice(
                        cache["v_scale"], (0, phys, 0, 0),
                        scale_blk(cache["v_scale"]))
                    bk = dequantize_kv(bk, sk, rk.dtype)
                    bv = dequantize_kv(bv, sv, rv.dtype)
                rk = jax.lax.dynamic_update_slice(
                    rk, bk, (0, 0, 0, start, 0))
                rv = jax.lax.dynamic_update_slice(
                    rv, bv, (0, 0, 0, start, 0))
                return rk, rv

            self._scratch_block = jax.jit(scratch_from_block)

            def cow_block(cache, src, dst):
                # copy-on-write: duplicate a shared block before its
                # first write so no written block is ever aliased. The
                # scale planes copy in the SAME program — a COW'd int8
                # block without its scales would dequantize garbage.
                bk = jax.lax.dynamic_slice(
                    cache["k"], (0, src, 0, 0, 0), blk_shape(cache["k"]))
                bv = jax.lax.dynamic_slice(
                    cache["v"], (0, src, 0, 0, 0), blk_shape(cache["v"]))
                cache["k"] = jax.lax.dynamic_update_slice(
                    cache["k"], bk, (0, dst, 0, 0, 0))
                cache["v"] = jax.lax.dynamic_update_slice(
                    cache["v"], bv, (0, dst, 0, 0, 0))
                if "k_scale" in cache:
                    sk = jax.lax.dynamic_slice(
                        cache["k_scale"], (0, src, 0, 0),
                        scale_blk(cache["k_scale"]))
                    sv = jax.lax.dynamic_slice(
                        cache["v_scale"], (0, src, 0, 0),
                        scale_blk(cache["v_scale"]))
                    cache["k_scale"] = jax.lax.dynamic_update_slice(
                        cache["k_scale"], sk, (0, dst, 0, 0))
                    cache["v_scale"] = jax.lax.dynamic_update_slice(
                        cache["v_scale"], sv, (0, dst, 0, 0))
                return cache

            self._cow_block = jax.jit(cow_block, donate_argnums=(0,))

            def restore_blocks(cache, bk, bv, idx):
                # swap-in: a request's WHOLE payload ([L, nblk, Hkv,
                # bs, D]) scatters back into the arena in ONE donated
                # dispatch — swap resume, supervised restart and
                # handoff adoption were paying one dispatch per block,
                # which showed up as decode-tick stalls on a decode-
                # role engine adopting under load. Shape key = nblk
                # (bounded by max_len / block_size compiled variants).
                cache["k"] = cache["k"].at[:, idx].set(bk)
                cache["v"] = cache["v"].at[:, idx].set(bv)
                return cache

            self._restore_blocks = jax.jit(restore_blocks,
                                           donate_argnums=(0,))

            def restore_blocks_q(cache, bk, bv, sk, sv, idx):
                # int8 swap-in: the quantized bytes AND their scales
                # restore together — byte-exact by construction, so a
                # swapped-and-restored int8 slot continues on the
                # identical dequantized timeline
                cache["k"] = cache["k"].at[:, idx].set(bk)
                cache["v"] = cache["v"].at[:, idx].set(bv)
                cache["k_scale"] = cache["k_scale"].at[:, idx].set(sk)
                cache["v_scale"] = cache["v_scale"].at[:, idx].set(sv)
                return cache

            self._restore_blocks_q = jax.jit(restore_blocks_q,
                                             donate_argnums=(0,))

            def set_row_state(cache, last, slot, pos, tok):
                # shared admission/resume/fork tail: the slot's device
                # position and feed token in one donated update
                cache["pos"] = cache["pos"].at[slot].set(pos)
                last = last.at[slot, 0].set(tok)
                return cache, last

            self._set_row_state = jax.jit(set_row_state,
                                          donate_argnums=(0,))

    # ------------------------------------------------------------------
    def submit(self, prompt: List[int], max_new_tokens: int, *,
               temperature: float = 0.0, top_k: int = 0,
               top_p: float = 0.0, seed: Optional[int] = None,
               cache_prefix: bool = False,
               stop_tokens: Optional[List[int]] = None,
               priority: int = 0,
               tenant: Optional[str] = None,
               deadline_s: Optional[float] = None) -> int:
        """Enqueue a request. ``temperature`` 0 = greedy (bit-identical to
        ``generate``); > 0 samples, optionally truncated per-request by
        ``top_k``/``top_p``. ``seed`` keys the request's sample stream
        (default: the request id) — same (prompt, params, seed) always
        yields the same tokens, whatever else shares the batch.
        ``priority`` matters only under paged-KV memory pressure: when
        the block pool runs dry the LOWEST-priority (then
        youngest-admitted) slot is preempted, never a higher one.

        ``tenant`` is the request-level elastic-quota identity (None =
        the default tenant). With ``tenant_quota`` configured,
        admission order is the weighted tenant pick, the prefix cache
        is tenant-scoped, and a tenant measured at/over its ``max``
        token-rate while the engine is busy is shed with the
        machine-readable ``tenant_quota`` reason (TenantQuotaExceeded,
        a QueueFull: HTTP 429 + Retry-After).

        ``deadline_s`` is the request's remaining completion budget in
        seconds (None/0 = none): the budgeted chunked-prefill
        scheduler (``prefill_budget``) orders chunk work EDF-style on
        the slack left against it and protects decode slots whose
        TPOT slack runs out. Enforcement (shedding, mid-flight
        expiry) stays with the serving loop — the engine only
        schedules against it.

        Refusals split permanent from transient: ``Infeasible`` (a
        ValueError — the request can NEVER fit this server: HTTP 400)
        vs ``QueueFull`` (capacity is exhausted right now: HTTP 429 +
        Retry-After)."""
        if not prompt:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if len(prompt) + max_new_tokens > self.max_len:
            raise Infeasible(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds cache length {self.max_len}")
        if self.paged:
            # total KV the request can ever need: positions
            # [0, plen + max_new - 1) — the final token is produced by
            # the forward that writes KV at plen + max_new - 2
            cap = len(prompt) + max_new_tokens - 1
            need = blocks_for(cap, self.kv_block_size)
            if need > self._alloc.capacity:
                raise Infeasible(
                    f"request needs {need} KV blocks at its full length "
                    f"but the pool only has {self._alloc.capacity} "
                    f"(kv_blocks={self._alloc.num_blocks}, "
                    f"kv_block_size={self.kv_block_size}); no amount of "
                    f"retrying can serve it")
        if temperature <= 0 and (top_k or top_p):
            raise ValueError(
                "top_k/top_p only apply when sampling — set temperature "
                "> 0 (greedy decoding ignores truncation)")
        if top_k < 0 or not (0.0 <= top_p <= 1.0):
            raise ValueError(
                f"top_k must be >= 0 and top_p in [0, 1]: got "
                f"top_k={top_k}, top_p={top_p}")
        if self._tq is not None:
            now = self._tq_clock()
            busy = bool(not self._free or self._pending
                        or self._prefilling)
            if busy and self._tq.over_max(tenant, now):
                # the ladder's last rung: this tenant is at/over its
                # max token-rate AND the engine has contention — shed
                # with the tenant_quota reason so the client (and the
                # gateway's retry policy) backs off on ITS quota, not
                # on fleet capacity. An idle engine keeps lending even
                # past max: refusing work for an idle slot would trade
                # throughput for nothing (work conservation).
                self._tq.note_shed(tenant)
                spec = self._tq.cfg.spec(tenant)
                raise TenantQuotaExceeded(
                    f"tenant {self._tq.cfg.resolve(tenant)!r} is at "
                    f"{self._tq.rate(tenant, now):.1f} tokens/s, "
                    f"max {spec.max_rate:.1f}, with the engine under "
                    f"contention; back off until the window drains")
        if self.max_pending and len(self._pending) >= self.max_pending:
            if not self._free:
                raise QueueFull(
                    f"{len(self._pending)} requests already waiting "
                    f"(max_pending={self.max_pending}); shed load and "
                    f"retry")
            if self.paged and self._admit_blocked:
                # free slots exist but the queue head is waiting on
                # KV-block/HBM headroom: without this shed the pending
                # line would grow past max_pending unbounded whenever
                # memory (not slots) is the bottleneck
                raise QueueFull(
                    f"{len(self._pending)} requests already waiting "
                    f"(max_pending={self.max_pending}) on KV-block/HBM "
                    f"headroom; shed load and retry",
                    reason="hbm_admission")
        rid = self._next_rid
        self._next_rid += 1
        self._pending.append(_Request(
            rid, list(prompt), max_new_tokens,
            temperature=float(temperature), top_k=int(top_k),
            top_p=float(top_p),
            seed=(rid if seed is None else int(seed)) & 0xFFFFFFFF,
            cache_prefix=bool(cache_prefix) and self._prefix_max > 0,
            stop_tokens=tuple(int(t) for t in stop_tokens or ()),
            priority=int(priority),
            tenant=(str(tenant) if tenant else DEFAULT_TENANT),
            led=_Ledger(time.perf_counter()),
            deadline=(self._slack_clock() + float(deadline_s)
                      if deadline_s else None)))
        self._admit()
        return rid

    def _admit(self) -> None:
        self._admit_blocked = False
        if self._pending and self._free:
            # pipeline barrier: an admission install changes batch
            # composition, and un-consumed in-flight arrivals still
            # reference the OLD slot->request binding — flush them
            # before _install writes the new request's rows
            self._flush()
        while self._pending:
            if not self._free:
                # request-level quota reclaim: a guaranteed tenant
                # waiting with every slot busy may evict the most-
                # over-quota tenant's youngest slot (bit-exact, re-
                # enqueued under its own tenant's weight — never
                # killed). Without a tenant scheduler (or nothing to
                # reclaim) this is the old "queue waits for a
                # completion" behavior.
                if not self._reclaim_for(
                        self._pending[self._pick_pending()]):
                    break
                continue
            i = self._pick_pending()
            req = self._pending[i]
            if self.paged and not self._admit_headroom(req):
                # memory-aware admission: the picked head waits for
                # free-block headroom (or the HBM backstop) instead of
                # thrashing the pool — completions and preemptions
                # re-run this. A guaranteed tenant blocked on headroom
                # reclaims blocks the same way it reclaims slots.
                if self._reclaim_for(req):
                    continue
                self._admit_blocked = True
                break
            del self._pending[i]
            slot = self._free.popleft()
            req.slot = slot
            self._active[slot] = req
            # admitted-to-slot: prefill starts immediately (one-shot or
            # the first chunk of a chunked admission); a preempted
            # request resumes through restore/recompute instead
            req.led.t_admit = req.led.t_prefill_start = time.perf_counter()
            if req.swap_state is not None:
                self._resume_swapped(req)
            elif req.preempted:
                self._resume_recompute(req)
            else:
                self._prefill_slot(req)

    def _pick_pending(self) -> int:
        """Index of the next request to admit. FIFO without a tenant
        scheduler; with one, the weighted tenant pick — guaranteed
        (under-min) tenants first, then borrowers ordered so realized
        borrowing stays proportional to their guaranteed_overquotas
        shares, over-max tenants last (work conservation: they still
        admit when nobody else is waiting). Within a tenant, arrival
        order (a preempted request sits at the global front, so it is
        the first of its tenant by construction)."""
        if self._tq is None or len(self._pending) <= 1:
            return 0
        t = self._tq.pick((r.tenant for r in self._pending),
                          self._tq_clock())
        for i, r in enumerate(self._pending):
            if self._tq.cfg.resolve(r.tenant) == t:
                return i
        return 0

    def _reclaim_for(self, req: _Request) -> bool:
        """Preemptive quota reclaim for ``req``'s tenant (the ISSUE 13
        tentpole): when a GUARANTEED tenant (under its min token-rate)
        waits with no free slot or no block headroom, vacate the most-
        over-quota tenant's youngest slot through the existing
        bit-exact preemption machinery — swap or recompute per
        ``kv_swap``, re-enqueued at the front of the queue where the
        weighted pick re-admits it under its own tenant's weight the
        moment capacity allows. Never victimizes a tenant within its
        min, never the requester's own tenant, and only ever on a
        paged engine (slot-static engines have no preempt primitive).
        Returns True when it made progress (a preemption, or a flush
        that freed a slot), False when there is nothing to reclaim —
        the caller then falls back to waiting, exactly the pre-quota
        behavior."""
        if self._tq is None or not self.paged:
            return False
        now = self._tq_clock()
        if not self._tq.under_min(req.tenant, now):
            return False
        me = self._tq.cfg.resolve(req.tenant)

        def victims():
            pre = {e["req"].slot for e in self._prefilling}
            out = []
            for s, r in self._active.items():
                if s in pre or r.done or not r.out:
                    continue
                vt = self._tq.cfg.resolve(r.tenant)
                if vt == me or not self._tq.over_min(r.tenant, now):
                    continue
                out.append((s, r, vt))
            return out

        # pre-scan BEFORE paying the flush barrier: under sustained
        # guaranteed load with nothing preemptible (every slot
        # within-min — commonly the requester's own tenant), this runs
        # on every _admit, and flushing the in-flight window each time
        # would serialize the pipelined decode for nothing
        if not victims():
            return False
        free0 = len(self._free)
        self._flush()       # barrier: preemption needs a drained window
        if len(self._free) > free0:
            return True     # a late completion freed a slot: progress
        cands = victims()   # re-scan: the flush may have finished one
        if not cands:
            return False
        # most-over-quota tenant first (over-rate normalized by its
        # fair borrow share — the same fairness currency the pick
        # admits by), youngest slot within it (least sunk work lost to
        # the re-queue wait). ONE shares build for the whole ranking:
        # each build walks the QuotaInfos aggregates.
        shares = self._tq.borrow_shares(now)
        ratios = {vt: self._tq.over_quota_ratio(vt, now, shares)
                  for _, _, vt in cands}
        s, _r, _vt = max(
            cands, key=lambda c: (ratios[c[2]], c[1].led.t_admit))
        self._preempt_slot(s, "swap" if self.kv_swap else "recompute")
        self.tenant_reclaims += 1
        return True

    def _timed_dispatch(self, key: tuple, fn, *args):
        """Run ``fn`` and, on its FIRST call per shape ``key``, time it
        as a compile event: a jitted program traces + compiles
        synchronously inside that call, so the duration isolates XLA
        compile cost from steady-state dispatch. Steady-state calls pay
        one set lookup — nothing else."""
        if key in self._compiled:
            return fn(*args)
        t0 = time.perf_counter()
        out = fn(*args)
        dt = time.perf_counter() - t0
        self._compiled.add(key)
        self.compiles += 1
        self.compile_s += dt
        self.compile_events.append(dt)
        return out

    def _run_prefill(self, toks, row):
        """Prefill forward with compile accounting keyed by the shapes
        XLA keys on: (token bucket, scratch row length)."""
        return self._timed_dispatch(
            ("prefill", toks.shape[1], row["k"].shape[3]),
            self._prefill, self.params, toks, row)

    @functools.lru_cache(maxsize=None)      # noqa: B019 — engine-lived
    def _row_zeros(self, bucket: int):
        shape = list(self.cache["k"].shape)
        shape[1], shape[3] = 1, bucket
        # scratch rows stay cfg.dtype even over an int8 arena: prefill
        # math is full-precision, quantization happens at block install
        dtype = (self.cfg.dtype if self.kv_dtype == "int8"
                 else self.cache["k"].dtype)
        z = jnp.zeros(tuple(shape), dtype)
        if self._row_shd is not None:
            # scratch rows carry the same head sharding as the shared
            # cache: prefill runs sharded and _install never gathers
            z = jax.device_put(z, self._row_shd)
        return z

    def _prefix_scope(self, req: _Request) -> Optional[str]:
        """The prefix-cache partition this request may share KV with:
        its RESOLVED tenant under a tenant-scoped cache (the ISSUE 13
        default — cross-tenant KV sharing is a timing side-channel),
        one global scope (None) otherwise (no tenancy, or the
        operator's ``share_prefix`` opt-out for trusted fleets).
        Resolved, not raw: unknown labels fold into the default tenant
        exactly like their quota/metrics identity — matching the
        gateway's affinity-key scoping (so the cache hits its routing
        colocates actually exist) and keeping scope cardinality
        operator-bounded rather than client-minted."""
        if not self._prefix_scoped:
            return None
        return self._tq.cfg.resolve(req.tenant)

    def _prefix_match(self, prompt: List[int],
                      scope: Optional[str] = None):
        """Pure lookup: (m, entry_key) for the longest common HEAD
        between ``prompt`` and any cached entry in ``scope`` — a
        partial entry match
        reuses the entry's first m KV rows (valid on their own: they are
        exactly positions 0..m), so an identical prompt resubmit reuses
        plen-1 of itself and a longer cached prompt still serves its
        shared head. Capped at plen-1: at least one suffix token must run
        to produce the next token's logits. No side effects — the caller
        decides whether the match is actually USED (fit + profitability)
        before stats and LRU order move. Linear scan: the cache is
        operator-capped small (system prompts, not pages)."""
        cap = len(prompt) - 1
        best, best_key = 0, None
        for key in self._prefixes:
            if key[0] != scope:
                continue        # another tenant's prefix: invisible
            m = 0
            for a, b in zip(key[1], prompt[:cap]):
                if a != b:
                    break
                m += 1
            if m > best:
                best, best_key = m, key
        return best, best_key

    def _publish_prefix(self, prompt: List[int], rk, rv,
                        scope: Optional[str] = None) -> None:
        """Store this prompt's KV rows as a reusable prefix in
        ``scope`` (trimmed to the exact prompt length), evicting
        least-recently-used entries past the cap."""
        key = (scope, tuple(prompt))
        plen = len(prompt)
        # pop-then-set: dict assignment to an existing key keeps its OLD
        # insertion position, and a just-republished hot prefix must not
        # sit first in line for eviction
        self._prefixes.pop(key, None)
        self._prefixes[key] = (rk[:, :, :, :plen, :], rv[:, :, :, :plen, :])
        while len(self._prefixes) > self._prefix_max:
            self._prefixes.pop(next(iter(self._prefixes)))

    def _prefill_slot(self, req: _Request) -> None:
        """Prefill the prompt over a bucket-sized scratch cache (cost
        proportional to the request), then install the rows + position
        into the shared cache in one donated jitted update. A cached
        prefix skips its share of the forward: its KV rows are written
        into the scratch cache and only the suffix tokens run. With
        ``prefill_chunk`` set and a suffix longer than one chunk, the
        forwards are deferred to step() one chunk at a time instead
        (_start_chunked_prefill) — admission costs the host only the
        scratch allocation."""
        if self.paged:
            return self._paged_prefill_slot(req)
        plen = len(req.prompt)
        m, mkey = (self._prefix_match(req.prompt,
                                      self._prefix_scope(req))
                   if self._prefixes else (0, None))
        if self._prefill_chunk and self._start_chunked_prefill(
                req, m, mkey):
            return
        # fit: the suffix's padded bucket must land inside max_len after
        # the prefix (forward_with_cache writes the whole bucket at pos
        # m, and dynamic_update_slice CLAMPS an overrunning start — which
        # would silently overwrite the prefix KV). Shrink m instead of
        # discarding the match: a 400-token reuse trimmed to 384 beats
        # zero. _bucket(plen - m) grows as m shrinks, so iterate.
        while m > 0 and m + _bucket(plen - m) > self.max_len:
            m = max(0, self.max_len - _bucket(plen - m))
        # profitability: reuse must make the suffix forward strictly
        # cheaper than full prefill (fewer query tokens per bucket tier),
        # or a trivial shared head (e.g. a lone BOS token) would route
        # every request through the prefix path — extra copies, same
        # compute — while the metrics report savings
        if m > 0 and _bucket(plen - m) >= _bucket(plen):
            m = 0
        sbucket = _bucket(plen - m)
        if m > 0:
            self._prefixes[mkey] = self._prefixes.pop(mkey)   # LRU refresh
            self.prefix_hits += 1
            self.prefix_tokens_saved += m
        else:
            mkey = None
        # scratch sized so prefix + padded suffix both fit (≥ the plen
        # bucket: _install expects rows at least plen long)
        bucket = min(_bucket(max(plen, m + sbucket)), self.max_len)
        row = {
            "k": self._row_zeros(bucket),
            "v": self._row_zeros(bucket),
            "pos": jnp.zeros((), jnp.int32),
        }
        if m > 0:
            pk, pv = self._prefixes[mkey]
            row["k"] = jax.lax.dynamic_update_slice(
                row["k"], pk[:, :, :, :m, :], (0, 0, 0, 0, 0))
            row["v"] = jax.lax.dynamic_update_slice(
                row["v"], pv[:, :, :, :m, :], (0, 0, 0, 0, 0))
            row["pos"] = jnp.int32(m)
            suffix = req.prompt[m:]
            toks = jnp.asarray(
                [suffix + [0] * (sbucket - len(suffix))], jnp.int32)
            logits, row = self._run_prefill(toks, row)
            step = logits[0, len(suffix) - 1]
        else:
            # pad to the row length (not the raw bucket): _bucket can
            # round past max_len and the write must fit the scratch
            toks = jnp.asarray(
                [req.prompt + [0] * (bucket - plen)], jnp.int32)
            logits, row = self._run_prefill(toks, row)
            step = logits[0, plen - 1]
        self._chip_add(req.tenant, "prefill", plen - m)
        self._finish_prefill(req, row, step)

    def _start_chunked_prefill(self, req: _Request, m: int,
                               mkey) -> bool:
        """Queue ``req`` for chunk-at-a-time prefill (step() drives it).
        Returns False to fall back to the one-shot path when chunking
        buys nothing (suffix fits one chunk) or the chunk-padded span
        cannot fit ``max_len`` (non-power-of-two max_len edge)."""
        chunk = self._prefill_chunk
        plen = len(req.prompt)

        def span(m_: int) -> int:
            # last chunk pads to its own bucket (<= chunk: both are
            # powers of two), full chunks are exact
            full, rem = divmod(plen - m_, chunk)
            return m_ + full * chunk + (_bucket(rem) if rem else 0)

        # profitability (same invariant as the one-shot path): the reuse
        # must save at least one chunk forward, or a trivial shared head
        # does extra copies for the same compute while the metrics
        # report savings. Checked before fit-shrink: shrinking only
        # lowers m, which never makes an unprofitable match profitable.
        if m > 0 and -(-(plen - m) // chunk) >= -(-plen // chunk):
            m = 0
        # fit: same contract as the one-shot path — a clamped
        # dynamic_update_slice must never overwrite prefix KV
        guard = 0
        while m > 0 and span(m) > self.max_len and guard < 64:
            m = max(0, self.max_len - (span(m) - m))
            guard += 1
        if plen - m <= chunk or span(m) > self.max_len:
            return False
        if m > 0:
            self._prefixes[mkey] = self._prefixes.pop(mkey)   # LRU refresh
            self.prefix_hits += 1
            self.prefix_tokens_saved += m
        bucket = min(_bucket(max(plen, span(m))), self.max_len)
        row = {
            "k": self._row_zeros(bucket),
            "v": self._row_zeros(bucket),
            "pos": jnp.int32(m),
        }
        if m > 0:
            pk, pv = self._prefixes[mkey]
            row["k"] = jax.lax.dynamic_update_slice(
                row["k"], pk[:, :, :, :m, :], (0, 0, 0, 0, 0))
            row["v"] = jax.lax.dynamic_update_slice(
                row["v"], pv[:, :, :, :m, :], (0, 0, 0, 0, 0))
        suffix = req.prompt[m:]
        todo = deque(suffix[i:i + chunk]
                     for i in range(0, len(suffix), chunk))
        self._prefilling.append({"req": req, "row": row, "todo": todo})
        return True

    def _prefill_tick(self) -> int:
        """Advance the head prefilling request by one tick; when its
        chunks are exhausted, finish admission (first token + install).
        Returns tokens emitted (1 on completion, else 0). The legacy
        unbudgeted rule — _prefill_sched delegates here when
        prefill_budget is 0."""
        return self._advance_entry(0)

    def _advance_entry(self, idx: int) -> int:
        """Run ONE timed chunk forward for ``self._prefilling[idx]``
        (the measurement feeds the budget scheduler's cost model),
        retiring the entry through _finish_prefill when its chunks are
        exhausted. Returns tokens emitted (1 on completion, else 0)."""
        ent = self._prefilling[idx]
        cost = self._chunk_cost(ent)
        t0 = time.perf_counter()
        done = self._prefill_advance(ent)
        dt = time.perf_counter() - t0
        self.prefill_chunk_tokens += cost
        self._chip_add(ent["req"].tenant, "prefill", cost)
        if cost > 0:
            self._chunk_tok_s.append(dt / cost)
        if not done:
            return 0
        del self._prefilling[idx]
        self._finish_prefill(ent["req"], ent["row"], ent["step"])
        return 1

    def _chunk_cost(self, ent: dict) -> int:
        """Prompt tokens the entry's NEXT chunk forward will process —
        the unit the per-tick budget is denominated in. Subclasses
        whose entries carry sibling chunk queues (the speculative
        draft) override so the cost stays defined until the whole
        entry retires."""
        return len(ent["todo"][0])

    def _prefill_remaining(self, ent: dict) -> int:
        """Prompt tokens still to prefill for the entry — the work
        term of its TTFT-slack estimate."""
        return sum(len(c) for c in ent["todo"])

    def note_tick_seconds(self, seconds: float) -> None:
        """Feed one measured decode-tick duration into the rolling
        TPOT cost model (the serving loop calls this with its
        tick-phase profiler's totals; plain step() callers
        self-measure on compile-free ticks)."""
        if seconds > 0:
            self._tick_s.append(seconds)

    def _est_prefill_tok_s(self) -> float:
        """Estimated seconds per prefill prompt-token: the pinned hint
        when a bench/test set one, else the rolling-window median —
        0.0 until the first chunk forward lands (a cold model means
        slack checks stand down rather than guess)."""
        if self.prefill_tok_s_hint is not None:
            return self.prefill_tok_s_hint
        if not self._chunk_tok_s:
            return 0.0
        s = sorted(self._chunk_tok_s)
        return s[len(s) // 2]

    def _est_tick_s(self) -> float:
        """Estimated seconds per decode tick (the TPOT cost model):
        hint, else rolling median, else 0.0 (stand down)."""
        if self.tick_s_hint is not None:
            return self.tick_s_hint
        if not self._tick_s:
            return 0.0
        s = sorted(self._tick_s)
        return s[len(s) // 2]

    def prefill_backlog(self) -> int:
        """Prompt tokens queued in chunked-prefill entries — what a
        fresh admission must wait behind under a per-tick budget."""
        return sum(self._prefill_remaining(e) for e in self._prefilling)

    def prefill_backlog_s(self) -> float:
        """Estimated seconds of chunk-forward work in the prefill
        backlog (0.0 when idle or the cost model is cold): the serving
        loop adds this to its admission-time completion estimate so a
        deadline that cannot survive the chunk queue ahead of it sheds
        at submit — the earliest layer that can know."""
        return self.prefill_backlog() * self._est_prefill_tok_s()

    def _ttft_slack(self, ent: dict, now: float, tok_s: float) -> float:
        """Seconds of slack before the entry's deadline assuming its
        remaining chunks ran back-to-back; +inf with no deadline (so
        deadline-less work sorts last, FIFO-stable)."""
        req = ent["req"]
        if req.deadline is None:
            return float("inf")
        return (req.deadline - now) \
            - self._prefill_remaining(ent) * tok_s

    def _prefill_sched(self) -> int:
        """One tick of budgeted chunked prefill. With no budget
        configured, the legacy unconditional rule: exactly one chunk
        for the head entry. With one, spend at most ``prefill_budget``
        prompt tokens (plus accrued credit) on chunk forwards this
        tick, advancing entries in EDF order on estimated TTFT slack;
        clamp to zero when any decode slot's TPOT slack is negative;
        allow ONE over-budget chunk when the most urgent prefill's
        TTFT slack is inside one tick. The scheduler chooses only WHEN
        chunks run — their contents, the order within a request, and
        the forwards themselves are exactly the unbudgeted ones, so
        outputs stay token-identical to the unbudgeted run."""
        if not self._prefilling:
            return 0
        if self.prefill_budget <= 0:
            return self._prefill_tick()
        now = self._slack_clock()
        tok_s = self._est_prefill_tok_s()
        tick_s = self._est_tick_s()
        budget = float(self.prefill_budget)
        decode_slots = [s for s in self._active_slots()
                        if not self._active[s].done]
        if tick_s > 0:
            for s in decode_slots:
                r = self._active[s]
                if r.deadline is None:
                    continue
                rem_out = max(0, r.max_new_tokens - len(r.out))
                if (r.deadline - now) - rem_out * tick_s < 0:
                    # a decode slot is already out of TPOT slack:
                    # every chunk forward now widens its inter-token
                    # gaps further — decode drains first, prefill
                    # rides on whatever credit it accrued
                    budget = 0.0
                    self.prefill_budget_clamped += 1
                    break
        clamped = budget == 0.0
        # unspent budget accrues as credit, capped so a long idle
        # stretch cannot bank an unbounded prefill burst; the cap
        # covers the largest chunk so a chunk bigger than the per-tick
        # budget still advances every ceil(chunk/budget) ticks
        cap = float(max(self.prefill_budget, self._prefill_chunk))
        self._prefill_credit = min(self._prefill_credit + budget, cap)
        emitted = 0
        advanced = 0
        overrode = False
        while self._prefilling:
            # re-rank every iteration: _finish_prefill can recursively
            # admit a NEW chunked entry, and slack shifts as work runs
            idx = min(range(len(self._prefilling)),
                      key=lambda i: (self._ttft_slack(
                          self._prefilling[i], now, tok_s), i))
            cost = self._chunk_cost(self._prefilling[idx])
            if self._prefill_credit >= cost:
                self._prefill_credit -= cost
                self.prefill_budget_spent += cost
            elif (not clamped and not overrode
                  and self._ttft_slack(self._prefilling[idx], now,
                                       tok_s) < max(tick_s, 0.0)):
                # TTFT-critical overdraw: this prefill's deadline dies
                # within ~one tick of waiting — exceed the budget for
                # ONE chunk and pay it back (credit goes negative)
                self._prefill_credit -= cost
                self.prefill_budget_spent += cost
                self.prefill_budget_overrides += 1
                overrode = True
            elif advanced == 0 and not decode_slots:
                # liveness: nothing decodable and no credit banked —
                # an idle engine must still make prefill progress
                # (drain() would otherwise spin forever). One free
                # advance per tick, no budget charge: exactly the
                # legacy pace.
                pass
            else:
                break
            emitted += self._advance_entry(idx)
            advanced += 1
        return emitted

    def _prefill_advance(self, ent: dict) -> bool:
        """Run ONE chunk forward for ``ent``; on the final chunk, store
        the last real position's logits in ``ent["step"]`` and return
        True (entry fully prefilled). Subclasses extend this to advance
        sibling caches (speculative draft) in the same tick."""
        toks_list = ent["todo"].popleft()
        rem = len(toks_list)
        rbucket = _bucket(rem) if not ent["todo"] else rem
        toks = jnp.asarray([toks_list + [0] * (rbucket - rem)], jnp.int32)
        logits, ent["row"] = self._run_prefill(toks, ent["row"])
        if ent["todo"]:
            return False
        ent["step"] = logits[0, rem - 1]
        return True

    def _finish_prefill(self, req: _Request, row: Cache,
                        step: jax.Array, *,
                        installed: bool = False) -> None:
        """Shared admission tail: publish the prefix, pick the first
        token from the final-position logits, set the slot's sampling
        rows, and install the prefilled KV into the shared cache
        (``installed=True`` — the in-arena kernel prefill — means the
        KV already lives in the arena; only the table/pos/feed state
        and the publish remain)."""
        plen = len(req.prompt)
        if req.cache_prefix and not self.paged:
            # paged publish happens in _paged_install, where the slot's
            # block table (the thing being shared) exists
            self._publish_prefix(req.prompt, row["k"], row["v"],
                                 self._prefix_scope(req))
        if self.mesh is not None:
            # the first-token decision runs EAGERLY on this row: under
            # a mesh it would otherwise execute on the vocab-sharded
            # layout the unembed left it in, where categorical's RNG
            # draws different bits than the single-host run (the
            # decode program's replicated_logits twin, eager form)
            step = jax.device_put(step.astype(jnp.float32), self._rep)
        if req.temperature > 0:
            # token at absolute index plen: same (seed, index) keying as
            # the decode program, so prefill vs decode is seamless
            key = jax.random.fold_in(
                jax.random.PRNGKey(jnp.uint32(req.seed)), plen)
            trunc = _truncate_logits_rows(
                (step / max(req.temperature, 1e-6))[None, :],
                jnp.asarray([req.top_k], jnp.int32),
                jnp.asarray([req.top_p], jnp.float32))
            first = int(jax.random.categorical(key, trunc[0]))
        else:
            first = int(jnp.argmax(step))
        self._set_sampling_rows(req)
        # padding garbage past plen stays masked until overwritten: only
        # pos decides what exists
        if self.paged:
            self._paged_install(req, row, plen, first,
                                installed=installed)
        else:
            self.cache, self._last = self._install(
                self.cache, row["k"], row["v"], jnp.int32(req.slot),
                jnp.int32(plen), jnp.int32(first), self._last)
        req.out.append(first)
        req.note_token()
        self._note_tenant_tokens(req, 1)
        # the first token is observed HERE (the argmax/sample above was
        # a host sync): TTFT's far stamp, and the TPOT clock's arm
        req.led.t_prefill_end = req.led.t_first = req.led.t_last = \
            time.perf_counter()
        if self.role == "prefill" and not req.done:
            # disaggregated serving: a prefill-role engine never
            # decodes — the request leaves NOW as a KV handoff payload
            # (its prompt KV + first token), and the freed slot admits
            # the next prefill
            return self._handoff_slot(req)
        self._finish_if_done(req)

    def _note_tenant_tokens(self, req: _Request, n: int) -> None:
        """Tenant token-rate accounting — the currency the weighted
        pick, max-rate sheds and reclaim all decide on. One scheduler
        note per arrival (not per token), same cost discipline as the
        latency ledger."""
        if self._tq is not None and n:
            self._tq.note_tokens(req.tenant, n, self._tq_clock())

    def _chip_add(self, tenant: Optional[str], phase: str,
                  n: int) -> None:
        """Accumulate ``n`` tokens of structural work weight for this
        quantum's attribution split (ISSUE 20): decode tokens emitted
        per slot (batch-share weighting) and prefill prompt-tokens
        advanced, both charged to the RESOLVED tenant. One dict-add per
        arrival/chunk when SLO accounting is on; one attribute check
        when off."""
        if self.chip is None or n <= 0:
            return
        key = (self._tq.cfg.resolve(tenant), phase)
        self._chip_work[key] = self._chip_work.get(key, 0) + n

    def _chip_kv_bytes(self) -> Dict[str, int]:
        """Resident HBM KV bytes per tenant, from the paged arena's
        refcounts: each active slot's block table charges its resolved
        tenant; prefix chains held by the index charge their scope (or
        ``_shared`` for an unscoped cache). Charging is per REFERENCE —
        a copy-on-write-shared block charges every holder, the same
        convention the arena's own occupancy accounting uses. Empty for
        slot-static engines (fixed allocation, not per-tenant)."""
        if not self.paged:
            return {}
        nb = self._chain_block_nbytes()
        out: Dict[str, int] = {}
        for s, req in self._active.items():
            blocks = len(self._tables[s]) if s < len(self._tables) else 0
            if blocks:
                t = self._tq.cfg.resolve(req.tenant)
                out[t] = out.get(t, 0) + nb * blocks
        if self._pindex is not None:
            for (scope, _toks), blocks in self._pindex.chain_items():
                t = scope if scope is not None else "_shared"
                out[t] = out.get(t, 0) + nb * len(blocks)
        return out

    def chip_note_quantum(self, t0: float, t1: float) -> None:
        """Charge one engine quantum ``[t0, t1]`` to the attribution
        ledger, draining the accumulated token weights — the serving
        loop calls this with the SAME two tick-profiler clock reads it
        already pays for (one-clock-read discipline: the ledger adds no
        timer of its own); library step() self-charges. No-op when SLO
        accounting is off."""
        if self.chip is None:
            return
        work = self._chip_work
        self._chip_work = {}
        self.chip.note_quantum(t0, t1, work, self._chip_kv_bytes())

    def _finish_if_done(self, req: _Request, admit: bool = True) -> None:
        """Completion + slot recycling. Resetting the slot's per-row pos
        is the pipeline ROLLBACK: a completion observed up to
        pipeline_depth ticks late (or mid-way through a fused
        decode_steps burst) has over-decoded past the true length, but
        only pos decides what exists — the truncated host output plus
        this reset discard the overrun by construction. ``admit=False``
        is the arrival-consumption path: admission is a pipeline barrier
        and must not re-enter the flush that is consuming this arrival —
        the caller admits once, after the window drains."""
        if req.done and req.slot >= 0:
            s = req.slot
            del self._active[s]
            if self.paged:
                self._free_slot_blocks(s)
            self.cache["pos"] = self.cache["pos"].at[s].set(0)
            self._free.append(s)
            req.slot = -1
            self._done[req.rid] = req
            self._record_ledger(req)
            if not self._active:
                # nothing left to decode: stop the dispatch-gap clock —
                # an idle engine is not host-blocked, and a stale mark
                # would book the whole idle period against the next
                # serving burst's first dispatch
                self._idle_since = None
            if admit:
                self._admit()

    def _record_ledger(self, req: _Request,
                       outcome: Optional[str] = None) -> None:
        """Close the request's ledger and park the snapshot for
        pop_ledger/drain_ledgers. FIFO-capped: a caller that never
        collects ledgers (library use, benches between fences) must not
        grow the engine unboundedly."""
        led = req.led
        if outcome is not None and led.outcome is None:
            led.outcome = outcome
        led.t_done = time.perf_counter()
        self._ledgers[req.rid] = led.snapshot(req)
        while len(self._ledgers) > self.ledger_cap:
            del self._ledgers[next(iter(self._ledgers))]

    def pop_ledger(self, rid: int) -> Optional[dict]:
        """The finished request's latency ledger (see _Ledger.snapshot),
        handed out exactly once — the serving loop pops it alongside
        pop_result to feed the TTFT/TPOT/queue/e2e histograms. None
        while the request is still running (or already popped)."""
        return self._ledgers.pop(rid, None)

    def drain_ledgers(self) -> List[dict]:
        """All uncollected finished-request ledgers, cleared — the
        bench-harness bulk read."""
        out = list(self._ledgers.values())
        self._ledgers.clear()
        return out

    # ------------------------------------------------------------------
    # paged KV subsystem (kv_blocks > 0): block-table admission,
    # COW fork, memory-aware pressure relief (flush -> prefix eviction
    # -> preemption by swap or recompute). All host bookkeeping lives
    # here; the device side is forward_paged's gather/scatter.
    # ------------------------------------------------------------------
    def _paged_prefill_slot(self, req: _Request) -> None:
        """Paged admission: prefill runs over the SAME contiguous
        scratch row as the slot-static path (identical compiled
        programs, identical numerics), then lands block-by-block in the
        arena. A block-granular prefix match skips both the shared
        head's compute (suffix-only forward) and its storage (the
        matched blocks are refcount-shared, not copied)."""
        bs = self.kv_block_size
        plen = len(req.prompt)
        m, mkey = (self._pindex.match(req.prompt, plen - 1,
                                      self._prefix_scope(req))
                   if self._pindex is not None else (0, None))
        if self._host_tier is not None:
            # an HBM miss (or a shorter HBM hit) may still be a host-
            # tier hit: promote the demoted chain back into the arena
            # before the profitability/fit checks judge the match
            m, mkey = self._promote_from_host(req, m, mkey, plen)
        # profitability: block reuse must also save prefill compute
        # (fewer query tokens per bucket tier) — same invariant as the
        # slot-static prefix path
        if m > 0 and _bucket(plen - m) >= _bucket(plen):
            m = 0
        # fit: prefix + padded suffix must land inside max_len; shrink
        # by whole blocks (a partial block cannot be shared)
        guard = 0
        while m > 0 and m + _bucket(plen - m) > self.max_len \
                and guard < 64:
            m = (max(0, self.max_len - _bucket(plen - m)) // bs) * bs
            guard += 1
        if m > 0 and m + _bucket(plen - m) > self.max_len:
            m = 0
        if self._prefill_chunk and plen - m > self._prefill_chunk \
                and self._paged_start_chunked(req, m, mkey):
            return
        sbucket = _bucket(plen - m)
        # scratch rounded up to the block size so whole blocks copy out
        bucket = min(max(_bucket(max(plen, m + sbucket)), bs),
                     self.max_len)
        shared = self._pindex.take(mkey, m) if m > 0 else []
        req.shared_blocks = shared
        self._sync_prefix_stats()
        if m > 0 and self.paged_kernel == "kernel":
            # with the fused kernel, a prefix-hit suffix prefills on
            # the paged formulation IN the arena: the S>1 kernel window
            # attends over the shared head through the block table, so
            # the scratch row, its _seed_scratch block copies and the
            # install pass all disappear. The dense scratch path
            # remains for m == 0 (no shared head to read through a
            # table) and for the gather formulation.
            return self._paged_prefill_in_arena(req, m, sbucket)
        row = {"k": self._row_zeros(bucket), "v": self._row_zeros(bucket),
               "pos": jnp.int32(m)}
        if m > 0:
            row = self._seed_scratch(row, shared)
            suffix = req.prompt[m:]
            toks = jnp.asarray(
                [suffix + [0] * (sbucket - len(suffix))], jnp.int32)
            logits, row = self._run_prefill(toks, row)
            step = logits[0, len(suffix) - 1]
        else:
            toks = jnp.asarray(
                [req.prompt + [0] * (bucket - plen)], jnp.int32)
            logits, row = self._run_prefill(toks, row)
            step = logits[0, plen - 1]
        self._chip_add(req.tenant, "prefill", plen - m)
        self._finish_prefill(req, row, step)

    def _paged_prefill_in_arena(self, req: _Request, m: int,
                                sbucket: int) -> None:
        """Prefix-hit admission through the fused kernel: allocate the
        slot's full block table up front (shared prefix entries + fresh
        suffix blocks — the chunked path's reservation discipline),
        then run ONE bucketed S>1 window of the kernel program over a
        1-row cache view at pos=m: the ``_replay_committed`` template,
        one window wide. K/V scatter lands directly in the fresh blocks
        (quantizing on write under int8, exactly like decode steps);
        attention reads the shared head through the scalar-prefetched
        in-kernel table walk instead of re-attending over a dense
        scratch copy. Padding past the suffix routes to the null block
        or to masked tail positions — the same only-``pos``-decides-
        what-exists invariant the scratch row relies on."""
        bs = self.kv_block_size
        plen = len(req.prompt)
        s = req.slot
        shared = req.shared_blocks
        n_total = blocks_for(plen, bs)
        table = shared + self._alloc.alloc_many(n_total - len(shared))
        self._tables[s] = table
        self._set_table_row(s)
        if self._scales is not None:
            for blk in table[len(shared):]:
                self._scales.note_write(blk)
        suffix = req.prompt[m:]
        toks = jnp.asarray(
            [suffix + [0] * (sbucket - len(suffix))], jnp.int32)
        cache = {k: v for k, v in self.cache.items() if k != "pos"}
        cache["pos"] = jnp.asarray([m], jnp.int32)
        logits, cache = self._timed_dispatch(
            ("prefill_arena", sbucket), self._replay_step, self.params,
            toks, cache, self._table[s:s + 1])
        for key in self.cache:
            if key != "pos":
                self.cache[key] = cache[key]
        step = logits[0, len(suffix) - 1]
        req.reserved_blocks = table
        self._chip_add(req.tenant, "prefill", len(suffix))
        self._finish_prefill(req, None, step, installed=True)

    def _paged_start_chunked(self, req: _Request, m: int, mkey) -> bool:
        """Chunk-at-a-time admission under paging. The slot's FULL
        block table is reserved here (shared prefix + fresh blocks):
        prefill spans several ticks during which other slots grow, and
        an install that discovered an empty pool mid-admission would
        have no good answer. False falls back to the one-shot path."""
        bs = self.kv_block_size
        chunk = self._prefill_chunk
        plen = len(req.prompt)
        suffix = plen - m
        full, rem = divmod(suffix, chunk)
        span = m + full * chunk + (_bucket(rem) if rem else 0)
        bucket = min(max(_bucket(max(plen, span)), bs), self.max_len)
        if suffix <= chunk or span > bucket:
            return False
        shared = self._pindex.take(mkey, m) if m > 0 else []
        try:
            fresh = self._alloc.alloc_many(
                blocks_for(plen, bs) - len(shared))
        except NoFreeBlocks:
            for b in shared:            # undo the claim, fall back
                self._alloc.decref(b)
            if m > 0:
                # roll the hit stats back too: the one-shot fallback
                # will take() again — one admission, one hit
                self._pindex.hits -= 1
                self._pindex.tokens_saved -= m
            return False
        req.shared_blocks = shared
        req.reserved_blocks = shared + fresh
        self._sync_prefix_stats()
        row = {"k": self._row_zeros(bucket), "v": self._row_zeros(bucket),
               "pos": jnp.int32(m)}
        if m > 0:
            row = self._seed_scratch(row, shared)
        tail = req.prompt[m:]
        todo = deque(tail[i:i + chunk] for i in range(0, suffix, chunk))
        self._prefilling.append({"req": req, "row": row, "todo": todo})
        return True

    def _seed_scratch(self, row: dict, shared: List[int]) -> dict:
        """Copy a shared prefix's arena blocks into the scratch row so
        the suffix forward attends to the reused KV — the paged twin of
        the slot-static path's prefix-row copy."""
        bs = self.kv_block_size
        rk, rv = row["k"], row["v"]
        for j, phys in enumerate(shared):
            rk, rv = self._timed_dispatch(
                ("scratchblk", rk.shape[3]), self._scratch_block,
                rk, rv, self.cache, jnp.int32(phys), jnp.int32(j * bs))
        row["k"], row["v"] = rk, rv
        return row

    def _paged_install(self, req: _Request, row: Cache, plen: int,
                       first: int, *, installed: bool = False) -> None:
        """Admission tail under paging: land the prefilled scratch row
        in the arena block-by-block (shared prefix blocks are table
        entries, not copies), set the device table row and the slot's
        pos/feed token, and publish a cache_prefix prompt's full blocks
        for block-granular reuse. ``installed=True`` skips the
        block-install pass: the in-arena kernel prefill scattered the
        suffix KV straight into its (pre-reserved) blocks."""
        bs = self.kv_block_size
        shared = req.shared_blocks
        req.shared_blocks = []
        n_total = blocks_for(plen, bs)
        if req.reserved_blocks is not None:     # chunked admission
            table = req.reserved_blocks
            req.reserved_blocks = None
        else:
            table = shared + self._alloc.alloc_many(
                n_total - len(shared))
        if not installed:
            for j in range(len(shared), n_total):
                self.cache = self._timed_dispatch(
                    ("installblk", row["k"].shape[3]),
                    self._install_block,
                    self.cache, row["k"], row["v"], jnp.int32(table[j]),
                    jnp.int32(j * bs))
                if self._scales is not None:
                    self._scales.note_write(table[j])
        s = req.slot
        self._tables[s] = table
        self._set_table_row(s)
        self.cache, self._last = self._set_row_state(
            self.cache, self._last, jnp.int32(s), jnp.int32(plen),
            jnp.int32(first))
        if req.cache_prefix and self._pindex is not None:
            self._pindex.publish(req.prompt, table,
                                 self._prefix_scope(req))
            self._sync_prefix_stats()

    def _set_table_row(self, slot: int) -> None:
        """Mirror one slot's host block table into the device table
        (unassigned logical blocks -> the reserved null block 0)."""
        row = np.zeros((self._nbs,), np.int32)
        blocks = self._tables[slot]
        row[:len(blocks)] = blocks
        self._table = self._table.at[slot].set(jnp.asarray(row))

    def _sync_prefix_stats(self) -> None:
        if self._pindex is not None:
            self.prefix_hits = self._pindex.hits
            self.prefix_tokens_saved = self._pindex.tokens_saved

    def _free_slot_blocks(self, slot: int) -> None:
        """Release a finished/cancelled slot's block references. With
        decode ticks still in flight the frees PARK (_deferred): those
        ticks' in-graph writes still target this table, and a block
        re-allocated to a new owner before the window drains would be
        cross-corrupted. Barriers and window-drain land them."""
        table = self._tables[slot]
        self._tables[slot] = []
        if self._inflight:
            self._deferred.extend(table)
        else:
            for b in table:
                self._alloc.decref(b)

    def _drain_deferred(self) -> None:
        if self._deferred and not self._inflight:
            for b in self._deferred:
                self._alloc.decref(b)
            self._deferred.clear()

    def _hbm_sample(self) -> Optional[dict]:
        """device.memory_stats() snapshot at admission-decision time —
        the live-gauge backstop the ISSUE asks for, throttled to 2 Hz
        so a blocked admission retried every tick stays cheap. Guarded:
        backends without memory stats (CPU) disable themselves."""
        if self._hbm_dead:
            return self.hbm
        now = time.perf_counter()
        if self.hbm is not None and now < self._hbm_next:
            return self.hbm
        self._hbm_next = now + 0.5
        try:
            d = jax.devices()[0]
            stats = d.memory_stats() or {}
        except Exception:
            self._hbm_dead = True
            return self.hbm
        in_use = stats.get("bytes_in_use")
        limit = stats.get("bytes_limit") \
            or stats.get("bytes_reservable_limit")
        if in_use is None:
            self._hbm_dead = True
            return self.hbm
        self.hbm = {"device": f"{d.platform}:{d.id}",
                    "in_use": int(in_use), "limit": int(limit or 0)}
        return self.hbm

    def _admit_headroom(self, req: _Request) -> bool:
        """Memory-aware admission: the pending head enters only when
        the pool holds its install blocks plus one block of growth
        headroom (capped at its full-length need, so a maximal request
        is not starved), and the HBM gauges say the device itself has
        room. With no slot decoding, cached prefixes are evicted rather
        than deadlocking the queue."""
        bs = self.kv_block_size
        plen = len(req.prompt)
        cap_blocks = blocks_for(plen + req.max_new_tokens - 1, bs)
        if req.swap_state is not None:
            base_need = req.swap_state["nblk"]
        elif req.preempted:
            base_need = blocks_for(plen + len(req.out) - 1, bs)
        else:
            base_need = blocks_for(plen, bs)
        need = min(base_need + 1, max(base_need, cap_blocks))
        hbm = self._hbm_sample()
        if self.hbm_admit_frac and hbm and hbm.get("limit") \
                and hbm["in_use"] / hbm["limit"] > self.hbm_admit_frac:
            return False
        if need <= self._alloc.free_count:
            return True
        if self._pindex is not None:
            # cached prefixes are the cheapest memory (the same rank
            # _relieve_pressure uses): reclaim them for a waiting
            # request rather than stalling it behind live decoders —
            # and with NO slot decoding, nothing else will ever free a
            # block, so this is also the deadlock breaker
            self._pindex.evict_lru(need - self._alloc.free_count)
            return need <= self._alloc.free_count
        return False

    def _dispatch_span(self) -> int:
        """Max KV positions ONE decode dispatch writes per slot —
        ``decode_steps`` for the plain engine; the speculative engine
        overrides with ``decode_steps * n_draft`` (each fused round
        writes a whole verify window before rolling back by pos)."""
        return self.decode_steps

    def _ensure_blocks(self, active: List[int]) -> None:
        """Pre-dispatch block discipline: every decodable slot's next
        ``_dispatch_span()`` write positions (beyond what in-flight
        ticks already cover) must land in blocks it owns EXCLUSIVELY —
        growth allocates, shared blocks COW-copy (the copy op is
        enqueued after the in-flight writes it must include; single-
        device dispatch order makes that exact). Positions past the
        request's terminal length stay unallocated: the zero table
        entry routes those overrun writes to the null block. Raises
        NoFreeBlocks under pool pressure."""
        T = self._dispatch_span()
        for s in active:
            req = self._active[s]
            base = len(req.prompt) + len(req.out) - 1
            pending = sum(1 for ent in self._inflight
                          if not ent.consumed and s in ent.slots)
            start = base + pending * T
            cap = len(req.prompt) + req.max_new_tokens - 1
            end = min(start + T, cap)
            if start >= end:
                # only overrun writes left: past max_len they null-route
                # (paged_scatter_kv), within the table they overwrite
                # positions >= cap that every reader rewrites before
                # reading — either way, no committed KV is reachable
                continue
            self._grow_slot_blocks(s, start, end)

    def _grow_slot_blocks(self, s: int, start: int, end: int) -> None:
        """Make slot ``s`` own every block covering write positions
        [start, end) exclusively: COW-copy shared blocks, allocate
        growth. The speculative engine extends this to grow the draft
        table over the same span (draft and target timelines advance
        in lockstep)."""
        bs = self.kv_block_size
        table = self._tables[s]
        changed = False
        for j in range(start // bs, (end - 1) // bs + 1):
            if j < len(table):
                if not self._alloc.writable(table[j]):
                    fresh = self._alloc.alloc()
                    self.cache = self._timed_dispatch(
                        ("cowblk",), self._cow_block, self.cache,
                        jnp.int32(table[j]), jnp.int32(fresh))
                    if self._scales is not None:
                        self._scales.note_copy(table[j], fresh)
                    self._alloc.decref(table[j])
                    table[j] = fresh
                    changed = True
            else:
                while len(table) <= j:
                    table.append(self._alloc.alloc())
                    changed = True
            if self._scales is not None:
                # data + scales written by this dispatch's scatter:
                # stamped at the decision point the host actually has
                self._scales.note_write(table[j])
        if changed:
            self._set_table_row(s)

    def _pre_dispatch(self, active: List[int]) -> bool:
        """Hook run before every decode dispatch. True = dispatch with
        ``active`` as-is; False = the block pool or batch composition
        changed (pressure relief ran) — recompute and retry."""
        if not self.paged:
            return True
        try:
            self._ensure_blocks(active)
            return True
        except NoFreeBlocks:
            self._relieve_pressure()
            return False

    def _relieve_pressure(self) -> None:
        """Free KV blocks, cheapest first. Every step either makes
        progress or escalates, so the step_begin retry loop terminates:
        1) barrier-flush the window — late-observed completions and
           deferred frees land;
        2) evict LRU prefix chains — cached prefixes are reclaimable
           without hurting any live request;
        3) preempt the lowest-priority (then youngest-admitted) slot —
           swap-to-host or recompute per ``kv_swap``, re-enqueued at
           the FRONT of the pending queue;
        4) nothing left: raise (the pool cannot serve even one slot —
           a sizing error, not a load condition)."""
        if self._inflight:
            self._flush()
            return
        self._drain_deferred()
        if self._pindex is not None and self._pindex.evict_lru(1) > 0:
            return
        if self._preempt_victim():
            return
        raise NoFreeBlocks(
            "KV block pool exhausted with nothing left to reclaim (no "
            "in-flight ticks, no cached prefixes, no preemptible slot); "
            "size kv_blocks to hold at least one full-length request")

    def _preempt_victim(self) -> bool:
        pre = {ent["req"].slot for ent in self._prefilling}
        cands = [s for s in self._active if s not in pre]
        if len(cands) <= 1 and not self._prefilling:
            # the sole decoder cannot steal from itself — UNLESS a
            # chunk-prefilling admission holds reserved blocks: then
            # vacating the decoder lets that admission finish, decode,
            # and free the pool (refusing here would escalate a
            # transient reservation squeeze into a dead serving loop)
            return False
        if not cands:
            return False
        victim = min(cands, key=lambda s: (self._active[s].priority,
                                           -self._active[s].led.t_admit))
        self._preempt_slot(victim, "swap" if self.kv_swap else "recompute")
        return True

    def preempt(self, rid: int, mode: Optional[str] = None) -> bool:
        """Preempt an active request's slot NOW (swap-to-host or
        recompute; default per ``kv_swap``), re-enqueuing it at the
        front of the pending queue. The engine calls this itself under
        block pressure; it is public for operator tooling and the
        coming request-level elastic-quota controller. False for a
        request that is not an active, fully-prefilled slot."""
        if not self.paged:
            raise RuntimeError("preempt requires paged KV (kv_blocks > 0)")
        mode = mode or ("swap" if self.kv_swap else "recompute")
        if mode not in ("swap", "recompute"):
            raise ValueError(f"mode must be swap|recompute, got {mode!r}")
        if any(e["req"].rid == rid for e in self._prefilling):
            return False
        slot = next((s for s, r in self._active.items() if r.rid == rid),
                    None)
        if slot is None:
            return False
        self._flush()       # barrier — may even FINISH the request
        req = self._active.get(slot)
        if req is None or req.rid != rid or req.done:
            return False
        self._preempt_slot(slot, mode)
        return True

    def _preempt_slot(self, slot: int, mode: str) -> None:
        """Vacate ``slot`` (window must be flushed): capture resume
        state (swap: host copies of its committed blocks; recompute:
        nothing — the tokens themselves are the state), free its
        blocks, and re-enqueue the request at the FRONT of _pending."""
        assert not self._inflight, "preemption requires a flushed window"
        req = self._active.pop(slot)
        bs = self.kv_block_size
        base = len(req.prompt) + len(req.out) - 1
        nblk = blocks_for(base, bs)
        table = self._tables[slot]
        if mode == "swap":
            req.swap_state = self._swap_payload(table, nblk)
        self._tables[slot] = []
        for b in table:
            self._alloc.decref(b)
        self.cache["pos"] = self.cache["pos"].at[slot].set(0)
        self._free.append(slot)
        req.slot = -1
        req.preempted = True
        self._pending.appendleft(req)
        self.preempts[mode] += 1
        if self._tq is not None:
            self._tq.note_preempt(req.tenant, mode)
        if not self._active:
            self._idle_since = None

    # ------------------------------------------------------------------
    # prefill/decode disaggregation (role="prefill"): after prefill
    # produces a request's first token, the request leaves this engine
    # as a resumable handoff state — the SAME swap-payload format
    # preemption and supervised restart already serialize (quantized
    # blocks + per-block scales under int8, so the handoff bytes halve
    # with the arena) — which a decode-role engine adopts via the
    # ordinary ``restore()``, bit-exactly. One payload format for
    # preempt, restart and handoff: the three paths can never drift.
    # ------------------------------------------------------------------
    def _request_state(self, req: _Request) -> dict:
        """The resumable description of one request — the schema
        ``restore()`` consumes, shared by supervised-restart capture
        and the handoff path."""
        return {
            "rid": req.rid,
            "prompt": list(req.prompt),
            "out": list(req.out[:req.max_new_tokens]),
            "max_new_tokens": req.max_new_tokens,
            "temperature": req.temperature,
            "top_k": req.top_k,
            "top_p": req.top_p,
            "seed": req.seed,
            "stop_tokens": list(req.stop_tokens),
            "priority": req.priority,
            "tenant": req.tenant,
            "cache_prefix": req.cache_prefix,
        }

    def _handoff_slot(self, req: _Request) -> None:
        """Vacate a freshly-prefilled slot into a handoff state: swap
        the committed KV (prompt positions — the first token's KV is
        written by the decode step that consumes it, which happens on
        the decode engine) to host, free the blocks, park the state for
        ``pop_handoffs``. The prefill engine never dispatches a decode
        tick, so there is no in-flight window to barrier here; the
        deferred-free discipline still applies for safety."""
        t0 = time.perf_counter()
        s = req.slot
        base = len(req.prompt) + len(req.out) - 1
        nblk = blocks_for(base, self.kv_block_size)
        table = self._tables[s]
        state = self._request_state(req)
        state["swap"] = self._swap_payload(table, nblk)
        state["handoff"] = True
        del self._active[s]
        self._free_slot_blocks(s)
        self.cache["pos"] = self.cache["pos"].at[s].set(0)
        self._free.append(s)
        req.slot = -1
        self._handoffs[req.rid] = state
        self.handoffs += 1
        self.handoff_payload_bytes += handoff_nbytes(state)
        self.handoff_capture_s += time.perf_counter() - t0
        self._record_ledger(req, outcome="handoff")
        if not self._active:
            self._idle_since = None
        self._admit()

    def pop_handoffs(self) -> List[dict]:
        """Drain the parked handoff states in admission order — the
        serving loop ships each to a decode-role replica and resolves
        the waiting client with the decode-side rid."""
        out = list(self._handoffs.values())
        self._handoffs.clear()
        return out

    # ------------------------------------------------------------------
    # supervised-restart support (models/supervision.EngineSupervisor):
    # capture every live request's resumable state from THIS (failed)
    # engine, restore captured state into a FRESH engine. Both lean on
    # the bit-exact resume primitives the paged preemption path proved:
    # byte-exact swap restore and chunking-invariant recompute
    # re-prefill — extended here to the slot-static engine too.
    # ------------------------------------------------------------------
    def capture_resumable(self, device_ok: bool = True) -> List[dict]:
        """Resumable snapshots of every request this engine still owes
        an answer for — active slots (mid-prefill ones as fresh
        submissions), the pending queue (preempted swap payloads kept),
        and finished-but-unpopped results — in original arrival (rid)
        order. Read-only host bookkeeping, safe on a dead engine; the
        one device interaction (swap-to-host KV snapshot of an active
        slot's committed blocks, paged + kv_swap only) is guarded —
        an unreadable device downgrades that slot to recompute — and
        skipped entirely with ``device_ok=False`` (a watchdog-declared
        wedged device could HANG the copy, which no guard catches).
        Iterates over list() snapshots throughout: the supervisor runs
        capture OUTSIDE the loop lock (so handlers answer 503 fast),
        and a concurrently tearing-down stream may pop entries from
        the host dicts while this reads."""
        pre = {ent["req"].rid for ent in self._prefilling}
        states = []
        live = list(self._active.values()) + list(self._pending)
        for req in sorted(live, key=lambda r: r.rid):
            st = self._request_state(req)
            if req.rid in pre:
                st["out"] = []          # mid-prefill: restart admission
            elif req.swap_state is not None:
                st["swap"] = req.swap_state     # already host-resident
            elif device_ok and self.paged and self.kv_swap \
                    and req.slot >= 0 and req.out:
                base = len(req.prompt) + len(req.out) - 1
                nblk = blocks_for(base, self.kv_block_size)
                table = self._tables[req.slot]
                if nblk and len(table) >= nblk:
                    try:
                        st["swap"] = self._swap_payload(table, nblk)
                    except Exception:   # device gone: recompute instead
                        pass
            states.append(st)
        # parked handoff states (prefill role): already host-resident
        # resumable dicts — an engine death between prefill and the
        # loop's push must not lose the KV the client already paid for
        states.extend(dict(st) for st in self._handoffs.values())
        for rid, req in list(self._done.items()):
            states.append({
                "rid": rid,
                "prompt": list(req.prompt),
                "out": list(req.out[:req.max_new_tokens]),
                "max_new_tokens": req.max_new_tokens,
                "tenant": req.tenant,
                "done": True,
            })
        return states

    def restore(self, state: dict) -> int:
        """Re-admit one captured request into this (fresh) engine with
        its committed tokens intact, returning its new rid. The
        supervisor restores in original arrival order into an empty
        engine, so plain appends reproduce front-of-queue semantics;
        client re-submissions after recovery queue behind. A ``done``
        state parks straight in the result table (the loop still owes
        a client that handoff). Committed output resumes through the
        preemption machinery: byte-exact swap restore when the state
        carries a paged KV payload, recompute re-prefill of
        ``prompt + out[:-1]`` otherwise — both bit-exact, so a greedy
        request's tokens are indistinguishable from an undisturbed
        run (tested)."""
        prompt = list(state["prompt"])
        max_new = int(state["max_new_tokens"])
        rid = self._next_rid
        self._next_rid += 1
        req = _Request(
            rid, prompt, max_new,
            temperature=float(state.get("temperature", 0.0)),
            top_k=int(state.get("top_k", 0)),
            top_p=float(state.get("top_p", 0.0)),
            seed=int(state.get("seed", rid)) & 0xFFFFFFFF,
            cache_prefix=bool(state.get("cache_prefix", False))
            and self._prefix_max > 0,
            stop_tokens=tuple(int(t) for t in state.get("stop_tokens")
                              or ()),
            priority=int(state.get("priority", 0)),
            tenant=str(state.get("tenant") or DEFAULT_TENANT),
            led=_Ledger(time.perf_counter()))
        req.out = list(state.get("out") or [])
        if state.get("done"):
            self._done[rid] = req
            return rid
        if state.get("handoff") and self.role == "prefill":
            # a rebuilt PREFILL engine re-parks a captured handoff
            # state (the payload is host-resident — no device work):
            # the loop still owes a decode replica this push. A decode
            # engine adopting the same state falls through below to
            # the ordinary swap-restore resume.
            st = dict(state)
            st["rid"] = rid
            self._handoffs[rid] = st
            return rid
        if len(prompt) + max_new > self.max_len:
            raise Infeasible(
                f"restored prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new}) exceeds cache length {self.max_len}")
        if self.paged:
            need = blocks_for(len(prompt) + max_new - 1,
                              self.kv_block_size)
            if need > self._alloc.capacity:
                raise Infeasible(
                    f"restored request needs {need} KV blocks but the "
                    f"pool only has {self._alloc.capacity}")
        if req.out:
            swap = state.get("swap")
            if self.paged and swap is not None:
                want = tuple(self.cache["k"].shape[i] for i in (0, 2, 3, 4))
                got = tuple(np.asarray(swap["k"]).shape[i]
                            for i in (0, 2, 3, 4))
                want_dt = str(self.cache["k"].dtype)
                got_dt = str(np.asarray(swap["k"]).dtype)
                if want != got or want_dt != got_dt or \
                        (("k_scale" in swap) !=
                         (self.kv_dtype == "int8")):
                    # a handoff/restart payload from a mismatched
                    # engine (different block size, kv heads, layers or
                    # kv_dtype — INCLUDING the planes' float dtype: the
                    # scatter below would silently cast bf16<->f32,
                    # perturbing the KV timeline the byte-exact
                    # contract promises) can never restore here —
                    # permanent, so Infeasible (HTTP 400), not a retry
                    raise Infeasible(
                        f"KV payload geometry [L,Hkv,bs,D]={got} "
                        f"dtype={got_dt} does not match this engine's "
                        f"arena {want} dtype={want_dt} "
                        f"kv_dtype={self.kv_dtype}; handoff/restore "
                        f"requires identical kv_block_size, kv_dtype "
                        f"and model geometry on both ends")
                req.swap_state = dict(swap)
            req.preempted = True
        self._pending.append(req)
        self._admit()
        return rid

    def _swap_payload(self, table: List[int], nblk: int) -> dict:
        """Host copies of a slot's first ``nblk`` committed KV blocks —
        the swap-out payload both preemption (_preempt_slot) and
        supervised-restart capture share, so what the two paths
        snapshot can never silently diverge. An int8 arena swaps the
        quantized bytes PLUS their per-block scales (the payload is
        the dequantizable unit — and roughly half the bf16 bytes, so
        preempt/recovery traffic shrinks with the arena)."""
        idx = jnp.asarray(table[:nblk], jnp.int32)
        payload = {
            "nblk": nblk,
            "k": np.asarray(self.cache["k"][:, idx]),
            "v": np.asarray(self.cache["v"][:, idx]),
        }
        if self.kv_dtype == "int8":
            payload["k_scale"] = np.asarray(self.cache["k_scale"][:, idx])
            payload["v_scale"] = np.asarray(self.cache["v_scale"][:, idx])
        return payload

    # ------------------------------------------------------------------
    # KV fabric (ISSUE 17): host-RAM tier demote/promote under the HBM
    # arena, plus cross-replica chain export/ingest. Everything below
    # moves the SAME swap payload preemption and handoff already move
    # byte-exactly, so tier transitions are bit-exact by construction.
    # ------------------------------------------------------------------
    def _demote_chain(self, key: tuple, blocks: Tuple[int, ...]) -> bool:
        """PrefixBlockIndex.on_evict: offer an evicting chain to the
        host tier. Runs BEFORE the chain's refcounts drop, so the
        arena blocks are still live to snapshot. True = demoted (the
        eviction counts tier="demote"); False falls through to the
        pre-fabric drop."""
        scope, tokens = key
        swap = self._swap_payload(list(blocks), len(blocks))
        if not self._host_tier.put(scope, tokens, swap):
            return False
        self._fabric["demote"] += 1
        return True

    def _promote_from_host(self, req: _Request, m: int, mkey,
                           plen: int) -> Tuple[int, Optional[tuple]]:
        """Admission-time promotion: if the host tier holds a strictly
        longer prefix of ``req.prompt`` than the HBM index matched,
        scatter it back into fresh arena blocks, republish it, and
        re-match. The chain moves tiers (host entry popped); promotion
        is always best-effort, never required for correctness."""
        bs = self.kv_block_size
        scope = self._prefix_scope(req)
        cap = ((plen - 1) // bs) * bs
        key = self._host_tier.match(scope, req.prompt, cap)
        if key is None or len(key[1]) <= m:
            return m, mkey
        ent = self._host_tier.get(key)
        if ent is None:
            return m, mkey
        if self._ingest_swap(key[1], ent["swap"], scope):
            self._host_tier.pop(key)
            self._fabric["promote"] += 1
        # re-match after ANY ingest attempt, success or failure: a
        # FAILED ingest may still have run evict_lru making room, and
        # that eviction can take mkey's own chain with it (a shared
        # chain's blocks free nothing, so the sweep can empty the
        # index and still come up short) — returning the pre-eviction
        # (m, mkey) would hand take() a key the index no longer holds
        return self._pindex.match(req.prompt, plen - 1, scope)

    def _ingest_swap(self, tokens: tuple, swap: dict,
                     scope: Optional[str]) -> bool:
        """Land a chain payload in the arena as a published prefix
        chain: the adopt-by-scatter restore (bit-exact — the bytes
        never changed), then ``publish`` so the next match COW-shares
        it. Allocation never preempts live work for a cache fill —
        only LRU prefix chains may be reclaimed to make room; False =
        no room or a mismatched payload, and the caller falls back to
        plain prefill."""
        nblk = int(swap.get("nblk") or 0)
        bs = self.kv_block_size
        if nblk <= 0 or nblk * bs != len(tokens) \
                or nblk > self._alloc.capacity:
            return False
        # same geometry gate as restore(): a payload from a mismatched
        # engine (block size, heads, layers, kv_dtype) must never
        # scatter — it would silently cast or misalign the timeline
        want = tuple(self.cache["k"].shape[i] for i in (0, 2, 3, 4))
        got_arr = np.asarray(swap["k"])
        if want != tuple(got_arr.shape[i] for i in (0, 2, 3, 4)) \
                or str(self.cache["k"].dtype) != str(got_arr.dtype) \
                or ("k_scale" in swap) != (self.kv_dtype == "int8"):
            return False
        if nblk > self._alloc.free_count:
            self._pindex.evict_lru(nblk - self._alloc.free_count)
            if nblk > self._alloc.free_count:
                return False
        blocks = self._alloc.alloc_many(nblk)
        idx = jnp.asarray(blocks, jnp.int32)
        if "k_scale" in swap:
            self.cache = self._timed_dispatch(
                ("restoreblks_q", nblk), self._restore_blocks_q,
                self.cache, jnp.asarray(swap["k"]),
                jnp.asarray(swap["v"]), jnp.asarray(swap["k_scale"]),
                jnp.asarray(swap["v_scale"]), idx)
        else:
            self.cache = self._timed_dispatch(
                ("restoreblks", nblk), self._restore_blocks, self.cache,
                jnp.asarray(swap["k"]), jnp.asarray(swap["v"]), idx)
        if self._scales is not None:
            for phys in blocks:
                self._scales.note_write(phys)
        self._pindex.publish(list(tokens), blocks, scope)
        for b in blocks:    # the index holds its own references now
            self._alloc.decref(b)
        return True

    def prefix_scope_for(self, tenant: Optional[str]) -> Optional[str]:
        """The ``_prefix_scope`` rule for a raw tenant label (no
        request object yet — the peer-pull ingest path resolves the
        requester's scope BEFORE any pulled chain enters the cache)."""
        if not self._prefix_scoped:
            return None
        return self._tq.cfg.resolve(tenant)

    def ingest_chain(self, data: bytes, tenant: Optional[str] = None,
                     expect_digest: Optional[str] = None) -> bool:
        """Adopt a fabric chain payload pulled from a peer replica.
        Rejections (counted, never raised — a failed pull falls back
        to plain prefill): undecodable bytes, a payload scope that is
        not the requesting tenant's OWN resolved scope (cross-tenant
        migration barrier), a digest that does not match the payload's
        recomputed identity, or no arena room."""
        if not self.paged or self._pindex is None:
            return False
        try:
            state = decode_chain(data)
        except ValueError:
            self._fabric["ingest_rejected"] += 1
            return False
        scope = state.get("scope")
        tokens = tuple(int(t) for t in state.get("tokens") or ())
        if scope != self.prefix_scope_for(tenant) \
                or (expect_digest is not None
                    and chain_digest(tokens, scope) != expect_digest) \
                or not self._ingest_swap(tokens, state["swap"], scope):
            self._fabric["ingest_rejected"] += 1
            return False
        self._fabric["ingest"] += 1
        return True

    def export_chain_begin(self, digest: str) -> Optional[tuple]:
        """Phase 1 of a peer-pull export (runs under the serving-loop
        lock): locate ``digest``'s chain and ENQUEUE the device gather
        of its blocks. jax dispatch is asynchronous, so this returns
        as soon as the gather is on the stream — the gather reads the
        arena version current at enqueue (chain blocks are COW, never
        written in place, and later cache updates produce new
        buffers), so the snapshot is stable no matter what decodes
        after the lock drops. A host-tier hit returns its stored host
        payload directly. Returns an opaque handle for
        ``export_chain_finish``, or None (not here — the puller
        re-prefills; peers' indexes are eventually consistent by
        design)."""
        if not self.paged or self._pindex is None:
            return None
        for key, chain in self._pindex.chain_items():
            if self._chain_digest(key) == digest:
                idx = jnp.asarray(chain, jnp.int32)
                swap = {"nblk": len(chain),
                        "k": self.cache["k"][:, idx],
                        "v": self.cache["v"][:, idx]}
                if self.kv_dtype == "int8":
                    swap["k_scale"] = self.cache["k_scale"][:, idx]
                    swap["v_scale"] = self.cache["v_scale"][:, idx]
                return key[0], key[1], swap
        if self._host_tier is not None:
            hit = self._host_tier.find(digest)
            if hit is not None:
                key, ent = hit
                return key[0], key[1], ent["swap"]
        return None

    @staticmethod
    def export_chain_finish(handle: tuple) -> bytes:
        """Phase 2 (safe OUTSIDE the loop lock): the blocking
        device->host fetch of the gathered planes plus npz encoding —
        the multi-megabyte part of an export, off the serving loop's
        critical section."""
        scope, tokens, swap = handle
        out = {k: (v if isinstance(v, (int, np.ndarray))
                   else np.asarray(v)) for k, v in swap.items()}
        return encode_chain(scope, tokens, out)

    def export_chain(self, digest: str) -> Optional[bytes]:
        """One chain's fabric payload by fleet-wide digest (the
        ``GET /v1/kvchain/<digest>`` surface): an HBM chain snapshots
        the same bytes a demotion would store, a host-tier chain ships
        as stored. Begin + finish in one call, for callers with no
        lock to shed."""
        handle = self.export_chain_begin(digest)
        if handle is None:
            return None
        return self.export_chain_finish(handle)

    def _chain_digest(self, key: tuple) -> str:
        d = self._digests.get(key)
        if d is None:
            d = chain_digest(key[1], key[0])
            self._digests[key] = d
        return d

    def _chain_block_nbytes(self) -> int:
        """Host-side bytes one arena block snapshots to (KV planes +
        scale planes under int8) — sizes the /stats chain rows without
        materializing any payload."""
        if self._blk_nbytes is None:
            tot = 0
            for name in ("k", "v", "k_scale", "v_scale"):
                arr = self.cache.get(name)
                if arr is None:
                    continue
                per = arr.dtype.itemsize * arr.shape[0]
                for d in arr.shape[2:]:
                    per *= d
                tot += int(per)
            self._blk_nbytes = tot
        return self._blk_nbytes

    def prefix_index_snapshot(self) -> Optional[dict]:
        """The /stats ``prefix_index`` section the gateway's fleet
        index consumes: eviction tiers, fabric counters, host-tier
        occupancy, and every resident chain as (digest, token length,
        tier, bytes, scope). Present whenever the engine has a paged
        prefix index — fabric off still reports evictions and HBM
        chains (the observability half stands alone); None
        otherwise."""
        if not self.paged or self._pindex is None:
            return None
        per_blk = self._chain_block_nbytes()
        chains, live = [], set()
        for key, blks in self._pindex.chain_items():
            live.add(key)
            chains.append({"digest": self._chain_digest(key),
                           "len": len(key[1]), "tier": "hbm",
                           "nbytes": per_blk * len(blks),
                           "scope": key[0]})
        # drop cached digests of evicted chains alongside the snapshot
        self._digests = {k: v for k, v in self._digests.items()
                         if k in live}
        host = None
        if self._host_tier is not None:
            host = self._host_tier.stats()
            for row in self._host_tier.digests():
                chains.append(dict(row, tier="host"))
        return {"evicted": dict(self._pindex.evicted),
                "fabric": dict(self._fabric),
                "host_tier": host,
                "chains": chains}

    def _resume_draft(self, req: _Request, seq: List[int]) -> None:
        """Hook for engines with sibling caches (the speculative
        engine's draft KV): re-prefill them over ``seq`` alongside a
        recompute resume. Base engine: nothing to do."""

    def _resume_recompute_static(self, req: _Request) -> None:
        """Slot-static recompute resume — the supervised-restart path
        (slot-static engines never preempt, but a rebuilt engine
        re-admits requests with committed tokens): re-prefill
        ``prompt + out[:-1]`` over a scratch row (per-position forward
        math is chunking-invariant, so the regenerated KV and every
        token after it are bit-exact) and install with pos = committed
        length, feed token = the last committed, not-yet-fed token."""
        req.preempted = False
        seq = req.prompt + req.out[:-1]
        n = len(seq)
        bucket = min(_bucket(n), self.max_len)
        row = {"k": self._row_zeros(bucket), "v": self._row_zeros(bucket),
               "pos": jnp.zeros((), jnp.int32)}
        toks = jnp.asarray([seq + [0] * (bucket - n)], jnp.int32)
        _logits, row = self._run_prefill(toks, row)
        s = req.slot
        self._set_sampling_rows(req)
        self.cache, self._last = self._install(
            self.cache, row["k"], row["v"], jnp.int32(s), jnp.int32(n),
            jnp.int32(req.out[-1]), self._last)
        self._resume_draft(req, seq)
        req.led.t_prefill_end = time.perf_counter()

    def _resume_swapped(self, req: _Request) -> None:
        """Swap-in resume: restore the preempted request's KV bytes
        into freshly allocated blocks — bit-exact by construction (the
        bytes never changed)."""
        st = req.swap_state
        req.swap_state = None
        req.preempted = False
        nblk = st["nblk"]
        blocks = self._alloc.alloc_many(nblk)
        idx = jnp.asarray(blocks, jnp.int32)
        if "k_scale" in st:
            self.cache = self._timed_dispatch(
                ("restoreblks_q", nblk), self._restore_blocks_q,
                self.cache, jnp.asarray(st["k"]), jnp.asarray(st["v"]),
                jnp.asarray(st["k_scale"]), jnp.asarray(st["v_scale"]),
                idx)
        else:
            self.cache = self._timed_dispatch(
                ("restoreblks", nblk), self._restore_blocks, self.cache,
                jnp.asarray(st["k"]), jnp.asarray(st["v"]), idx)
        if self._scales is not None:
            for phys in blocks:
                self._scales.note_write(phys)
        self._tables[req.slot] = blocks
        self._set_table_row(req.slot)
        # sibling caches (the speculative draft) re-prefill over the
        # committed sequence: the target KV restored byte-exact above,
        # the draft regenerated chunking-invariantly — accept/reject
        # decisions continue undisturbed
        self._resume_draft(req, req.prompt + req.out[:-1])
        self._resume_row(req)

    def _resume_recompute(self, req: _Request) -> None:
        """Recompute resume: re-prefill prompt + committed output
        (minus the not-yet-fed last token). Per-position forward math
        is chunking-invariant — the same invariant chunked prefill and
        prefix reuse already rest on — so the regenerated KV, and every
        token after it, is bit-exact. One-shot scratch prefill (no
        chunking: the request already waited once). Slot-static engines
        route to the supervised-restart twin (_resume_recompute_static)
        — same math over the shared cache row instead of arena blocks.
        With the fused decode kernel on, chunking-invariance covers
        only the prompt span (the kernel's decode steps are not
        bit-equal to a gather prefill of the same positions), so
        _replay_committed re-runs the committed output tokens through
        the kernel program afterwards — bit-exactness preserved by
        replay instead of by invariance."""
        if not self.paged:
            return self._resume_recompute_static(req)
        req.preempted = False
        bs = self.kv_block_size
        seq = req.prompt + req.out[:-1]
        n = len(seq)
        bucket = min(max(_bucket(n), bs), self.max_len)
        row = {"k": self._row_zeros(bucket), "v": self._row_zeros(bucket),
               "pos": jnp.int32(0)}
        toks = jnp.asarray([seq + [0] * (bucket - n)], jnp.int32)
        _logits, row = self._run_prefill(toks, row)
        blocks = self._alloc.alloc_many(blocks_for(n, bs))
        for j, phys in enumerate(blocks):
            self.cache = self._timed_dispatch(
                ("installblk", row["k"].shape[3]), self._install_block,
                self.cache, row["k"], row["v"], jnp.int32(phys),
                jnp.int32(j * bs))
            if self._scales is not None:
                self._scales.note_write(phys)
        self._tables[req.slot] = blocks
        self._set_table_row(req.slot)
        if self.paged_kernel == "kernel" and len(req.out) > 1:
            self._replay_committed(req)
        self._resume_draft(req, seq)
        self._resume_row(req)

    def _replay_committed(self, req: _Request) -> None:
        """Kernel-formulation tail of recompute resume: the one-shot
        re-prefill above rebuilt the committed-OUTPUT span with
        gather-formulation math, but the undisturbed run built those
        positions with S==1 kernel decode steps — tolerance-equivalent,
        not bit-equal, and resume promises bit-exactness. Overwrite
        them by replaying the committed tokens through a 1-row twin of
        the decode program (same kernel, same per-position inputs;
        per-row math is batch-invariant — the property the
        serving==generate_paged pin already rests on), so the rebuilt
        arena is bit-identical to the undisturbed run's. Rare path:
        one extra 1-row dispatch per committed token, cache undonated
        (a transient arena alias per call beats surrendering the
        engine's live buffers)."""
        n0 = len(req.prompt)
        table = self._table[req.slot:req.slot + 1]
        cache = {k: v for k, v in self.cache.items() if k != "pos"}
        for i, tok in enumerate(req.out[:-1]):
            cache["pos"] = jnp.asarray([n0 + i], jnp.int32)
            _lg, cache = self._timed_dispatch(
                ("replaytok",), self._replay_step, self.params,
                jnp.asarray([[tok]], jnp.int32), cache, table)
        for key in self.cache:
            if key != "pos":
                self.cache[key] = cache[key]

    def _set_sampling_rows(self, req: _Request) -> None:
        """Install one request's per-slot sampling params (the rows the
        compiled decode program reads) — the ONE place they land, shared
        by admission (_finish_prefill), fork/preempt resume
        (_resume_row) and supervised-restart static resume, so a future
        sampling knob cannot silently miss a path."""
        s = req.slot
        self._temp = self._temp.at[s].set(req.temperature)
        self._topk = self._topk.at[s].set(req.top_k)
        self._topp = self._topp.at[s].set(req.top_p)
        self._seed = self._seed.at[s].set(req.seed)

    def _resume_row(self, req: _Request) -> None:
        """Shared fork/resume tail: sampling rows, device pos (=
        committed KV length) and the feed token (= last committed,
        not yet fed)."""
        s = req.slot
        self._set_sampling_rows(req)
        base = len(req.prompt) + len(req.out) - 1
        self.cache, self._last = self._set_row_state(
            self.cache, self._last, jnp.int32(s), jnp.int32(base),
            jnp.int32(req.out[-1]))
        req.led.t_prefill_end = time.perf_counter()

    def fork(self, rid: int, *, max_new_tokens: Optional[int] = None,
             temperature: Optional[float] = None,
             top_k: Optional[int] = None, top_p: Optional[float] = None,
             seed: Optional[int] = None) -> int:
        """COW-fork an active request: the new request shares every KV
        block of the source's committed context by refcount — n>1
        sampling or branching from a shared system prompt for the
        price of a block table, not a cache copy — and diverges from
        its next token on. A shared block is copied only on first
        write (_ensure_blocks), so a fully-greedy fork that never
        diverges still never aliases a written block. Greedy forks
        continue bit-identically to the source's own continuation;
        pass a different ``seed``/``temperature``/``top_*`` to branch a
        sampled stream. Needs a free slot (QueueFull otherwise) and an
        active, fully-prefilled source (ValueError otherwise); returns
        the new request id."""
        if not self.paged:
            raise RuntimeError("fork requires paged KV (kv_blocks > 0)")
        if any(e["req"].rid == rid for e in self._prefilling):
            raise ValueError(f"request {rid} is still prefilling")
        src = next((r for r in self._active.values() if r.rid == rid),
                   None)
        if src is None:
            raise ValueError(f"request {rid} is not active")
        self._flush()       # barrier: batch composition changes below
        if src.done or src.slot < 0:
            raise ValueError(
                f"request {rid} finished during the fork barrier")
        # free-slot check AFTER the barrier: a completion parked in an
        # unconsumed in-flight tick frees its slot during the flush
        if not self._free:
            raise QueueFull(
                "no free slot to fork into; retry after a completion")
        new_max = src.max_new_tokens if max_new_tokens is None \
            else int(max_new_tokens)
        if new_max <= len(src.out):
            raise ValueError(
                f"max_new_tokens {new_max} <= tokens already produced "
                f"({len(src.out)}); nothing left to decode")
        if len(src.prompt) + new_max > self.max_len:
            raise Infeasible(
                f"prompt ({len(src.prompt)}) + max_new_tokens "
                f"({new_max}) exceeds cache length {self.max_len}")
        fork_cap = blocks_for(len(src.prompt) + new_max - 1,
                              self.kv_block_size)
        if fork_cap > self._alloc.capacity:
            # same permanent-infeasibility guard as submit(): a fork
            # that can never fit the pool must not enter and later
            # crash the loop as an unpreemptible sole decoder
            raise Infeasible(
                f"fork needs {fork_cap} KV blocks at its full length "
                f"but the pool only has {self._alloc.capacity}")
        nrid = self._next_rid
        self._next_rid += 1
        req = _Request(
            nrid, list(src.prompt), new_max,
            temperature=(src.temperature if temperature is None
                         else float(temperature)),
            top_k=src.top_k if top_k is None else int(top_k),
            top_p=src.top_p if top_p is None else float(top_p),
            seed=(src.seed if seed is None else int(seed)) & 0xFFFFFFFF,
            stop_tokens=src.stop_tokens, priority=src.priority,
            tenant=src.tenant,
            led=_Ledger(time.perf_counter()))
        req.out = list(src.out)
        now = time.perf_counter()
        req.led.t_admit = req.led.t_prefill_start = now
        req.led.t_first = req.led.t_last = now
        slot = self._free.popleft()
        req.slot = slot
        self._active[slot] = req
        base = len(src.prompt) + len(src.out) - 1
        nblk = blocks_for(base, self.kv_block_size)
        self._tables[slot] = self._alloc.fork(
            self._tables[src.slot][:nblk])
        self._set_table_row(slot)
        self._resume_row(req)
        return nrid

    def kv_stats(self) -> Optional[dict]:
        """Block-pool accounting for /stats and the serving-loop
        gauges; None when paging is off."""
        if not self.paged:
            return None
        return {
            "block_size": self.kv_block_size,
            "dtype": self.kv_dtype,
            "kernel": self.paged_kernel,
            "scaled_blocks": (self._scales.count
                              if self._scales is not None else None),
            "blocks_total": self._alloc.capacity,
            "blocks_free": self._alloc.free_count,
            "blocks_used": self._alloc.used_count,
            "cow_shared": self._alloc.shared_count(),
            "deferred_frees": len(self._deferred),
            "prefix": (self._pindex.stats()
                       if self._pindex is not None else None),
            "preempts": dict(self.preempts),
            "tenant_reclaims": self.tenant_reclaims,
            "swapped_pending": sum(1 for r in self._pending
                                   if r.swap_state is not None),
            "hbm": self.hbm,
        }

    # ------------------------------------------------------------------
    # pipelined decode: step() == step_begin (dispatch) + step_wait
    # (block on the oldest arrival) + step_finish (host bookkeeping).
    # The serving loop calls the three phases separately so the blocking
    # wait runs OUTSIDE its condition lock; library callers and tests
    # keep calling step().
    # ------------------------------------------------------------------
    def step(self) -> int:
        """One scheduling quantum: dispatch decode ticks until the
        in-flight window is full, consume the oldest arrival, advance
        ONE prefill chunk for the head admitting request (chunked
        prefill); returns the number of tokens emitted. Inactive slots
        ride along in each dispatch (their output discarded, their pos
        frozen in-graph — same compiled program every tick); slots
        mid-prefill are excluded from the decode batch (their cache rows
        aren't installed yet). With pipeline_depth k > 1 a completion is
        observed up to k ticks late; _finish_if_done's pos reset rolls
        the overrun back."""
        c0, t0 = self.compiles, time.perf_counter()
        handle = self.step_begin()
        self.step_wait(handle)
        if handle is not None and self.prefill_budget > 0 \
                and self.compiles == c0:
            # library callers never run the serving loop's tick-phase
            # profiler: self-measure the decode dispatch + wait as the
            # TPOT cost-model sample, skipping ticks that paid a
            # synchronous XLA compile (they'd poison the median)
            self.note_tick_seconds(time.perf_counter() - t0)
        emitted = self.step_finish(handle)
        if self.chip is not None:
            # library callers have no serving loop paying the
            # tick-profiler reads: self-charge the quantum (one tail
            # clock read, only when SLO accounting is on)
            self.chip_note_quantum(t0, time.perf_counter())
        return emitted

    def _active_slots(self) -> List[int]:
        pre = {ent["req"].slot for ent in self._prefilling}
        return sorted(s for s in self._active if s not in pre)

    def step_begin(self) -> Optional[_InFlight]:
        """Dispatch phase: enqueue compiled decode ticks back-to-back
        until the in-flight window holds ``pipeline_depth`` entries (the
        program computes its own next feed tokens on-device, so tick N+1
        never waits for tick N's tokens), each with a non-blocking
        device->host token fetch already started. Returns the oldest
        unconsumed arrival to wait on (None when idle). Cheap host work
        only — safe to call while holding a serving-loop lock.

        Under paged KV, every dispatch is preceded by the block
        discipline (_pre_dispatch): growth blocks allocated, shared
        blocks COW-copied; pool pressure resolves by barrier-flush ->
        prefix eviction -> preemption, each of which changes the batch
        composition — the loop recomputes the active set and retries."""
        t_begin = time.perf_counter()
        self._begin_dispatch_s = 0.0
        active = self._active_slots()
        while active and len(self._inflight) < self.pipeline_depth:
            if not self._pre_dispatch(active):
                active = self._active_slots()
                continue
            self._dispatch_tick(active)
        # everything in this call that was NOT inside _dispatch_tick is
        # assembly: block discipline, batch composition, keep-mask work
        self.last_assemble_s = max(
            0.0, time.perf_counter() - t_begin - self._begin_dispatch_s)
        return self._inflight[0] if self._inflight else None

    def step_wait(self, ent: Optional[_InFlight]) -> None:
        """Block until ``ent``'s tokens are on the host (no-op for None
        or an entry a barrier flush already consumed). This is the ONLY
        place the pipelined hot loop blocks on the device; callers that
        split the phases run it outside their locks."""
        if ent is None or ent.consumed:
            return
        self._fetch(ent)

    def _fetch(self, ent: _InFlight) -> None:
        if ent.host is not None:
            return
        t0 = time.perf_counter()
        ent.host = tuple(np.asarray(a) for a in ent.payload)
        self.host_block_s += time.perf_counter() - t0

    def step_finish(self, ent: Optional[_InFlight]) -> int:
        """Host bookkeeping phase: consume ``ent`` (append tokens,
        retire completions), run one prefill chunk, and re-admit into
        any freed slots. Returns tokens emitted, including any consumed
        by barrier flushes since the last step_finish (so throughput
        accounting never loses the flushed ticks)."""
        emitted = self._flush_emitted
        self._flush_emitted = 0
        if ent is not None and not ent.consumed:
            # arrivals are consumed strictly in dispatch order; ent is
            # the window head unless a flush got there first
            assert self._inflight and self._inflight[0] is ent
            self._inflight.popleft()
            emitted += self._consume(ent)
        if self._prefilling:
            emitted += self._prefill_sched()
        self._admit()       # fill slots freed by completions (barriers)
        if not self._active and not self._pending and self._inflight:
            # the burst ended with over-decoded ticks still in flight:
            # consume them NOW (their tokens are pure rollback — no
            # request appends) so no device handles or deferred block
            # frees linger while the engine idles
            self._flush()
        self._drain_deferred()      # paged: window empty -> frees land
        self._note_window_empty()
        return emitted

    def _note_window_empty(self) -> None:
        """Start the dispatch-gap clock when the in-flight window runs
        empty with decodable slots still present: from here until the
        next decode dispatch, the accelerator is host-blocked. Called
        only at the END of step_finish, after the prefill chunk and
        admission forwards have run — those are real device work, not
        gap, and must not be booked against the clock. (Every
        mid-prefill request holds a slot in _active, so the decodable
        count is the difference.)"""
        if not self._inflight and self._idle_since is None \
                and len(self._active) > len(self._prefilling):
            self._idle_since = time.perf_counter()

    def reset_dispatch_stats(self) -> None:
        """Zero the dispatch-economics counters and the gap clock —
        bench measurement windows call this at their timing fence."""
        self.dispatch_gap_s = 0.0
        self.host_block_s = 0.0
        self.ticks_dispatched = 0
        self.pipeline_flushes = 0
        self._idle_since = None

    def _dispatch_tick(self, active: List[int]) -> None:
        """Enqueue ONE compiled decode dispatch for ``active`` slots and
        start the async token fetch; no host sync."""
        keep = self._keep_mask(tuple(active))
        sampling = any(self._active[s].temperature > 0 for s in active)
        t0 = time.perf_counter()
        if self._idle_since is not None:
            # the dispatch gap ends the moment a tick is in flight again
            self.dispatch_gap_s += t0 - self._idle_since
            self._idle_since = None
        payload = self._timed_dispatch(("decode", sampling),
                                       self._dispatch, active, keep,
                                       sampling)
        self.ticks_dispatched += 1
        for a in payload:
            copy = getattr(a, "copy_to_host_async", None)
            if copy is not None:
                copy()
        dt = time.perf_counter() - t0
        self.host_block_s += dt
        self._begin_dispatch_s += dt
        self._inflight.append(_InFlight(payload, tuple(active)))

    def _keep_mask(self, active: Tuple[int, ...]) -> jax.Array:
        """Device keep-mask for an active-slot tuple, memoized per
        instance: active sets repeat for whole decode phases, and
        rebuilding the mask was a measurable per-dispatch host cost
        (~1ms on the CPU smoke shape). Bounded: at most 2^max_batch
        distinct sets, and the dict dies with the engine (a class-level
        lru_cache would pin every engine — and its device KV cache —
        for the life of the process)."""
        keep = self._keep_masks.get(active)
        if keep is None:
            keep = jnp.zeros((self.max_batch,), bool).at[
                jnp.asarray(active, jnp.int32)].set(True)
            if self.mesh is not None:
                keep = jax.device_put(keep, self._rep)
            self._keep_masks[active] = keep
        return keep

    def _dispatch(self, active: List[int], keep: jax.Array,
                  sampling: bool) -> Tuple[jax.Array, ...]:
        """One compiled decode dispatch for ``active`` slots; returns
        the device handles the matching ``_consume_payload`` will read.
        The template owns the shared scaffolding (window management,
        keep mask, sampling flag, async fetch, ordered consumption) so
        engine subclasses override only this pair."""
        if self.paged:
            toks, self._last, self.cache = self._decode(
                self.params, self._last, self.cache, self._table, keep,
                self._temp, self._topk, self._topp, self._seed, sampling)
        else:
            toks, self._last, self.cache = self._decode(
                self.params, self._last, self.cache, keep,
                self._temp, self._topk, self._topp, self._seed, sampling)
        return (toks,)                                  # [B, T]

    def _consume(self, ent: _InFlight) -> int:
        """Process one arrival's host tokens in order. Idempotent via
        ``ent.consumed``: a step_finish holder racing a barrier flush
        processes each tick exactly once."""
        if ent.consumed:
            return 0
        ent.consumed = True
        self._fetch(ent)        # usually a no-op: fetch already landed
        # ONE clock read per arrival (not per token) stamps every token
        # this arrival lands — the ledger's hot-path cost in full
        now = time.perf_counter() if self.ledger_enabled else 0.0
        emitted = self._consume_payload(ent, ent.host, now)
        self.tokens_emitted += emitted
        ent.payload = ()        # drop device refs promptly
        return emitted

    def _consume_payload(self, ent: _InFlight, host: tuple,
                         now: float = 0.0) -> int:
        """Append one tick's tokens ([B, T]) to its requests. A slot
        whose request already finished (observed in an EARLIER arrival,
        or mid-burst below) contributes nothing — its late tokens are
        the pipeline overrun the pos-reset rollback discards; because
        they are never appended, they also never earn a ledger stamp
        (no duplicate TPOT samples from rollbacks by construction)."""
        (toks,) = host
        emitted = 0
        for s in ent.slots:
            req = self._active.get(s)
            if req is None or req.done:
                continue
            n = 0
            for j in range(toks.shape[1]):
                req.out.append(int(toks[s, j]))
                req.note_token()
                emitted += 1
                n += 1
                if req.done:
                    break
            if n and now:
                req.led.note_tokens(n, now)
            self._note_tenant_tokens(req, n)
            self._chip_add(req.tenant, "decode", n)
            self._finish_if_done(req, admit=False)
        return emitted

    def _flush(self) -> int:
        """Pipeline barrier: consume every in-flight arrival in dispatch
        order. Called before any batch-composition change (admission
        install, cancel) — un-consumed arrivals reference the old
        slot->request binding and must land first. Tokens emitted here
        are credited to the next step_finish via _flush_emitted."""
        emitted = 0
        if self._inflight:
            self.pipeline_flushes += 1
        while self._inflight:
            emitted += self._consume(self._inflight.popleft())
        self._flush_emitted += emitted
        self._drain_deferred()      # paged: barrier landed, frees land
        return emitted

    def pop_result(self, rid: int) -> Optional[List[int]]:
        """The finished sequence for ``rid`` (prompt + generated), or None
        while it is still pending/active. Popping forgets it — each
        result is handed out exactly once (the HTTP server's contract)."""
        req = self._done.pop(rid, None)
        if req is None:
            return None
        return req.prompt + req.out[:req.max_new_tokens]

    def cancel(self, rid: int) -> bool:
        """Stop decoding a request NOW: a pending request is dropped from
        the queue; an active one is truncated at its current output and
        its slot recycled (the serving loop calls this when a streaming
        client disconnects — without it an abandoned 480-token request
        would burn its remaining ticks while queued requests wait). The
        request lands in the done-table (possibly with a partial output)
        for the caller to pop. False for an unknown/finished rid."""
        for i, req in enumerate(self._pending):
            if req.rid == rid:
                del self._pending[i]
                self._done[rid] = req        # empty output; poppable
                self._record_ledger(req, outcome="cancelled")
                return True
        # pipeline barrier: cancel mutates the slot->request binding; in-
        # flight arrivals for the old binding must land first (this may
        # even FINISH the request — then it is already done-table'd and
        # the scans below correctly find nothing). Unknown/finished rids
        # change no binding, so they must not collapse the window — the
        # serving loop cancels unconditionally on every client timeout
        if not any(req.rid == rid for req in self._active.values()) \
                and not any(e["req"].rid == rid for e in self._prefilling):
            return False
        if self._inflight:
            self._flush()
        for i, ent in enumerate(self._prefilling):
            if ent["req"].rid == rid:
                # drop the chunk queue FIRST: the slot frees below, and
                # a later _prefill_tick must never install into it
                del self._prefilling[i]
                if self.paged:
                    # blocks reserved at chunked admission (shared
                    # prefix refs included) were never exposed to the
                    # device table — release them directly
                    for b in (ent["req"].reserved_blocks or []):
                        self._alloc.decref(b)
                    ent["req"].reserved_blocks = None
                    ent["req"].shared_blocks = []
                break
        for req in self._active.values():
            if req.rid == rid:
                req.max_new_tokens = len(req.out)
                req.led.outcome = "cancelled"
                self._finish_if_done(req)    # frees the slot, admits next
                return True
        return False

    def progress(self, rid: int) -> Optional[tuple]:
        """(generated tokens so far, done) for a submitted request —
        the streaming read. None for an unknown (or already-popped) rid.
        Unlike ``pop_result`` this never forgets: a finished request
        stays readable until popped, so a streamer can observe the tail
        and THEN pop. O(slots + pending) scan — both are small by
        construction."""
        req = self._done.get(rid)
        if req is not None:
            return list(req.out[:req.max_new_tokens]), True
        for req in self._active.values():
            if req.rid == rid:
                return list(req.out), False
        for req in self._pending:
            if req.rid == rid:
                # a preempted request waits here WITH committed tokens:
                # a streaming client keeps them through the pause
                return list(req.out), False
        return None

    def occupancy(self) -> tuple:
        """(active slots, waiting requests) — the live load view the
        serving loop mirrors into gauges."""
        return len(self._active), len(self._pending)

    def stats(self) -> dict:
        """Live introspection snapshot (the /stats endpoint's engine
        half): per-slot request state, pending-queue depth and oldest
        wait, pipeline-window occupancy, prefix-cache and compile
        accounting. Host dict reads only — safe to call between ticks
        under the serving loop's lock."""
        now = time.perf_counter()
        prefilling = {e["req"].rid for e in self._prefilling}
        slots = []
        for s in sorted(self._active):
            req = self._active[s]
            slots.append({
                "slot": s,
                "rid": req.rid,
                "tenant": req.tenant,
                "age_s": round(now - (req.led.t_admit
                                      or req.led.t_submit), 6),
                "pos": len(req.prompt) + len(req.out),
                "tokens_out": len(req.out),
                "max_new_tokens": req.max_new_tokens,
                "prefilling": req.rid in prefilling,
                "sampling": {"temperature": req.temperature,
                             "top_k": req.top_k, "top_p": req.top_p,
                             "seed": req.seed},
            })
        oldest = (now - self._pending[0].led.t_submit
                  if self._pending else 0.0)
        return {
            "engine": type(self).__name__,
            "role": self.role,
            # prefill/decode disaggregation surface (None when
            # colocated — no dead sections): parked payloads waiting
            # for the loop's push, cumulative handoffs and bytes
            "handoff": ({
                "ready": len(self._handoffs),
                "total": self.handoffs,
                "payload_bytes": self.handoff_payload_bytes,
                "capture_s": round(self.handoff_capture_s, 6),
            } if self.role == "prefill" else None),
            "max_batch": self.max_batch,
            "max_len": self.max_len,
            "slots": slots,
            "pending": {"depth": len(self._pending),
                        "oldest_wait_s": round(oldest, 6)},
            # budgeted chunked prefill (None when chunking is off — no
            # dead sections): budget + banked credit, the chunk-queue
            # backlog a fresh admission waits behind, and the clamp /
            # overdraw counters the loop mirrors into counters
            "prefill_sched": ({
                "budget": self.prefill_budget,
                "credit": round(self._prefill_credit, 3),
                "backlog_tokens": self.prefill_backlog(),
                "chunk_tokens": self.prefill_chunk_tokens,
                "budget_spent_tokens": self.prefill_budget_spent,
                "clamped_ticks": self.prefill_budget_clamped,
                "overrides": self.prefill_budget_overrides,
                "est_prefill_tok_s": round(
                    self._est_prefill_tok_s(), 9),
                "est_tick_s": round(self._est_tick_s(), 9),
            } if self._prefill_chunk else None),
            "pipeline": {"depth": self.pipeline_depth,
                         "decode_steps": self.decode_steps,
                         "in_flight": len(self._inflight),
                         "flushes": self.pipeline_flushes,
                         "ticks_dispatched": self.ticks_dispatched},
            "prefix_cache": (
                {"capacity_blocks": self._pindex.max_blocks,
                 "entries": self._pindex.stats()["chains"],
                 "blocks": self._pindex.block_count,
                 "hits": self._pindex.hits,
                 "tokens_saved": self._pindex.tokens_saved}
                if self.paged and self._pindex is not None else
                {"capacity": self._prefix_max,
                 "entries": len(self._prefixes),
                 "hits": self.prefix_hits,
                 "tokens_saved": self.prefix_tokens_saved}),
            # the KV-fabric surface: chain digests + lengths + tier
            # (what the gateway's fleet index scrapes), eviction tiers
            # and host-tier occupancy; None without a paged prefix
            # index
            "prefix_index": self.prefix_index_snapshot(),
            # block-pool occupancy + the admission-time HBM snapshot:
            # why a request queued, answerable from one /stats read
            "kv": self.kv_stats(),
            # request-level elastic quota: per-tenant rates vs min/max,
            # borrow shares, sheds and reclaim preemptions — the
            # gateway sums ``rate_tokens_per_s`` across replicas for
            # its fleet-wide door admission
            "tenants": self.tenant_snapshot(),
            "compiles": {"count": self.compiles,
                         "seconds": round(self.compile_s, 6)},
            "tokens_emitted": self.tokens_emitted,
        }

    def tenant_snapshot(self) -> Optional[dict]:
        """Per-tenant quota accounting for /stats and the serving
        loop's gauge mirror; None when tenancy is off (no dead
        sections on single-tenant servers)."""
        if self._tq is None:
            return None
        snap = self._tq.snapshot(self._tq_clock())
        pending_by, active_by = {}, {}
        for r in self._pending:
            t = self._tq.cfg.resolve(r.tenant)
            pending_by[t] = pending_by.get(t, 0) + 1
        for r in self._active.values():
            t = self._tq.cfg.resolve(r.tenant)
            active_by[t] = active_by.get(t, 0) + 1
        for name, row in snap.items():
            row["pending"] = pending_by.get(name, 0)
            row["active"] = active_by.get(name, 0)
        return snap

    def has_work(self) -> bool:
        return bool(self._active or self._pending)

    def drain(self) -> Dict[int, List[int]]:
        """Run until every submitted request completes; returns
        {request_id: prompt + generated tokens} for requests finished
        since the last drain, and forgets them."""
        while self._active or self._pending:
            if not self._active:
                # a preemption can legitimately leave only pending
                # work (the victim re-queued, everyone else finished):
                # admission is the step that makes progress here. If
                # it cannot admit either, THAT is the bug.
                self._admit()
                if not self._active:
                    raise RuntimeError(
                        "pending requests with no active slots")
            self.step()
        # the last completion can leave over-decoded arrivals in flight
        # (every request already done): drain them so no device handles
        # linger between serving bursts
        self._flush()
        self._flush_emitted = 0
        out = {r.rid: r.prompt + r.out[:r.max_new_tokens]
               for r in self._done.values()}
        self._done.clear()
        return out
