"""Decoder-only transformer (Llama-style), TPU-first.

The flagship workload for the scheduling stack's gang-scheduled JobSets and
the driver's multi-chip dry run. Design choices per the TPU brief:

- bf16 activations/params compute path; fp32 rmsnorm statistics and loss;
- every matmul shaped for the MXU (model dims multiples of 128 at real
  sizes; tiny test configs still compile the same program);
- GSPMD sharding via explicit NamedSharding annotations: params sharded
  over (fsdp, tp) following the megatron+zero layout, activations over
  (dp/fsdp batch, sp sequence, tp heads/features);
- sequence parallelism: when the mesh has an ``sp`` axis, attention runs as
  ring attention under shard_map (exact, long-context) — otherwise the
  pallas flash kernel / XLA path;
- per-layer ``jax.checkpoint`` rematerialization to trade FLOPs for HBM;
- ``lax.scan`` over layers: one compiled layer body, no Python unrolling.
"""
from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from nos_tpu.ops.attention import attention
from nos_tpu.ops.layers import (
    apply_rope, rms_norm, rope_frequencies, swiglu,
)
from nos_tpu.ops.moe import moe_ffn
from nos_tpu.ops.ring_attention import ring_attention
from nos_tpu.utils.jax_compat import shard_map

Params = Dict[str, Any]


@dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 32000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    d_ff: int = 1408
    max_seq: int = 2048
    rope_theta: float = 10000.0
    dtype: Any = jnp.bfloat16
    remat: bool = True
    # "full": recompute the whole layer in backward (max HBM savings,
    # ~+33% FLOPs). "dots": save matmul outputs, recompute only cheap
    # elementwise ops — near-zero recompute but the wide d_ff
    # intermediates dominate HBM. "except_mlp": save the qkv/attention
    # tensors, recompute only the gate/up mlp matmuls (~16% FLOPs
    # overhead at a fraction of dots' memory — the policy that lets the
    # flagship batch fit un-rematerialized where it matters).
    # "minimal": save only the attention outputs (and kernel residuals)
    # — recompute every projection, max batch headroom short of "full".
    remat_policy: str = "full"
    # > 0: compute the lm head + cross-entropy in sequence chunks of this
    # size under jax.checkpoint, so the [B, S, vocab] fp32 logits never
    # materialize at once (peak transient becomes [B, chunk, vocab]).
    loss_chunk: int = 0
    # grouped-query attention: 0 means MHA (n_kv_heads == n_heads)
    n_kv_heads: int = 0
    # sequence-parallel attention strategy when the mesh has an sp axis:
    # "ring" (K/V rotation, no head-count constraint) or "ulysses"
    # (all-to-all head/sequence reshuffle; heads must divide by sp)
    sp_strategy: str = "ring"
    # Mixture-of-Experts: when n_experts > 0 every layer's FFN is a top-2
    # MoE with experts sharded over the mesh's ep axis (nos_tpu/ops/moe.py)
    n_experts: int = 0
    expert_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01

    def __post_init__(self):
        if self.d_model % self.n_heads:
            raise ValueError("d_model must divide by n_heads")
        if self.n_kv_heads and self.n_heads % self.n_kv_heads:
            raise ValueError("n_heads must divide by n_kv_heads")
        if self.sp_strategy not in ("ring", "ulysses"):
            raise ValueError(f"unknown sp_strategy {self.sp_strategy!r}")
        if self.remat_policy not in ("full", "dots", "except_mlp", "minimal"):
            raise ValueError(f"unknown remat_policy {self.remat_policy!r}")

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_heads

    @property
    def kv_dim(self) -> int:
        return self.kv_heads * self.head_dim


# ---------------------------------------------------------------------------
# params
# ---------------------------------------------------------------------------

def init_params(rng: jax.Array, cfg: TransformerConfig) -> Params:
    k_embed, k_layers, k_out = jax.random.split(rng, 3)

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32) * fan_in ** -0.5
                ).astype(cfg.dtype)

    keys = jax.random.split(k_layers, cfg.n_layers * 8).reshape(cfg.n_layers, 8, 2)

    def layer(i):
        kq, kk, kv, ko, kg, ku, kd, kr = [keys[i, j] for j in range(8)]
        d, h, e = cfg.d_model, cfg.d_ff, cfg.n_experts
        out = {
            "attn_norm": jnp.ones((d,), jnp.float32),
            "wq": dense(kq, (d, d), d),
            "wk": dense(kk, (d, cfg.kv_dim), d),
            "wv": dense(kv, (d, cfg.kv_dim), d),
            "wo": dense(ko, (d, d), d),
            "mlp_norm": jnp.ones((d,), jnp.float32),
        }
        if e > 0:
            out["w_router"] = (jax.random.normal(kr, (d, e), jnp.float32)
                               * d ** -0.5)
            out["w_gate"] = dense(kg, (e, d, h), d)
            out["w_up"] = dense(ku, (e, d, h), d)
            out["w_down"] = dense(kd, (e, h, d), h)
        else:
            out["w_gate"] = dense(kg, (d, h), d)
            out["w_up"] = dense(ku, (d, h), d)
            out["w_down"] = dense(kd, (h, d), h)
        return out

    layers = jax.tree.map(lambda *xs: jnp.stack(xs), *[layer(i) for i in range(cfg.n_layers)])
    return {
        "embed": (jax.random.normal(k_embed, (cfg.vocab, cfg.d_model), jnp.float32)
                  * cfg.d_model ** -0.5).astype(cfg.dtype),
        "layers": layers,
        "final_norm": jnp.ones((cfg.d_model,), jnp.float32),
        "unembed": dense(k_out, (cfg.d_model, cfg.vocab), cfg.d_model),
    }


def param_shardings(mesh: Mesh, cfg: TransformerConfig) -> Params:
    """Megatron+zero layout: feature axes over tp, the other matmul axis
    over fsdp; norms replicated."""
    from nos_tpu.parallel.mesh import logical_to_sharding

    def ns(*axes):
        return logical_to_sharding(mesh, *axes)

    layer = {
        "attn_norm": ns(None, None),
        "wq": ns(None, "fsdp", "tp"),
        "wk": ns(None, "fsdp", "tp"),
        "wv": ns(None, "fsdp", "tp"),
        "wo": ns(None, "tp", "fsdp"),
        "mlp_norm": ns(None, None),
    }
    if cfg.n_experts > 0:
        # experts over ep; within each expert the megatron layout
        layer["w_router"] = ns(None, "fsdp", None)
        layer["w_gate"] = ns(None, "ep", "fsdp", "tp")
        layer["w_up"] = ns(None, "ep", "fsdp", "tp")
        layer["w_down"] = ns(None, "ep", "tp", "fsdp")
    else:
        layer["w_gate"] = ns(None, "fsdp", "tp")
        layer["w_up"] = ns(None, "fsdp", "tp")
        layer["w_down"] = ns(None, "tp", "fsdp")
    return {
        "embed": ns("tp", None),
        "layers": layer,
        "final_norm": ns(None),
        "unembed": ns(None, "tp"),
    }


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _activation_spec(mesh: Optional[Mesh]) -> Optional[P]:
    if mesh is None:
        return None
    batch = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names) or None
    seq = "sp" if "sp" in mesh.axis_names else None
    return P(batch, seq, None)


def attention_block(h_in, layer, cfg: TransformerConfig, freqs,
                    attention_call):
    """Pre-RMSNorm attention sublayer + residual. ``attention_call(q, k, v)``
    takes/returns [B, S, H, D]."""
    b, s = h_in.shape[:2]
    h = rms_norm(h_in, layer["attn_norm"])
    q = jnp.dot(h, layer["wq"]).reshape(b, s, cfg.n_heads, cfg.head_dim)
    k = jnp.dot(h, layer["wk"]).reshape(b, s, cfg.kv_heads, cfg.head_dim)
    v = jnp.dot(h, layer["wv"]).reshape(b, s, cfg.kv_heads, cfg.head_dim)
    q, k = apply_rope(q, freqs), apply_rope(k, freqs)
    # checkpoint_name tags feed the named remat policies ("except_mlp",
    # "minimal"); under other policies they are inert
    q = checkpoint_name(q, "qkv_proj")
    k = checkpoint_name(k, "qkv_proj")
    v = checkpoint_name(v, "qkv_proj")
    # GQA: k/v stay at kv_heads — the attention ops group query heads
    # internally, un-materialized on every path
    o = attention_call(q, k, v).reshape(b, s, cfg.d_model)
    o = checkpoint_name(o, "attn_out")
    return h_in + jnp.dot(o, layer["wo"])


def dense_ffn_block(h_in, layer):
    """Pre-RMSNorm SwiGLU FFN sublayer + residual (dense path). The wide
    [B, S, d_ff] intermediates carry no checkpoint_name on purpose: every
    named policy exists to NOT save them (that is the memory win over
    "dots")."""
    h = rms_norm(h_in, layer["mlp_norm"])
    return h_in + swiglu(h, layer["w_gate"], layer["w_up"],
                         layer["w_down"])


def dense_layer_block(h_in, layer, cfg: TransformerConfig, freqs,
                      attention_call):
    """One decoder layer on the dense path. Shared by the plain forward and
    the pipelined forward so the two cannot drift."""
    x = attention_block(h_in, layer, cfg, freqs, attention_call)
    return dense_ffn_block(x, layer)


def cross_entropy(logits: jax.Array, targets: jax.Array) -> jax.Array:
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(nll)


def _remat_policy(cfg: TransformerConfig):
    """Saveable-set for jax.checkpoint by cfg.remat_policy (None means
    checkpoint everything). "attn_residuals" is the splash kernel's
    logsumexp tag (ops/attention.py) — saving it means the backward never
    re-runs the forward attention kernel under the named policies."""
    cp = jax.checkpoint_policies
    if cfg.remat_policy == "dots":
        return cp.dots_with_no_batch_dims_saveable
    if cfg.remat_policy == "except_mlp":
        return cp.save_only_these_names(
            "qkv_proj", "attn_out", "attn_residuals")
    if cfg.remat_policy == "minimal":
        return cp.save_only_these_names("attn_out", "attn_residuals")
    return None


def lm_head_loss(norm_w, unembed, hidden, targets, loss_chunk: int = 0):
    """Final rms-norm + unembed + token cross-entropy. With loss_chunk > 0
    the sequence is processed in checkpointed chunks so the fp32
    [B, S, vocab] logits (the largest transient in the whole step — 2 GB
    at the flagship's batch 8) never exist at once; the backward
    recomputes one [B, chunk, vocab] block at a time (one extra unembed
    matmul, ~2% of step FLOPs)."""
    hidden = rms_norm(hidden, norm_w)
    b, s, _ = hidden.shape
    if loss_chunk and s > loss_chunk and s % loss_chunk != 0:
        raise ValueError(
            f"loss_chunk={loss_chunk} does not divide seq_len={s}; "
            f"chunking would be silently disabled and the full fp32 "
            f"[B,S,vocab] logits materialised — pick a divisor of the "
            f"sequence length")
    if loss_chunk and s > loss_chunk:
        n = s // loss_chunk
        xs = hidden.reshape(b, n, loss_chunk, -1).swapaxes(0, 1)
        ts = targets.reshape(b, n, loss_chunk).swapaxes(0, 1)

        @jax.checkpoint
        def chunk_nll(carry, xt):
            xc, tc = xt
            logits = jnp.dot(xc, unembed).astype(jnp.float32)
            logp = jax.nn.log_softmax(logits, axis=-1)
            nll = -jnp.take_along_axis(logp, tc[..., None], axis=-1)[..., 0]
            return carry + jnp.sum(nll), None

        total, _ = jax.lax.scan(chunk_nll, jnp.float32(0.0), (xs, ts))
        return total / (b * s)
    logits = jnp.dot(hidden, unembed).astype(jnp.float32)
    return cross_entropy(logits, targets)


def _attention_call(q, k, v, mesh: Optional[Mesh], sp_strategy: str = "ring"):
    """q,k,v: [B, S, H, D] -> transpose to [B, H, S, D] and dispatch."""
    q, k, v = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    if mesh is not None and "sp" in mesh.axis_names and mesh.shape["sp"] > 1:
        from nos_tpu.ops.ulysses import ulysses_attention

        sp_fn = ring_attention if sp_strategy == "ring" else ulysses_attention
        batch = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names) or None
        tp = "tp" if "tp" in mesh.axis_names else None
        spec = P(batch, tp, "sp", None)
        out = shard_map(
            functools.partial(sp_fn, axis_name="sp", causal=True),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
        )(q, k, v)
    else:
        out = attention(q, k, v, causal=True)
    return out.transpose(0, 2, 1, 3)


def forward(
    params: Params,
    cfg: TransformerConfig,
    tokens: jax.Array,
    mesh: Optional[Mesh] = None,
    return_aux: bool = False,
    return_hidden: bool = False,
):
    """tokens [B, S] -> logits [B, S, vocab] (plus the MoE auxiliary loss
    when ``return_aux``). ``return_hidden`` instead yields the pre-head
    hidden state [B, S, d_model] + aux, for callers (loss_fn) that apply
    the lm head themselves — chunked, so the logits never materialize."""
    b, s = tokens.shape
    freqs = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
    act_spec = _activation_spec(mesh)

    def constrain(x):
        if mesh is None or act_spec is None:
            return x
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, act_spec))

    x = constrain(params["embed"][tokens])

    # positions are global even when the sequence is sp-sharded: rope is
    # applied inside the layer on the local shard with its global offset
    # handled by the constraint (XLA keeps the gather local)
    def layer_body(x, layer):
        x = constrain(attention_block(
            x, layer, cfg, freqs,
            lambda q, k, v: _attention_call(q, k, v, mesh, cfg.sp_strategy),
        ))
        if cfg.n_experts > 0:
            h = rms_norm(x, layer["mlp_norm"])
            y, aux = moe_ffn(
                h, layer["w_router"], layer["w_gate"], layer["w_up"],
                layer["w_down"], cfg.expert_capacity_factor,
            )
            x = x + y
        else:
            x = dense_ffn_block(x, layer)
            aux = jnp.float32(0.0)
        return constrain(x), aux

    body = layer_body
    if cfg.remat:
        body = jax.checkpoint(layer_body, policy=_remat_policy(cfg))
    x, aux_per_layer = jax.lax.scan(body, x, params["layers"])

    if return_hidden:
        return x, jnp.mean(aux_per_layer)
    x = rms_norm(x, params["final_norm"])
    logits = jnp.dot(x, params["unembed"]).astype(jnp.float32)
    if return_aux:
        return logits, jnp.mean(aux_per_layer)
    return logits


# ---------------------------------------------------------------------------
# training
# ---------------------------------------------------------------------------

def loss_fn(params: Params, cfg: TransformerConfig, batch: Dict[str, jax.Array],
            mesh: Optional[Mesh] = None) -> jax.Array:
    hidden, aux = forward(params, cfg, batch["tokens"], mesh,
                          return_hidden=True)
    loss = lm_head_loss(params["final_norm"], params["unembed"], hidden,
                        batch["targets"], cfg.loss_chunk)
    return loss + cfg.moe_aux_weight * aux


def make_train_step(cfg: TransformerConfig, optimizer,
                    mesh: Optional[Mesh] = None):
    """Returns train_step(params, opt_state, batch) -> (params, opt_state,
    loss). Gradients/optimizer follow the param shardings under GSPMD."""

    def train_step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, cfg, batch, mesh)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        import optax

        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return train_step
