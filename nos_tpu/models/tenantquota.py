"""Request-level elastic quota — per-tenant token-rate min/max with
borrowing and fair-share preemptive reclaim (ISSUE 13 tentpole),
deliberately jax-free.

nos's signature idea — ElasticQuota min/max with borrowing of idle
capacity and fair-share preemption — has so far lived at POD
granularity (``nos_tpu/quota/info.py``, the scheduler's capacity
plugin). This module ports it down to the REQUEST level, the way DRF
ports fair sharing to multi-resource schedulers and Orca ports
scheduling to iteration granularity: the serving engine's admission
queue stops being strict FIFO and becomes a weighted pick over
tenants, where

- a tenant under its ``min`` token-rate is GUARANTEED: it is admitted
  before any tenant at/over its min (never skipped for a borrower);
- idle capacity is LENT: tenants over their min keep admitting,
  ordered so that borrowed rate stays proportional to each tenant's
  ``guaranteed_overquotas``-style share of the unused aggregate min —
  and the share math is not a re-implementation: ``borrow_shares``
  builds ``QuotaInfos`` from the tenant specs and calls
  ``QuotaInfos.guaranteed_overquotas`` (quota/info.py:207), so the
  request layer and the pod layer CANNOT disagree about what "fair"
  means;
- ``max`` is the lending ceiling under contention: a tenant measured
  at/over its max while the engine is busy is shed at submission with
  the machine-readable ``tenant_quota`` reason (429 + Retry-After) —
  the last rung of the degradation ladder borrow -> stop lending ->
  preempt -> shed-with-reason. An IDLE engine still lends past max
  (work conservation: no slot sits idle while any tenant has work).

Rates are measured over a sliding window on an injectable clock
(``now`` is always passed in), so the scheduler is deterministic under
a fake clock — the property fuzz and the multi-tenant bench both rely
on that.

The reclaim side (a guaranteed tenant arriving with no headroom
preempting the most-over-quota tenant's youngest slot, bit-exact
resume through ``DecodeServer.preempt``'s machinery) lives in the
engine; this module only answers the policy questions: who is under
min, who is most over quota, who admits next.
"""
from __future__ import annotations

import json
import os
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, Iterable, List, Optional, Tuple

from nos_tpu.quota.info import QuotaInfo, QuotaInfos

__all__ = ["DEFAULT_TENANT", "TenantSloSpec", "TenantSpec",
           "TenantQuotaConfig", "TenantScheduler", "RATE_RESOURCE"]

#: the tenant unlabeled traffic is accounted to
DEFAULT_TENANT = "default"

#: the synthetic ResourceList key tenant token-rates travel under when
#: the shares route through quota/info.py's aggregates
RATE_RESOURCE = "serve_tokens"

#: rates are scaled to milli-tokens/s before entering QuotaInfos:
#: ``_floor_quantity`` floors scalar resources at whole units, and a
#: sub-token/s share must not floor to zero
RATE_SCALE = 1000.0

#: tenant label charset/length guard — tenant names travel as metric
#: labels and annotation values, so the wire layer rejects the exotic
MAX_TENANT_LEN = 128


@dataclass(frozen=True)
class TenantSloSpec:
    """One tenant's SLO objectives (ISSUE 20): p99 latency targets and
    a goodput floor, all optional (0 = objective not tracked). These
    feed the serving loop's ``SloBudgetEngine``; a config with no
    ``slo`` blocks anywhere runs with SLO accounting OFF (zero new
    per-tick work)."""

    ttft_p99_ms: float = 0.0
    tpot_p99_ms: float = 0.0
    goodput_floor: float = 0.0

    def echo(self) -> dict:
        return {"ttft_p99_ms": self.ttft_p99_ms,
                "tpot_p99_ms": self.tpot_p99_ms,
                "goodput_floor": self.goodput_floor}


@dataclass(frozen=True)
class TenantSpec:
    """One tenant's token-rate quota. ``min_rate`` tokens/s are
    GUARANTEED (admitted first, reclaimed by preemption when necessary);
    ``max_rate`` is the borrowing ceiling under contention (0 =
    unlimited). min <= max is validated at parse time. ``slo`` carries
    the tenant's optional error-budget objectives."""

    name: str
    min_rate: float = 0.0
    max_rate: float = 0.0
    slo: Optional[TenantSloSpec] = None


@dataclass
class TenantQuotaConfig:
    """Parsed ``--tenant-config`` (file path or inline JSON):

        {"default_tenant": "default",
         "window_s": 5.0,
         "share_prefix": false,
         "tenants": {"gold":  {"min_rate": 200, "max_rate": 0},
                     "burst": {"min_rate": 0,   "max_rate": 50}}}

    Unknown tenant names resolve to ``default_tenant``'s quota (and its
    metric label) — identity is the same trust domain as the rest of
    the serving surface, but an unknown label must not mint unbounded
    scheduler/metric state. ``share_prefix`` is the OPT-OUT for
    tenant-scoped prefix-cache keys: by default two tenants with
    identical prompts get disjoint KV chains (cross-tenant block
    sharing is a timing side-channel); trusted single-org fleets may
    turn sharing back on."""

    tenants: Dict[str, TenantSpec] = field(default_factory=dict)
    default_tenant: str = DEFAULT_TENANT
    window_s: float = 5.0
    share_prefix: bool = False

    def __post_init__(self):
        if self.window_s <= 0:
            raise ValueError(
                f"window_s must be > 0, got {self.window_s}")
        if self.default_tenant not in self.tenants:
            # the default tenant always exists (unlabeled traffic needs
            # a ledger row), with unbounded borrowing unless configured
            self.tenants = dict(self.tenants)
            self.tenants[self.default_tenant] = TenantSpec(
                self.default_tenant)
        for name, spec in self.tenants.items():
            if spec.min_rate < 0 or spec.max_rate < 0:
                raise ValueError(
                    f"tenant {name!r}: rates must be >= 0")
            if spec.max_rate and spec.min_rate > spec.max_rate:
                raise ValueError(
                    f"tenant {name!r}: min_rate {spec.min_rate} > "
                    f"max_rate {spec.max_rate}")

    # -- parsing --------------------------------------------------------
    @classmethod
    def load(cls, spec: str) -> Optional["TenantQuotaConfig"]:
        """``--tenant-config`` semantics: empty = tenancy off (None);
        a string starting with ``{`` parses as inline JSON, anything
        else is a file path."""
        if not spec:
            return None
        text = spec
        if not spec.lstrip().startswith("{"):
            if not os.path.exists(spec):
                raise ValueError(
                    f"tenant config {spec!r}: not inline JSON and no "
                    f"such file")
            with open(spec) as f:
                text = f.read()
        return cls.from_json(text)

    @classmethod
    def from_json(cls, text: str) -> "TenantQuotaConfig":
        data = json.loads(text)
        if not isinstance(data, dict):
            raise ValueError("tenant config must be a JSON object")
        known = {"tenants", "default_tenant", "window_s", "share_prefix"}
        unknown = set(data) - known
        if unknown:
            raise ValueError(
                f"unknown tenant config keys {sorted(unknown)}")
        tenants = {}
        for name, body in (data.get("tenants") or {}).items():
            validate_tenant_name(name)
            extra = set(body) - {"min_rate", "max_rate", "slo"}
            if extra:
                raise ValueError(
                    f"tenant {name!r}: unknown keys {sorted(extra)}")
            slo = None
            if body.get("slo") is not None:
                sbody = body["slo"]
                if not isinstance(sbody, dict):
                    raise ValueError(
                        f"tenant {name!r}: slo must be an object")
                sextra = set(sbody) - {"ttft_p99_ms", "tpot_p99_ms",
                                       "goodput_floor"}
                if sextra:
                    raise ValueError(
                        f"tenant {name!r}: unknown slo keys "
                        f"{sorted(sextra)}")
                slo = TenantSloSpec(
                    ttft_p99_ms=float(sbody.get("ttft_p99_ms", 0.0)),
                    tpot_p99_ms=float(sbody.get("tpot_p99_ms", 0.0)),
                    goodput_floor=float(
                        sbody.get("goodput_floor", 0.0)))
                if slo.ttft_p99_ms < 0 or slo.tpot_p99_ms < 0:
                    raise ValueError(
                        f"tenant {name!r}: slo targets must be >= 0")
                if not 0.0 <= slo.goodput_floor < 1.0:
                    raise ValueError(
                        f"tenant {name!r}: goodput_floor must be in "
                        f"[0, 1)")
            tenants[name] = TenantSpec(
                name, min_rate=float(body.get("min_rate", 0.0)),
                max_rate=float(body.get("max_rate", 0.0)), slo=slo)
        return cls(
            tenants=tenants,
            default_tenant=str(data.get("default_tenant",
                                        DEFAULT_TENANT)),
            window_s=float(data.get("window_s", 5.0)),
            share_prefix=bool(data.get("share_prefix", False)))

    # -- identity -------------------------------------------------------
    def resolve(self, tenant: Optional[str]) -> str:
        """Canonical quota identity for a wire tenant: configured names
        pass through, everything else (None included) is the default
        tenant — bounded scheduler state and metric cardinality."""
        if tenant and tenant in self.tenants:
            return tenant
        return self.default_tenant

    def spec(self, tenant: Optional[str]) -> TenantSpec:
        return self.tenants[self.resolve(tenant)]

    def names(self) -> List[str]:
        return sorted(self.tenants)

    def slo_enabled(self) -> bool:
        """True when ANY tenant carries objectives — the single switch
        for the ledger + budget engine (ISSUE 20 acceptance: absent
        config must mean zero new per-tick work)."""
        return any(s.slo is not None for s in self.tenants.values())

    def echo(self) -> dict:
        """Config-echo shape for /stats (fleet drift detection)."""
        out = {
            "default_tenant": self.default_tenant,
            "window_s": self.window_s,
            "share_prefix": self.share_prefix,
            "tenants": {
                n: {"min_rate": s.min_rate, "max_rate": s.max_rate}
                for n, s in sorted(self.tenants.items())},
        }
        for n, s in self.tenants.items():
            if s.slo is not None:
                out["tenants"][n]["slo"] = s.slo.echo()
        return out


def validate_tenant_name(name: str) -> str:
    """Wire-level guard shared by the serving binary and the gateway:
    tenant names become metric labels and prefix-key scopes."""
    if not isinstance(name, str) or not name:
        raise ValueError("tenant must be a non-empty string")
    if len(name) > MAX_TENANT_LEN:
        raise ValueError(
            f"tenant name longer than {MAX_TENANT_LEN} chars")
    if any(c in name for c in "\n\r\"\\"):
        raise ValueError("tenant name contains forbidden characters")
    return name


class TenantScheduler:
    """The weighted pick over the admission queue, plus the rate ledger
    it decides on. Every method takes ``now`` explicitly (the engine
    reads its own clock), so identical call sequences are identical
    decisions — the determinism the bench's byte-identical reruns and
    the property fuzz pin.

    Pick order (``pick``), the two-layer mirror of the pod scheduler:

    1. tenants UNDER min, most-starved first (lowest rate/min) — the
       guarantee: never skipped for any tenant at/over its min;
    2. tenants at/over min and under max (borrowers), lowest
       borrowed-rate / guaranteed-share first — equalizing that ratio
       is what makes realized borrowing proportional to the
       ``guaranteed_overquotas`` shares (the quota/info.py oracle the
       property fuzz compares against);
    3. tenants at/over max — admitted ONLY when no class-1/2 tenant
       has pending work (work conservation: an idle slot is never held
       back by a ceiling), lowest rate/max first.
    """

    def __init__(self, cfg: TenantQuotaConfig):
        self.cfg = cfg
        # per-tenant (t, tokens) marks inside the sliding window
        self._marks: Dict[str, Deque[Tuple[float, int]]] = {}
        self._window_tokens: Dict[str, int] = {}
        self.tokens_total: Dict[str, int] = {
            n: 0 for n in cfg.tenants}
        self.sheds: Dict[str, int] = {}
        self.preempts: Dict[str, Dict[str, int]] = {}

    # -- the rate ledger -----------------------------------------------
    def note_tokens(self, tenant: Optional[str], n: int,
                    now: float) -> None:
        t = self.cfg.resolve(tenant)
        dq = self._marks.get(t)
        if dq is None:
            dq = self._marks[t] = deque()
        dq.append((now, n))
        self._window_tokens[t] = self._window_tokens.get(t, 0) + n
        self.tokens_total[t] = self.tokens_total.get(t, 0) + n
        self._prune(t, now)

    def _prune(self, tenant: str, now: float) -> None:
        dq = self._marks.get(tenant)
        if not dq:
            return
        cutoff = now - self.cfg.window_s
        while dq and dq[0][0] <= cutoff:
            _, n = dq.popleft()
            self._window_tokens[tenant] -= n

    def rate(self, tenant: Optional[str], now: float) -> float:
        """Tokens/s over the sliding window (fixed divisor: a burst
        decays to zero within one window of going idle)."""
        t = self.cfg.resolve(tenant)
        self._prune(t, now)
        return self._window_tokens.get(t, 0) / self.cfg.window_s

    # -- the quota/info.py mirror --------------------------------------
    def _quota_infos(self, now: float) -> QuotaInfos:
        """Tenant specs + live rates as ``QuotaInfos``, one synthetic
        quota per tenant over the RATE_RESOURCE — the pod layer's own
        accounting objects, so aggregated-min / overquota / guaranteed-
        share questions are answered by pkg-identical code."""
        infos = QuotaInfos()
        for name, spec in self.cfg.tenants.items():
            info = QuotaInfo(
                name=name, namespace=name, namespaces={name},
                min={RATE_RESOURCE: spec.min_rate * RATE_SCALE},
                max=({RATE_RESOURCE: spec.max_rate * RATE_SCALE}
                     if spec.max_rate else None),
                used={RATE_RESOURCE: self.rate(name, now) * RATE_SCALE})
            infos.add(info)
        return infos

    def borrow_shares(self, now: float) -> Dict[str, float]:
        """Each tenant's guaranteed slice of the aggregate UNUSED min
        (tokens/s) — literally ``QuotaInfos.guaranteed_overquotas``
        over the synthetic rate quotas, so this layer's notion of a
        fair borrow share is the pod layer's, floored at the same
        granularity (milli-tokens/s after RATE_SCALE)."""
        infos = self._quota_infos(now)
        return {
            name: infos.guaranteed_overquotas(name).get(
                RATE_RESOURCE, 0.0) / RATE_SCALE
            for name in self.cfg.tenants}

    # -- classification -------------------------------------------------
    def under_min(self, tenant: Optional[str], now: float) -> bool:
        spec = self.cfg.spec(tenant)
        return spec.min_rate > 0 \
            and self.rate(tenant, now) < spec.min_rate

    def over_min(self, tenant: Optional[str], now: float) -> bool:
        """Strictly above the guarantee — the preemptible class: a
        reclaim never victimizes a tenant within its min."""
        return self.rate(tenant, now) > self.cfg.spec(tenant).min_rate

    def over_max(self, tenant: Optional[str], now: float) -> bool:
        spec = self.cfg.spec(tenant)
        return spec.max_rate > 0 \
            and self.rate(tenant, now) >= spec.max_rate

    def over_quota_ratio(self, tenant: Optional[str], now: float,
                         shares: Optional[Dict[str, float]] = None
                         ) -> float:
        """How far past the guarantee a tenant is running, normalized
        by its fair borrow share — the victim-ordering key for reclaim
        (largest ratio = most over quota = preempted first). Pass a
        precomputed ``borrow_shares(now)`` when ranking several
        tenants in one pass; each shares build walks the QuotaInfos
        aggregates and must not be repaid per victim."""
        spec = self.cfg.spec(tenant)
        over = max(0.0, self.rate(tenant, now) - spec.min_rate)
        if shares is None:
            shares = self.borrow_shares(now)
        share = shares.get(self.cfg.resolve(tenant), 0.0)
        return over / max(share, 1e-9)

    # -- the pick -------------------------------------------------------
    def pick(self, candidates: Iterable[str], now: float
             ) -> Optional[str]:
        """Which tenant's request admits next, among tenants with
        pending work. Never None for a non-empty candidate set (work
        conservation); ties break by name for determinism."""
        cands = sorted(set(self.cfg.resolve(c) for c in candidates))
        if not cands:
            return None
        shares = self.borrow_shares(now)

        def key(t: str):
            spec = self.cfg.tenants[t]
            r = self.rate(t, now)
            if spec.min_rate > 0 and r < spec.min_rate:
                return (0, r / spec.min_rate, t)
            if spec.max_rate > 0 and r >= spec.max_rate:
                return (2, r / spec.max_rate, t)
            over = max(0.0, r - spec.min_rate)
            return (1, over / max(shares.get(t, 0.0), 1e-9), t)

        return min(cands, key=key)

    # -- shed/preempt bookkeeping (the engine's counters) ---------------
    def note_shed(self, tenant: Optional[str]) -> None:
        t = self.cfg.resolve(tenant)
        self.sheds[t] = self.sheds.get(t, 0) + 1

    def note_preempt(self, tenant: Optional[str], mode: str) -> None:
        t = self.cfg.resolve(tenant)
        per = self.preempts.setdefault(t, {"swap": 0, "recompute": 0})
        per[mode] = per.get(mode, 0) + 1

    # -- introspection --------------------------------------------------
    def snapshot(self, now: float) -> dict:
        """/stats ``tenants`` section + the loop's gauge mirror: one
        row per configured tenant. The gateway sums ``rate`` across
        replicas for its fleet-wide door admission."""
        shares = self.borrow_shares(now)
        out = {}
        for name, spec in sorted(self.cfg.tenants.items()):
            r = self.rate(name, now)
            out[name] = {
                "rate_tokens_per_s": round(r, 3),
                "min_rate": spec.min_rate,
                "max_rate": spec.max_rate,
                "borrowed_tokens_per_s": round(
                    max(0.0, r - spec.min_rate), 3),
                "borrow_share": round(shares.get(name, 0.0), 3),
                "under_min": bool(spec.min_rate > 0
                                  and r < spec.min_rate),
                "over_max": bool(spec.max_rate > 0
                                 and r >= spec.max_rate),
                "tokens_total": self.tokens_total.get(name, 0),
                "sheds": self.sheds.get(name, 0),
                "preempts": dict(self.preempts.get(
                    name, {"swap": 0, "recompute": 0})),
            }
        return out
