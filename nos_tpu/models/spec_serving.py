"""Speculative decoding inside the continuous-batching engine.

``SpeculativeDecodeServer`` extends ``DecodeServer`` with a draft model:
every tick, the draft proposes ``n_draft`` tokens per slot (n_draft
sequential small forwards), the target verifies them all in ONE wide
forward (the same weight traffic as a single decode step — the
bandwidth economics of models/speculative.py), and each row commits its
own accepted prefix plus, on the first rejection, the verified
correction token — up to ``n_draft`` tokens per tick (a full accept
commits all n_draft proposals; there is no bonus token, matching
speculative_generate). The slot
engine's per-row ``pos`` removes speculative_generate's batching
compromise: that API must advance every row uniformly by the MINIMUM
acceptance (a single scalar pos), while slots advance independently —
a row that accepted 3 of 4 commits those 3 plus its correction token
while its neighbour commits 1.

Exactness contract (same as models/speculative.py, per row):
- greedy rows (temperature 0) are bit-identical to plain decoding of
  the target model;
- sampled rows use accept-reject speculative sampling — every committed
  token is distributed exactly as target-only sampling, with the RNG
  keyed by (seed, absolute position, sub-stream) so a row's output is
  independent of batch composition. (The sample PATH differs from the
  non-speculative engine's — same distribution, different draws — so a
  seeded sampled request is reproducible against THIS engine, not
  token-equal to DecodeServer's.)

Rollback is position arithmetic: the verify pass writes k cache entries
per row, and per-row ``pos`` is then set to the committed length —
entries beyond pos are masked out of attention and overwritten by later
writes ("only pos decides what exists"). The draft keeps its own
per-row-pos KV cache, maintained under the same invariant as the
target's: processed == committed[:-1], ``last`` is the newest committed
token, not yet fed.
"""
from __future__ import annotations

import functools

from collections import deque
from typing import Optional

import jax
import jax.numpy as jnp

from nos_tpu.models.generate import (
    _truncate_logits_rows, forward_with_cache, init_cache,
)
from nos_tpu.models.serving import DecodeServer, _bucket
from nos_tpu.models.transformer import Params, TransformerConfig

__all__ = ["SpeculativeDecodeServer"]


def _row_dist(logits, temp, topk, topp):
    """Per-row tempered + truncated sampling distribution [B, V] — the
    distribution the plain engine samples from (serving's per-slot twin
    of speculative._dist)."""
    return jax.nn.softmax(
        _truncate_logits_rows(logits / jnp.maximum(temp, 1e-6)[:, None],
                              topk, topp), axis=-1)


def _sample_rows(keys, probs):
    logp = jnp.where(probs > 0, jnp.log(jnp.maximum(probs, 1e-38)),
                     -jnp.inf)
    return jax.vmap(jax.random.categorical)(keys, logp)


class SpeculativeDecodeServer(DecodeServer):
    """DecodeServer with draft-verified ticks. ``step()`` emits UP TO
    ``n_draft`` tokens per active slot per tick instead of one."""

    def __init__(self, params: Params, cfg: TransformerConfig,
                 draft_params: Params, draft_cfg: TransformerConfig,
                 *, n_draft: int = 4, max_batch: int = 8,
                 max_len: Optional[int] = None, **kw):
        if draft_cfg.vocab != cfg.vocab:
            raise ValueError("draft and target must share a vocabulary")
        # the speculative engine pins pipeline_depth=1 / decode_steps=1:
        # a spec tick already commits a variable-length burst (up to
        # n_draft tokens) per dispatch, and the submit-time headroom
        # guard below budgets exactly ONE un-rolled-back verify window
        # (k positions) past the committed prefix — k ticks in flight
        # would need k*n_draft headroom and buy little on top of the
        # burst amortization the draft/verify split already provides.
        # Operator configs (nos-tpu-server flags) apply to both engines,
        # so the knobs are accepted here and clamped, not rejected.
        kw["pipeline_depth"] = 1
        kw["decode_steps"] = 1
        # paged KV clamps off likewise: the draft model keeps its own
        # per-row-pos KV cache, and paging BOTH caches (plus the verify
        # window's k-position rollback discipline over block tables) is
        # the ROADMAP follow-up that also unpins the pipeline knobs —
        # until then the spec engine stays slot-static.
        kw["kv_blocks"] = 0
        kw["kv_block_size"] = 0
        super().__init__(params, cfg, max_batch=max_batch,
                         max_len=max_len, **kw)
        self.draft_params = draft_params
        self.draft_cfg = draft_cfg
        self.k = max(1, int(n_draft))
        self.d_cache = init_cache(draft_cfg, max_batch, self.max_len,
                                  per_row_pos=True)
        self._chunked_drow: dict = {}   # rid -> chunk-prefilled draft row
        self._d_row_shd = None
        if self.mesh is not None:
            from nos_tpu.models.generate import cache_shardings
            d_shd = cache_shardings(self.mesh, draft_cfg, per_row_pos=True)
            self.d_cache = jax.device_put(self.d_cache, d_shd)
            self._d_row_shd = d_shd["k"]
        k = self.k

        def spec_tick(p, dp, last, t_cache, d_cache, keep, temp, topk,
                      topp, seeds, sampling: bool):
            t_pos0 = t_cache["pos"]
            d_pos0 = d_cache["pos"]
            b = last.shape[0]

            def row_keys(offs, stream):
                # (seed, absolute position, sub-stream) keying: position
                # of the token being produced is t_pos0 + 1 + i; streams
                # 0/1/2 = draft draw / accept u / residual draw
                return jax.vmap(
                    lambda s, q: jax.random.fold_in(
                        jax.random.PRNGKey(s), q * 4 + stream)
                )(seeds, t_pos0 + 1 + offs)

            # 1. draft proposes k tokens autoregressively
            drafts, qs = [], []
            tok = last
            for i in range(k):
                dlogits, d_cache = forward_with_cache(
                    dp, self.draft_cfg, tok, d_cache)
                step_logits = dlogits[:, -1]
                nxt = jnp.argmax(step_logits, axis=-1)
                if sampling:
                    q = _row_dist(step_logits, temp, topk, topp)
                    drawn = _sample_rows(row_keys(i, 0), q)
                    nxt = jnp.where(temp > 0, drawn, nxt)
                    qs.append(q)
                tok = nxt[:, None]
                drafts.append(nxt)
            proposed = jnp.stack(drafts, axis=1)            # [B, k]

            # 2. target verifies in one pass: logits[:, i] is the
            # target's verdict on proposed[:, i]
            feed = jnp.concatenate([last, proposed[:, :-1]], axis=1)
            tlogits, t_cache = forward_with_cache(p, self.cfg, feed,
                                                  t_cache)
            greedy = jnp.argmax(tlogits, axis=-1)           # [B, k]
            if sampling:
                pdist = jax.vmap(_row_dist, in_axes=(1, None, None, None),
                                 out_axes=1)(tlogits, temp, topk, topp)
                qdist = jnp.stack(qs, axis=1)               # [B, k, V]
                px = jnp.take_along_axis(
                    pdist, proposed[..., None], -1)[..., 0]
                qx = jnp.take_along_axis(
                    qdist, proposed[..., None], -1)[..., 0]
                # one accept-u vector per row, keyed at the round's first
                # produced position (stream 1); u[i] gates proposed[:, i]
                u = jax.vmap(
                    lambda key: jax.random.uniform(key, (k,))
                )(row_keys(0, 1))
                accept_sampled = u * qx < px
                accept = jnp.where((temp > 0)[:, None], accept_sampled,
                                   proposed == greedy)
            else:
                accept = proposed == greedy

            # 3. per-row accepted-prefix length a in [0, k]
            a = jnp.argmin(
                jnp.concatenate([accept, jnp.zeros((b, 1), bool)], axis=1),
                axis=1)
            full = a == k
            # correction token at the first rejection: target argmax
            # (greedy) or a residual draw (sampling); full-accept rows
            # need none (committed = all k proposals, no bonus token —
            # matching speculative_generate)
            a_idx = jnp.minimum(a, k - 1)
            corr = jnp.take_along_axis(greedy, a_idx[:, None], 1)[:, 0]
            if sampling:
                p_a = jnp.take_along_axis(
                    pdist, a_idx[:, None, None], 1)[:, 0]   # [B, V]
                q_a = jnp.take_along_axis(
                    qdist, a_idx[:, None, None], 1)[:, 0]
                resid = jnp.maximum(p_a - q_a, 0.0)
                norm = jnp.sum(resid, axis=-1, keepdims=True)
                resid = jnp.where(norm > 0, resid / norm, p_a)
                corr_s = _sample_rows(row_keys(a_idx, 2), resid)
                corr = jnp.where(temp > 0, corr_s, corr)

            # 4. committed tokens [B, k]: proposed[:a], then corr, then
            # dead padding; counts c = k (full accept) | a + 1
            c = jnp.where(full, k, a + 1)                   # [B]
            j = jnp.arange(k)[None, :]
            # full-accept rows (a == k) fall out naturally: j < a holds
            # for every column, so commit == proposed with no special case
            commit = jnp.where(
                j < a[:, None], proposed,
                jnp.where(j == a[:, None], corr[:, None], 0))
            # new last = final committed token per row
            new_last = jnp.take_along_axis(
                commit, (c - 1)[:, None], 1)                # [B, 1]
            last = jnp.where(keep[:, None], new_last, last)

            # 5. rollback-by-position: processed == committed[:-1]
            t_cache["pos"] = jnp.where(keep, t_pos0 + c, t_pos0)
            d_cache["pos"] = jnp.where(keep, d_pos0 + c, d_pos0)
            return commit, c, last, t_cache, d_cache

        self._spec_tick = jax.jit(spec_tick, donate_argnums=(3, 4),
                                  static_argnums=(10,))

        def d_prefill(dp, toks, row):
            return forward_with_cache(dp, self.draft_cfg, toks, row)

        self._d_prefill = jax.jit(d_prefill)

        def d_install(cache, rk, rv, slot, plen):
            cache["k"] = jax.lax.dynamic_update_slice(
                cache["k"], rk, (0, slot, 0, 0, 0))
            cache["v"] = jax.lax.dynamic_update_slice(
                cache["v"], rv, (0, slot, 0, 0, 0))
            cache["pos"] = cache["pos"].at[slot].set(plen)
            return cache

        self._d_install = jax.jit(d_install, donate_argnums=(0,))

    # ------------------------------------------------------------------
    def submit(self, prompt, max_new_tokens, **kw) -> int:
        # headroom: a verify round writes up to k positions past the
        # committed prefix before rolling back-by-position; without this
        # the per-row dynamic_update_slice would CLAMP near max_len and
        # silently overwrite valid KV (same guard as
        # speculative_generate's s + max_new + k check)
        if prompt and len(prompt) + max_new_tokens + self.k > self.max_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) + draft window ({self.k}) exceeds "
                f"cache length {self.max_len}")
        return super().submit(prompt, max_new_tokens, **kw)

    def _run_d_prefill(self, toks, row):
        """Draft prefill with the base engine's first-dispatch-per-shape
        compile accounting (keyed apart from target prefill)."""
        return self._timed_dispatch(
            ("d_prefill", toks.shape[1], row["k"].shape[3]),
            self._d_prefill, self.draft_params, toks, row)

    @functools.lru_cache(maxsize=None)      # noqa: B019 — engine-lived
    def _d_row_zeros(self, bucket: int):
        shape = list(self.d_cache["k"].shape)
        shape[1], shape[3] = 1, bucket
        z = jnp.zeros(tuple(shape), self.d_cache["k"].dtype)
        if self._d_row_shd is not None:
            # same head sharding as d_cache: draft prefill runs sharded
            # and the draft install never gathers (mirrors _row_zeros)
            z = jax.device_put(z, self._d_row_shd)
        return z

    def _start_chunked_prefill(self, req, m, mkey) -> bool:
        """Chunk the DRAFT cache alongside the target: the per-tick cost
        stays one target chunk + one (much cheaper) draft chunk, so the
        head-of-line bound chunked prefill promises holds under
        speculative decoding too — no whole-prompt draft forward spikes
        on the install tick. The draft has no prefix cache, so its
        chunks cover the full prompt."""
        if not super()._start_chunked_prefill(req, m, mkey):
            return False
        ent = self._prefilling[-1]
        chunk = self._prefill_chunk
        plen = len(req.prompt)
        bucket = min(_bucket(plen), self.max_len)
        ent["drow"] = {
            "k": self._d_row_zeros(bucket),
            "v": self._d_row_zeros(bucket),
            "pos": jnp.zeros((), jnp.int32),
        }
        ent["dtodo"] = deque(req.prompt[i:i + chunk]
                             for i in range(0, plen, chunk))
        return True

    def _prefill_advance(self, ent) -> bool:
        if ent["todo"]:
            super()._prefill_advance(ent)       # one target chunk
        if ent["dtodo"]:                        # one draft chunk
            toks_list = ent["dtodo"].popleft()
            rem = len(toks_list)
            rbucket = _bucket(rem) if not ent["dtodo"] else rem
            toks = jnp.asarray([toks_list + [0] * (rbucket - rem)],
                               jnp.int32)
            _, ent["drow"] = self._run_d_prefill(toks, ent["drow"])
        if ent["todo"] or ent["dtodo"]:
            return False
        # hand the chunk-prefilled draft row to _finish_prefill (keyed
        # by rid: _prefilling order and recursion-safe)
        self._chunked_drow[ent["req"].rid] = ent["drow"]
        return True

    def _finish_prefill(self, req, row, step) -> None:
        # draft install FIRST: the request may finish inside the super
        # call (stop token / max_new=1), releasing the slot and
        # recursively admitting a pending request into it — a stale
        # draft install landing afterwards would overwrite the NEW
        # request's draft row (no prefix cache here: published entries
        # hold TARGET KV). The draft row arrives chunk-prefilled from
        # _prefill_advance, or is prefilled whole here on the one-shot
        # (short prompt) path.
        slot = req.slot
        plen = len(req.prompt)
        drow = self._chunked_drow.pop(req.rid, None)
        if drow is None:
            bucket = min(_bucket(plen), self.max_len)
            toks = jnp.asarray([req.prompt + [0] * (bucket - plen)],
                               jnp.int32)
            drow = {
                "k": self._d_row_zeros(bucket),
                "v": self._d_row_zeros(bucket),
                "pos": jnp.zeros((), jnp.int32),
            }
            _, drow = self._run_d_prefill(toks, drow)
        self.d_cache = self._d_install(
            self.d_cache, drow["k"], drow["v"], jnp.int32(slot),
            jnp.int32(plen))
        super()._finish_prefill(req, row, step)

    def _finish_if_done(self, req, admit: bool = True) -> None:
        if req.done and req.slot >= 0:
            self.d_cache["pos"] = self.d_cache["pos"].at[req.slot].set(0)
        super()._finish_if_done(req, admit)

    def _resume_draft(self, req, seq) -> None:
        """Supervised-restart resume for the DRAFT cache: re-prefill it
        over the same committed sequence the target resume installs
        (``prompt + out[:-1]``) so the draft invariant — processed ==
        committed[:-1], pos == committed length - 1 fed next — holds in
        the rebuilt engine exactly as it did before the failure. The
        draft's re-prefilled KV is bit-identical to the incrementally
        built one (chunking invariance), so greedy accept/reject
        decisions — and therefore committed tokens — are undisturbed."""
        n = len(seq)
        bucket = min(_bucket(n), self.max_len)
        toks = jnp.asarray([seq + [0] * (bucket - n)], jnp.int32)
        drow = {
            "k": self._d_row_zeros(bucket),
            "v": self._d_row_zeros(bucket),
            "pos": jnp.zeros((), jnp.int32),
        }
        _, drow = self._run_d_prefill(toks, drow)
        self.d_cache = self._d_install(
            self.d_cache, drow["k"], drow["v"], jnp.int32(req.slot),
            jnp.int32(n))

    # ------------------------------------------------------------------
    def _dispatch(self, active, keep, sampling):
        """One speculative dispatch: up to k tokens per active slot.
        The base step() template owns the scaffolding (mid-prefill slot
        exclusion, keep mask, in-flight window — pinned to depth 1 here —
        async fetch, prefill tick)."""
        commit, counts, self._last, self.cache, self.d_cache = \
            self._spec_tick(
                self.params, self.draft_params, self._last, self.cache,
                self.d_cache, keep, self._temp, self._topk, self._topp,
                self._seed, sampling)
        return commit, counts

    def _consume_payload(self, ent, host, now: float = 0.0) -> int:
        commit_host, counts_host = host
        emitted = 0
        for s in ent.slots:
            req = self._active.get(s)
            if req is None or req.done:
                continue
            n = 0
            for j in range(int(counts_host[s])):
                req.out.append(int(commit_host[s, j]))
                req.note_token()
                emitted += 1
                n += 1
                if req.done:
                    break
            if n and now:
                # a verify burst lands up to k tokens at one host
                # instant: the shared ledger template attributes the
                # arrival gap evenly across them (see _Ledger)
                req.led.note_tokens(n, now)
            self._finish_if_done(req, admit=False)
        return emitted
