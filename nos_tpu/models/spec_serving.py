"""Speculative decoding inside the continuous-batching engine.

``SpeculativeDecodeServer`` extends ``DecodeServer`` with a draft model:
every tick, the draft proposes ``n_draft`` tokens per slot (n_draft
sequential small forwards), the target verifies them all in ONE wide
forward (the same weight traffic as a single decode step — the
bandwidth economics of models/speculative.py), and each row commits its
own accepted prefix plus, on the first rejection, the verified
correction token — up to ``n_draft`` tokens per tick (a full accept
commits all n_draft proposals; there is no bonus token, matching
speculative_generate). The slot
engine's per-row ``pos`` removes speculative_generate's batching
compromise: that API must advance every row uniformly by the MINIMUM
acceptance (a single scalar pos), while slots advance independently —
a row that accepted 3 of 4 commits those 3 plus its correction token
while its neighbour commits 1.

Fast path (this PR's tentpole): the engine rides the full dispatch
template instead of pinning it —

- ``pipeline_depth=k``: up to k draft+verify dispatches in flight; the
  draft burst for window k+1 speculates on-device while verify k's
  tokens are still in transit to the host. Accept/reject and the
  resulting pos advance happen IN-GRAPH, so a rejection needs no host
  round-trip: dispatch k+1 reads the committed pos dispatch k wrote.
  The only host-side rollback is the pipeline one every engine shares —
  completions observed late reset pos ("only pos decides what exists").
- ``decode_steps=T``: T draft+verify rounds fused into ONE dispatch
  (lax.scan), [B, T, n_draft] committed tokens per device->host sync.
- paged KV (``kv_blocks > 0``): target AND draft caches live in pooled
  arenas with per-slot block tables. The draft pool mirrors the
  target's block count; draft blocks are always exclusively owned (no
  prefix sharing; ``fork`` copies the committed draft blocks outright —
  the draft writes every tick, so COW would copy on the next dispatch
  anyway). A verify window that rolled back leaves speculated-ahead
  writes in tail blocks past the committed prefix: once the in-flight
  window drains, those tails are freed and their table entries zeroed
  back to the null block (``_trim_spec_tails``) so the pool, a fork,
  and a swap capture all see exactly the committed footprint.
  ``kv_dtype="int8"`` applies to both arenas.

Exactness contract (same as models/speculative.py, per row):
- greedy rows (temperature 0) are bit-identical to plain decoding of
  the target model — at every (pipeline_depth, decode_steps), paged or
  slot-static, across COW forks and preempt-and-resume (tested);
- sampled rows use accept-reject speculative sampling — every committed
  token is distributed exactly as target-only sampling, with the RNG
  keyed by (seed, absolute position, sub-stream) so a row's output is
  independent of batch composition AND of the dispatch knobs. (The
  sample PATH differs from the non-speculative engine's — same
  distribution, different draws — so a seeded sampled request is
  reproducible against THIS engine, not token-equal to DecodeServer's.)

Rollback is position arithmetic: the verify pass writes k cache entries
per row, and per-row ``pos`` is then set to the committed length —
entries beyond pos are masked out of attention and overwritten by later
writes ("only pos decides what exists"). The draft keeps its own
per-row-pos KV cache, maintained under the same invariant as the
target's: processed == committed[:-1], ``last`` is the newest committed
token, not yet fed.
"""
from __future__ import annotations

import functools

from collections import deque
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from nos_tpu.models.generate import (
    _truncate_logits_rows, forward_paged, forward_with_cache, init_cache,
    init_paged_cache, replicated_logits,
)
from nos_tpu.models.kvblocks import (
    BlockAllocator, NoFreeBlocks, ScaleLedger, blocks_for,
)
from nos_tpu.models.serving import DecodeServer, QueueFull, _bucket
from nos_tpu.models.transformer import Params, TransformerConfig

__all__ = ["SpeculativeDecodeServer"]


def _row_dist(logits, temp, topk, topp):
    """Per-row tempered + truncated sampling distribution [B, V] — the
    distribution the plain engine samples from (serving's per-slot twin
    of speculative._dist)."""
    return jax.nn.softmax(
        _truncate_logits_rows(logits / jnp.maximum(temp, 1e-6)[:, None],
                              topk, topp), axis=-1)


def _sample_rows(keys, probs):
    logp = jnp.where(probs > 0, jnp.log(jnp.maximum(probs, 1e-38)),
                     -jnp.inf)
    return jax.vmap(jax.random.categorical)(keys, logp)


class SpeculativeDecodeServer(DecodeServer):
    """DecodeServer with draft-verified ticks. Each fused round emits UP
    TO ``n_draft`` tokens per active slot; a dispatch fuses
    ``decode_steps`` rounds and up to ``pipeline_depth`` dispatches fly
    before the host blocks — the template's economics, unpinned."""

    def __init__(self, params: Params, cfg: TransformerConfig,
                 draft_params: Params, draft_cfg: TransformerConfig,
                 *, n_draft: int = 4, max_batch: int = 8,
                 max_len: Optional[int] = None, **kw):
        if draft_cfg.vocab != cfg.vocab:
            raise ValueError("draft and target must share a vocabulary")
        if kw.get("role", "colocated") == "prefill":
            raise ValueError(
                "speculative decoding on a prefill-role engine is "
                "pointless: a prefill replica never decodes, so the "
                "draft would only burn HBM. Run the draft on the "
                "decode side (role=decode adopts handoffs and "
                "re-prefills the draft from the committed sequence) or "
                "colocated")
        super().__init__(params, cfg, max_batch=max_batch,
                         max_len=max_len, **kw)
        self.draft_params = draft_params
        self.draft_cfg = draft_cfg
        self.k = max(1, int(n_draft))
        # speculation observability: proposals drafted vs accepted by
        # verify (the engine-side truth nos_tpu_serve_spec_*_total
        # mirrors), plus per-verify-window accepted counts parked for
        # the serving loop's histogram (FIFO-capped like compile
        # events: a library caller that never drains must not leak)
        self.spec_drafted = 0
        self.spec_accepted = 0
        self.spec_window_events: List[int] = []
        self._chunked_drow: dict = {}   # rid -> chunk-prefilled draft row
        # rid -> draft blocks reserved at chunked-admission start (the
        # draft twin of the base class's req.reserved_blocks): chunked
        # prefill spans ticks during which decoders GROW draft blocks,
        # and an install that found the draft pool dry mid-admission
        # would have no good answer — NoFreeBlocks escaping step()
        # would kill the serving loop
        self._chunked_dreserved: dict = {}
        self._d_row_shd = None
        if self.paged:
            # the draft's own pooled arena: same block geometry as the
            # target's (draft and target timelines advance in lockstep,
            # and the draft has no prefix sharing, so its worst-case
            # block need per slot equals the target's)
            self._d_alloc = BlockAllocator(self._alloc.num_blocks,
                                           self.kv_block_size)
            self._d_scales: Optional[ScaleLedger] = None
            if self.kv_dtype == "int8":
                self._d_scales = ScaleLedger()
                self._d_alloc.scale_ledger = self._d_scales
            self.d_cache = init_paged_cache(
                draft_cfg, self._alloc.num_blocks, self.kv_block_size,
                max_batch, kv_dtype=self.kv_dtype)
            self._d_table = jnp.zeros((max_batch, self._nbs), jnp.int32)
            self._d_tables: List[List[int]] = [
                [] for _ in range(max_batch)]
            self._d_deferred: List[int] = []
        else:
            self.d_cache = init_cache(draft_cfg, max_batch, self.max_len,
                                      per_row_pos=True)
        if self.mesh is not None:
            from nos_tpu.models.generate import (
                cache_shardings, paged_cache_shardings,
            )
            if self.paged:
                # draft + target arenas shard in LOCKSTEP over tp: the
                # draft arena takes the same KV-head-axis sharding as
                # the target's (paged_cache_shardings validates the
                # draft's head divisibility), its device block table
                # stays a replicated host-written control row, and the
                # draft scratch prefill row carries the target
                # convention's head sharding so installs never gather.
                self.d_cache = jax.device_put(
                    self.d_cache,
                    paged_cache_shardings(self.mesh, draft_cfg,
                                          kv_dtype=self.kv_dtype))
                self._d_row_shd = cache_shardings(
                    self.mesh, draft_cfg, per_row_pos=True)["k"]
                self._d_table = jax.device_put(self._d_table, self._rep)
            else:
                d_shd = cache_shardings(self.mesh, draft_cfg,
                                        per_row_pos=True)
                self.d_cache = jax.device_put(self.d_cache, d_shd)
                self._d_row_shd = d_shd["k"]
        k = self.k
        T = self.decode_steps

        def spec_round(p, dp, last, t_cache, d_cache, t_fwd, d_fwd, keep,
                       temp, topk, topp, seeds, sampling: bool):
            """ONE draft+verify round: propose k, verify in one wide
            forward, commit the accepted prefix (+ correction), roll
            back by pos. ``t_fwd``/``d_fwd`` close over the cache
            flavour (slot-static forward_with_cache or forward_paged
            with the block table), so the accept/reject math is ONE
            implementation across both."""
            t_pos0 = t_cache["pos"]
            d_pos0 = d_cache["pos"]
            b = last.shape[0]

            def row_keys(offs, stream):
                # (seed, absolute position, sub-stream) keying: position
                # of the token being produced is t_pos0 + 1 + i; streams
                # 0/1/2 = draft draw / accept u / residual draw
                return jax.vmap(
                    lambda s, q: jax.random.fold_in(
                        jax.random.PRNGKey(s), q * 4 + stream)
                )(seeds, t_pos0 + 1 + offs)

            # 1. draft proposes k tokens autoregressively
            drafts, qs = [], []
            tok = last
            for i in range(k):
                dlogits, d_cache = d_fwd(dp, tok, d_cache)
                # canonicalize every SAMPLING-decision row (see
                # generate.replicated_logits): under a mesh the vocab-
                # sharded logits would partition categorical's RNG
                # lowering, drawing different bits than the single-host
                # run — the paged arena's sharding propagation tickles
                # this where the slot-static layout happened not to.
                # Identity on values single-host.
                step_logits = replicated_logits(dlogits[:, -1],
                                                self.mesh)
                nxt = jnp.argmax(step_logits, axis=-1)
                if sampling:
                    q = _row_dist(step_logits, temp, topk, topp)
                    drawn = _sample_rows(row_keys(i, 0), q)
                    nxt = jnp.where(temp > 0, drawn, nxt)
                    qs.append(q)
                tok = nxt[:, None]
                drafts.append(nxt)
            proposed = jnp.stack(drafts, axis=1)            # [B, k]

            # 2. target verifies in one pass: logits[:, i] is the
            # target's verdict on proposed[:, i]
            feed = jnp.concatenate([last, proposed[:, :-1]], axis=1)
            tlogits, t_cache = t_fwd(p, feed, t_cache)
            tlogits = replicated_logits(tlogits, self.mesh)
            greedy = jnp.argmax(tlogits, axis=-1)           # [B, k]
            if sampling:
                pdist = jax.vmap(_row_dist, in_axes=(1, None, None, None),
                                 out_axes=1)(tlogits, temp, topk, topp)
                qdist = jnp.stack(qs, axis=1)               # [B, k, V]
                px = jnp.take_along_axis(
                    pdist, proposed[..., None], -1)[..., 0]
                qx = jnp.take_along_axis(
                    qdist, proposed[..., None], -1)[..., 0]
                # one accept-u vector per row, keyed at the round's first
                # produced position (stream 1); u[i] gates proposed[:, i]
                u = jax.vmap(
                    lambda key: jax.random.uniform(key, (k,))
                )(row_keys(0, 1))
                accept_sampled = u * qx < px
                accept = jnp.where((temp > 0)[:, None], accept_sampled,
                                   proposed == greedy)
            else:
                accept = proposed == greedy

            # 3. per-row accepted-prefix length a in [0, k]
            a = jnp.argmin(
                jnp.concatenate([accept, jnp.zeros((b, 1), bool)], axis=1),
                axis=1)
            full = a == k
            # correction token at the first rejection: target argmax
            # (greedy) or a residual draw (sampling); full-accept rows
            # need none (committed = all k proposals, no bonus token —
            # matching speculative_generate)
            a_idx = jnp.minimum(a, k - 1)
            corr = jnp.take_along_axis(greedy, a_idx[:, None], 1)[:, 0]
            if sampling:
                p_a = jnp.take_along_axis(
                    pdist, a_idx[:, None, None], 1)[:, 0]   # [B, V]
                q_a = jnp.take_along_axis(
                    qdist, a_idx[:, None, None], 1)[:, 0]
                resid = jnp.maximum(p_a - q_a, 0.0)
                norm = jnp.sum(resid, axis=-1, keepdims=True)
                resid = jnp.where(norm > 0, resid / norm, p_a)
                corr_s = _sample_rows(row_keys(a_idx, 2), resid)
                corr = jnp.where(temp > 0, corr_s, corr)

            # 4. committed tokens [B, k]: proposed[:a], then corr, then
            # dead padding; counts c = k (full accept) | a + 1
            c = jnp.where(full, k, a + 1)                   # [B]
            j = jnp.arange(k)[None, :]
            # full-accept rows (a == k) fall out naturally: j < a holds
            # for every column, so commit == proposed with no special case
            commit = jnp.where(
                j < a[:, None], proposed,
                jnp.where(j == a[:, None], corr[:, None], 0))
            # new last = final committed token per row
            new_last = jnp.take_along_axis(
                commit, (c - 1)[:, None], 1)                # [B, 1]
            last = jnp.where(keep[:, None], new_last, last)

            # 5. rollback-by-position: processed == committed[:-1]
            t_cache["pos"] = jnp.where(keep, t_pos0 + c, t_pos0)
            d_cache["pos"] = jnp.where(keep, d_pos0 + c, d_pos0)
            return commit, c, a, last, t_cache, d_cache

        def spec_core(p, dp, last, t_cache, d_cache, t_fwd, d_fwd, keep,
                      temp, topk, topp, seeds, sampling: bool):
            # T == 1 keeps the unscanned program; T > 1 fuses T rounds
            # into ONE dispatch via lax.scan — per-round ops identical,
            # so greedy stays bit-exact at any T (each round reads the
            # pos the previous round committed: rejections resolve
            # in-graph, never on the host). Arrivals come back
            # [B, T, k] committed tokens + [B, T] counts/accepted.
            if T == 1:
                commit, c, a, last, t_cache, d_cache = spec_round(
                    p, dp, last, t_cache, d_cache, t_fwd, d_fwd, keep,
                    temp, topk, topp, seeds, sampling)
                return (commit[:, None], c[:, None], a[:, None], last,
                        t_cache, d_cache)

            def body(carry, _):
                last, t_cache, d_cache = carry
                commit, c, a, last, t_cache, d_cache = spec_round(
                    p, dp, last, t_cache, d_cache, t_fwd, d_fwd, keep,
                    temp, topk, topp, seeds, sampling)
                return (last, t_cache, d_cache), (commit, c, a)

            (last, t_cache, d_cache), (commits, cs, accs) = jax.lax.scan(
                body, (last, t_cache, d_cache), None, length=T)
            return (commits.transpose(1, 0, 2), cs.swapaxes(0, 1),
                    accs.swapaxes(0, 1), last, t_cache, d_cache)

        if self.paged:
            def spec_tick_paged(p, dp, last, t_cache, d_cache, t_table,
                                d_table, keep, temp, topk, topp, seeds,
                                sampling: bool):
                # inactive rows' tables zero to the reserved null block
                # (both caches): their in-graph writes land somewhere
                # no active row ever reads
                t_table = jnp.where(keep[:, None], t_table, 0)
                d_table = jnp.where(keep[:, None], d_table, 0)
                return spec_core(
                    p, dp, last, t_cache, d_cache,
                    # ONE formulation end to end: draft decode steps
                    # (S == 1) and target verify bursts (S == k) trace
                    # the engine's captured paged_kernel — under the
                    # fused kernel the S>1 verify window accumulates
                    # exactly what sequential kernel decode would (see
                    # forward_paged), which is what keeps this engine's
                    # greedy-equals-plain-decoding contract intact.
                    # mesh plumbs through for the kernel's shard_map
                    # (both arenas shard their head axis over tp).
                    lambda pp, t, c: forward_paged(
                        pp, self.cfg, t, c, t_table,
                        paged_impl=self.paged_kernel, mesh=self.mesh),
                    lambda pp, t, c: forward_paged(
                        pp, self.draft_cfg, t, c, d_table,
                        paged_impl=self.paged_kernel, mesh=self.mesh),
                    keep, temp, topk, topp, seeds, sampling)

            self._spec_tick = jax.jit(spec_tick_paged,
                                      donate_argnums=(3, 4),
                                      static_argnums=(12,))
        else:
            def spec_tick(p, dp, last, t_cache, d_cache, keep, temp,
                          topk, topp, seeds, sampling: bool):
                return spec_core(
                    p, dp, last, t_cache, d_cache,
                    lambda pp, t, c: forward_with_cache(pp, self.cfg,
                                                        t, c),
                    lambda pp, t, c: forward_with_cache(
                        pp, self.draft_cfg, t, c),
                    keep, temp, topk, topp, seeds, sampling)

            self._spec_tick = jax.jit(spec_tick, donate_argnums=(3, 4),
                                      static_argnums=(10,))

        def d_prefill(dp, toks, row):
            return forward_with_cache(dp, self.draft_cfg, toks, row)

        self._d_prefill = jax.jit(d_prefill)

        def d_install(cache, rk, rv, slot, plen):
            cache["k"] = jax.lax.dynamic_update_slice(
                cache["k"], rk, (0, slot, 0, 0, 0))
            cache["v"] = jax.lax.dynamic_update_slice(
                cache["v"], rv, (0, slot, 0, 0, 0))
            cache["pos"] = cache["pos"].at[slot].set(plen)
            return cache

        self._d_install = jax.jit(d_install, donate_argnums=(0,))

        if self.paged:
            def d_set_pos(cache, slot, pos):
                cache["pos"] = cache["pos"].at[slot].set(pos)
                return cache

            self._d_set_pos = jax.jit(d_set_pos, donate_argnums=(0,))
            # draft twin of the base _replay_step: 1-row draft decode
            # for kernel-formulation resume (_replay_draft) — same
            # forward_paged, same captured formulation, undonated
            self._d_replay_step = jax.jit(
                lambda dp, t, c, tab: forward_paged(
                    dp, self.draft_cfg, t, c, tab,
                    paged_impl=self.paged_kernel, mesh=self.mesh))

    # ------------------------------------------------------------------
    def submit(self, prompt, max_new_tokens, **kw) -> int:
        # slot-static headroom: every in-flight dispatch can write up to
        # decode_steps * k positions past the committed prefix before
        # rolling back-by-position; without this the per-row
        # dynamic_update_slice would CLAMP near max_len and silently
        # overwrite valid KV (same guard as speculative_generate's
        # s + max_new + k check, scaled by the unpinned window). The
        # PAGED engine needs no extra headroom: overrun positions past
        # the table null-route (paged_scatter_kv), so only the base
        # plen + max_new <= max_len bound applies — unpinning paging
        # widened the servable range.
        window = self.pipeline_depth * self.decode_steps * self.k
        if not self.paged and prompt \
                and len(prompt) + max_new_tokens + window > self.max_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) + draft window ({window}) exceeds "
                f"cache length {self.max_len}")
        return super().submit(prompt, max_new_tokens, **kw)

    def _run_d_prefill(self, toks, row):
        """Draft prefill with the base engine's first-dispatch-per-shape
        compile accounting (keyed apart from target prefill)."""
        return self._timed_dispatch(
            ("d_prefill", toks.shape[1], row["k"].shape[3]),
            self._d_prefill, self.draft_params, toks, row)

    @functools.lru_cache(maxsize=None)      # noqa: B019 — engine-lived
    def _d_row_zeros(self, bucket: int):
        shape = (self.draft_cfg.n_layers, 1, self.draft_cfg.kv_heads,
                 bucket, self.draft_cfg.head_dim)
        z = jnp.zeros(shape, self.draft_cfg.dtype)
        if self._d_row_shd is not None:
            # same head sharding as d_cache: draft prefill runs sharded
            # and the draft install never gathers (mirrors _row_zeros)
            z = jax.device_put(z, self._d_row_shd)
        return z

    def _d_bucket(self, n: int) -> int:
        """Draft scratch-row bucket: the prompt's power-of-two bucket,
        never below one KV block under paging (blocks install whole)."""
        b = min(_bucket(n), self.max_len)
        if self.paged:
            b = max(b, self.kv_block_size)
        return b

    def _fresh_drow(self, bucket: int) -> dict:
        return {
            "k": self._d_row_zeros(bucket),
            "v": self._d_row_zeros(bucket),
            "pos": jnp.zeros((), jnp.int32),
        }

    # -- draft admission (chunked + one-shot, slot-static + paged) -----
    def _attach_draft_chunks(self, ent, req) -> None:
        """Chunk the DRAFT cache alongside the target: the per-tick cost
        stays one target chunk + one (much cheaper) draft chunk, so the
        head-of-line bound chunked prefill promises holds under
        speculative decoding too — no whole-prompt draft forward spikes
        on the install tick. The draft has no prefix cache, so its
        chunks cover the full prompt."""
        chunk = self._prefill_chunk
        plen = len(req.prompt)
        ent["drow"] = self._fresh_drow(self._d_bucket(plen))
        ent["dtodo"] = deque(req.prompt[i:i + chunk]
                             for i in range(0, plen, chunk))

    def _start_chunked_prefill(self, req, m, mkey) -> bool:
        if not super()._start_chunked_prefill(req, m, mkey):
            return False
        self._attach_draft_chunks(self._prefilling[-1], req)
        return True

    def _paged_start_chunked(self, req, m, mkey) -> bool:
        # reserve the draft's install blocks UP FRONT (no prefix
        # sharing shrinks them): a dry draft pool falls back to the
        # one-shot path, whose install runs in the same tick its
        # headroom was checked — never mid-flight
        try:
            reserved = self._d_alloc.alloc_many(
                blocks_for(len(req.prompt), self.kv_block_size))
        except NoFreeBlocks:
            return False
        if not super()._paged_start_chunked(req, m, mkey):
            for b in reserved:
                self._d_alloc.decref(b)
            return False
        self._chunked_dreserved[req.rid] = reserved
        self._attach_draft_chunks(self._prefilling[-1], req)
        return True

    def cancel(self, rid: int) -> bool:
        ok = super().cancel(rid)
        if self.paged:
            # a cancel that dropped a mid-prefill entry released the
            # TARGET reservation in the base class; the draft twin
            # releases here (popped at install otherwise, so this is a
            # no-op for active/finished requests)
            for b in self._chunked_dreserved.pop(rid, None) or []:
                self._d_alloc.decref(b)
        return ok

    def _chunk_cost(self, ent) -> int:
        # budget accounting: the draft chunk rides the target chunk's
        # charge (one target + one much-cheaper draft forward per
        # advance — the same pairing the unbudgeted rule runs); once
        # the target queue empties first (prefix-hit admissions skip
        # target chunks the draft must still cover) the residual draft
        # chunks are charged at their own token count so the cost
        # stays defined until the entry retires
        if ent["todo"]:
            return len(ent["todo"][0])
        return len(ent["dtodo"][0])

    def _prefill_remaining(self, ent) -> int:
        # the entry retires only when BOTH queues empty: remaining
        # work (the TTFT-slack term) is whichever queue runs longer
        return max(sum(len(c) for c in ent["todo"]),
                   sum(len(c) for c in ent["dtodo"]))

    def _prefill_advance(self, ent) -> bool:
        if ent["todo"]:
            super()._prefill_advance(ent)       # one target chunk
        if ent["dtodo"]:                        # one draft chunk
            toks_list = ent["dtodo"].popleft()
            rem = len(toks_list)
            rbucket = _bucket(rem) if not ent["dtodo"] else rem
            toks = jnp.asarray([toks_list + [0] * (rbucket - rem)],
                               jnp.int32)
            _, ent["drow"] = self._run_d_prefill(toks, ent["drow"])
        if ent["todo"] or ent["dtodo"]:
            return False
        # hand the chunk-prefilled draft row to _finish_prefill (keyed
        # by rid: _prefilling order and recursion-safe)
        self._chunked_drow[ent["req"].rid] = ent["drow"]
        return True

    def _install_draft_row(self, req, drow: dict, plen: int) -> None:
        """Land one prefilled draft scratch row for ``req``'s slot:
        slot-static = the donated whole-row install; paged = block-wise
        into the draft arena (quantizing on install under int8, same as
        the target's _install_block). A chunked admission installs into
        the blocks reserved at its start; one-shot/resume installs
        allocate here, in the same tick their headroom was checked."""
        slot = req.slot
        if not self.paged:
            self.d_cache = self._d_install(
                self.d_cache, drow["k"], drow["v"], jnp.int32(slot),
                jnp.int32(plen))
            return
        bs = self.kv_block_size
        for b in self._d_tables[slot]:      # stale leftovers (resume)
            self._d_alloc.decref(b)
        table = self._chunked_dreserved.pop(req.rid, None)
        if table is None:
            table = self._d_alloc.alloc_many(blocks_for(plen, bs))
        for j, phys in enumerate(table):
            self.d_cache = self._timed_dispatch(
                ("dinstallblk", drow["k"].shape[3]), self._install_block,
                self.d_cache, drow["k"], drow["v"], jnp.int32(phys),
                jnp.int32(j * bs))
            if self._d_scales is not None:
                self._d_scales.note_write(phys)
        self._d_tables[slot] = table
        self._set_d_table_row(slot)
        self.d_cache = self._d_set_pos(self.d_cache, jnp.int32(slot),
                                       jnp.int32(plen))

    def _finish_prefill(self, req, row, step, *,
                        installed: bool = False) -> None:
        # draft install FIRST: the request may finish inside the super
        # call (stop token / max_new=1), releasing the slot and
        # recursively admitting a pending request into it — a stale
        # draft install landing afterwards would overwrite the NEW
        # request's draft row (no prefix cache here: published entries
        # hold TARGET KV). The draft row arrives chunk-prefilled from
        # _prefill_advance, or is prefilled whole here on the one-shot
        # (short prompt) path.
        plen = len(req.prompt)
        drow = self._chunked_drow.pop(req.rid, None)
        if drow is None:
            bucket = self._d_bucket(plen)
            toks = jnp.asarray([req.prompt + [0] * (bucket - plen)],
                               jnp.int32)
            drow = self._fresh_drow(bucket)
            _, drow = self._run_d_prefill(toks, drow)
        self._install_draft_row(req, drow, plen)
        super()._finish_prefill(req, row, step, installed=installed)

    def _finish_if_done(self, req, admit: bool = True) -> None:
        if req.done and req.slot >= 0:
            self.d_cache["pos"] = self.d_cache["pos"].at[req.slot].set(0)
        super()._finish_if_done(req, admit)

    def _resume_draft(self, req, seq) -> None:
        """Resume hook for the DRAFT cache (preempt-and-resume in both
        modes, and supervised restarts): re-prefill it over the same
        committed sequence the target resume installs
        (``prompt + out[:-1]``) so the draft invariant — processed ==
        committed[:-1], pos == committed length - 1 fed next — holds in
        the rebuilt slot exactly as it did before the pause. The
        draft's re-prefilled KV is bit-identical to the incrementally
        built one (chunking invariance) under the gather formulation;
        under the fused kernel the committed out-span is then replayed
        through the 1-row kernel twin (``_replay_draft``) so the same
        bit-exactness holds. Greedy accept/reject decisions — and
        therefore committed tokens — are undisturbed either way."""
        n = len(seq)
        bucket = self._d_bucket(n)
        toks = jnp.asarray([seq + [0] * (bucket - n)], jnp.int32)
        drow = self._fresh_drow(bucket)
        _, drow = self._run_d_prefill(toks, drow)
        self._install_draft_row(req, drow, n)
        if self.paged and self.paged_kernel == "kernel" \
                and n > len(req.prompt):
            self._replay_draft(req, seq)

    def _replay_draft(self, req, seq) -> None:
        """Kernel-formulation tail of the draft resume — the draft twin
        of ``serving._replay_committed``: the dense re-prefill above
        rebuilt the committed out-span with gather-formulation math,
        but the undisturbed run built those draft positions with S==1
        kernel steps (tolerance-equivalent, not bit-equal). Overwrite
        them by replaying the committed tokens through the 1-row draft
        decode twin so the rebuilt draft arena — and therefore every
        later proposal distribution a sampled row's residual draw
        depends on — is bit-identical to the undisturbed run's. This
        is also the disagg-adopt path: a decode-role spec engine
        re-prefills its draft from the adopted target handoff through
        exactly this hook."""
        n0 = len(req.prompt)
        table = self._d_table[req.slot:req.slot + 1]
        cache = {k: v for k, v in self.d_cache.items() if k != "pos"}
        for p in range(n0, len(seq)):
            cache["pos"] = jnp.asarray([p], jnp.int32)
            _lg, cache = self._timed_dispatch(
                ("replaydtok",), self._d_replay_step, self.draft_params,
                jnp.asarray([[seq[p]]], jnp.int32), cache, table)
        for key in self.d_cache:
            if key != "pos":
                self.d_cache[key] = cache[key]

    # -- paged draft-block discipline ----------------------------------
    def _set_d_table_row(self, slot: int) -> None:
        row = np.zeros((self._nbs,), np.int32)
        blocks = self._d_tables[slot]
        row[:len(blocks)] = blocks
        self._d_table = self._d_table.at[slot].set(jnp.asarray(row))

    def _dispatch_span(self) -> int:
        # each fused round writes a whole verify window (k positions)
        # before rolling back by pos
        return self.decode_steps * self.k

    def _grow_slot_blocks(self, s: int, start: int, end: int) -> None:
        super()._grow_slot_blocks(s, start, end)
        # the draft table grows over the SAME span: draft and target
        # timelines advance in lockstep (both sit at the committed
        # length). Draft blocks are exclusively owned by construction
        # (no prefix sharing, forks copy), so growth never COWs.
        bs = self.kv_block_size
        table = self._d_tables[s]
        changed = False
        for j in range(start // bs, (end - 1) // bs + 1):
            while len(table) <= j:
                table.append(self._d_alloc.alloc())
                changed = True
            if self._d_scales is not None:
                self._d_scales.note_write(table[j])
        if changed:
            self._set_d_table_row(s)

    def _free_slot_blocks(self, slot: int) -> None:
        super()._free_slot_blocks(slot)
        table = self._d_tables[slot]
        self._d_tables[slot] = []
        if self._inflight:
            self._d_deferred.extend(table)
        else:
            for b in table:
                self._d_alloc.decref(b)

    def _drain_deferred(self) -> None:
        super()._drain_deferred()
        if not self.paged:
            return
        if self._d_deferred and not self._inflight:
            for b in self._d_deferred:
                self._d_alloc.decref(b)
            self._d_deferred.clear()
        if not self._inflight:
            self._trim_spec_tails()

    def _trim_spec_tails(self) -> None:
        """Verify-window rollback, settled at the block layer: with the
        in-flight window empty, any block past the committed prefix
        holds only speculated-then-rolled-back writes — nothing ``pos``
        admits. Free those tails (both caches) and zero their table
        entries back to the null block, so the pool's free count, a
        COW fork's shared set, and a swap capture all see exactly the
        committed footprint, never speculation residue."""
        bs = self.kv_block_size
        pre = {ent["req"].slot for ent in self._prefilling}
        for s, req in list(self._active.items()):
            if s in pre or req.slot < 0:
                continue
            need = blocks_for(len(req.prompt) + len(req.out) - 1, bs)
            table = self._tables[s]
            if len(table) > need:
                for b in table[need:]:
                    self._alloc.decref(b)
                del table[need:]
                self._set_table_row(s)
            d_table = self._d_tables[s]
            if len(d_table) > need:
                for b in d_table[need:]:
                    self._d_alloc.decref(b)
                del d_table[need:]
                self._set_d_table_row(s)

    def _admit_headroom(self, req) -> bool:
        if not super()._admit_headroom(req):
            return False
        # the draft pool must hold the prompt's install blocks plus one
        # of growth too — no prefix sharing shrinks the draft's need,
        # so a heavily-shared target admission can still be
        # draft-bound. Pressure relief (preemption frees BOTH pools)
        # unblocks it like any other headroom wait.
        plen = len(req.prompt)
        cap_blocks = blocks_for(plen + req.max_new_tokens - 1,
                                self.kv_block_size)
        committed = plen + len(req.out) - 1 if req.preempted else plen
        base_need = blocks_for(committed, self.kv_block_size)
        need = min(base_need + 1, max(base_need, cap_blocks))
        return need <= self._d_alloc.free_count

    def _preempt_slot(self, slot: int, mode: str) -> None:
        super()._preempt_slot(slot, mode)
        # the draft's blocks free outright in BOTH modes: swap resume
        # restores the target byte-exact and re-prefills the draft
        # (_resume_draft via the base resume paths) — the draft is
        # derivable state, not payload
        for b in self._d_tables[slot]:
            self._d_alloc.decref(b)
        self._d_tables[slot] = []
        self.d_cache["pos"] = self.d_cache["pos"].at[slot].set(0)

    def fork(self, rid: int, **kw) -> int:
        """COW-fork under speculation: the target's committed blocks
        share by refcount exactly as DecodeServer.fork; the DRAFT's
        committed blocks copy outright into fresh blocks (the draft
        writes every round, so a COW would copy on the very next
        dispatch anyway — eager copy is the same cost with none of the
        shared-state bookkeeping). The fork's accept/reject decisions
        then run over bit-identical draft KV, so a greedy fork
        continues bit-identically to its source."""
        if not self.paged:
            raise RuntimeError("fork requires paged KV (kv_blocks > 0)")
        src = next((r for r in self._active.values() if r.rid == rid),
                   None)
        if src is not None:
            # barrier first (super().fork flushes anyway), then check
            # DRAFT capacity before the base fork commits anything —
            # a half-made fork with no draft blocks would corrupt the
            # accept/reject stream
            self._flush()
            src = next((r for r in self._active.values()
                        if r.rid == rid), None)
            if src is not None and src.slot >= 0 and not src.done:
                nblk = blocks_for(
                    len(src.prompt) + len(src.out) - 1,
                    self.kv_block_size)
                if nblk > self._d_alloc.free_count:
                    raise QueueFull(
                        f"fork needs {nblk} free draft-KV blocks, "
                        f"{self._d_alloc.free_count} free; retry after "
                        f"a completion")
        nrid = super().fork(rid, **kw)
        new = next(r for r in self._active.values() if r.rid == nrid)
        src = next(r for r in self._active.values() if r.rid == rid)
        base = len(new.prompt) + len(new.out) - 1
        nblk = blocks_for(base, self.kv_block_size)
        fresh = self._d_alloc.alloc_many(nblk)
        for j, dst in enumerate(fresh):
            self.d_cache = self._timed_dispatch(
                ("dcowblk",), self._cow_block, self.d_cache,
                jnp.int32(self._d_tables[src.slot][j]), jnp.int32(dst))
            if self._d_scales is not None:
                self._d_scales.note_copy(self._d_tables[src.slot][j],
                                         dst)
        self._d_tables[new.slot] = fresh
        self._set_d_table_row(new.slot)
        self.d_cache = self._d_set_pos(self.d_cache,
                                       jnp.int32(new.slot),
                                       jnp.int32(base))
        return nrid

    # ------------------------------------------------------------------
    def _dispatch(self, active, keep, sampling):
        """One speculative dispatch: decode_steps fused rounds of up to
        k tokens per active slot. The base step() template owns the
        scaffolding (mid-prefill slot exclusion, keep mask, in-flight
        window, async fetch, prefill tick); with pipeline_depth > 1 the
        next window's draft burst is enqueued while this one's verify
        is still in flight — accept/reject resolves in-graph, so the
        chain never waits on the host."""
        if self.paged:
            commit, counts, accepted, self._last, self.cache, \
                self.d_cache = self._spec_tick(
                    self.params, self.draft_params, self._last,
                    self.cache, self.d_cache, self._table,
                    self._d_table, keep, self._temp, self._topk,
                    self._topp, self._seed, sampling)
        else:
            commit, counts, accepted, self._last, self.cache, \
                self.d_cache = self._spec_tick(
                    self.params, self.draft_params, self._last,
                    self.cache, self.d_cache, keep, self._temp,
                    self._topk, self._topp, self._seed, sampling)
        return commit, counts, accepted

    def _consume_payload(self, ent, host, now: float = 0.0) -> int:
        commit_host, counts_host, acc_host = host   # [B,T,k] [B,T] [B,T]
        emitted = 0
        rounds = counts_host.shape[1]
        for s in ent.slots:
            req = self._active.get(s)
            if req is None or req.done:
                continue
            n = 0
            for t in range(rounds):
                if req.done:
                    break       # later rounds are pure rollback
                self.spec_drafted += self.k
                a = int(acc_host[s, t])
                self.spec_accepted += a
                self.spec_window_events.append(a)
                if len(self.spec_window_events) > 4096:
                    del self.spec_window_events[:2048]
                for j in range(int(counts_host[s, t])):
                    req.out.append(int(commit_host[s, t, j]))
                    req.note_token()
                    emitted += 1
                    n += 1
                    if req.done:
                        break
            if n and now:
                # a verify burst lands up to decode_steps*k tokens at
                # one host instant: the shared ledger template
                # attributes the arrival gap evenly across them
                req.led.note_tokens(n, now)
            self._note_tenant_tokens(req, n)
            # draft + verify both ran inside this quantum's measured
            # duration, so spec overhead charges the SERVED tenant via
            # the same accepted-token weights (ISSUE 20)
            self._chip_add(req.tenant, "decode", n)
            self._finish_if_done(req, admit=False)
        return emitted

    def stats(self) -> dict:
        st = super().stats()
        spec = {
            "n_draft": self.k,
            "drafted": self.spec_drafted,
            "accepted": self.spec_accepted,
            "acceptance": (round(self.spec_accepted
                                 / self.spec_drafted, 4)
                           if self.spec_drafted else None),
        }
        if self.paged:
            spec["draft_kv"] = {
                "blocks_total": self._d_alloc.capacity,
                "blocks_free": self._d_alloc.free_count,
                "blocks_used": self._d_alloc.used_count,
                "scaled_blocks": (self._d_scales.count
                                  if self._d_scales is not None
                                  else None),
            }
        st["speculative"] = spec
        return st
