"""YOLOS — detection-family model: ViT backbone + detection tokens.

The reference's single published benchmark serves **YOLOS-small**
(``demos/gpu-sharing-comparison/client/main.py:18-19`` loads
``hustvl/yolos-small``; README.md:12,50) under N pods sharing one GPU.
This module is that model family built TPU-first so the inference
comparison is apples-to-apples: the encoder is the shared ViT backbone
(`vit.encode` — one `lax.scan` over blocks, bf16, static shapes; at
this sequence length — 196 patches + 100 det tokens = 296, not a
128-multiple — the attention op dispatches XLA's fused path, the right
tool at short sequence, rather than the pallas flash kernel), with
YOLOS's two changes on top:

- the CLS token is replaced by ``n_det_tokens`` learned detection
  tokens appended AFTER the patch tokens (You Only Look at One
  Sequence, Fang et al. 2021 — detection as plain sequence encoding,
  no decoder, no region ops, which is exactly what the MXU wants);
- per detection token, a linear class head (``n_classes`` + 1
  no-object logit) and a 3-layer MLP box head with sigmoid output in
  normalized (cx, cy, w, h).

Training uses DETR-style set criterion. The bipartite matching is the
TPU-first part: instead of hosting out to scipy's Hungarian solver
(dynamic, host-synchronous — poison inside jit), `sinkhorn_match`
solves the entropic-regularized optimal transport relaxation with a
fixed number of `lax.scan` iterations and hardens it greedily — static
shapes, fully jittable, and exact-in-practice at the temperatures used
(validated against brute-force optimal matching in tests/test_yolos.py).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from nos_tpu.models.vit import ViTConfig, dense_init, encode, init_encoder
from nos_tpu.ops.layers import patchify

Params = Dict[str, Any]


@dataclass(frozen=True)
class YolosConfig:
    """YOLOS-small by default: ViT-small/16 backbone (d=384, 12 layers,
    6 heads, mlp 1536) + 100 detection tokens, 91 COCO classes."""
    image_size: int = 224
    patch: int = 16
    d_model: int = 384
    n_layers: int = 12
    n_heads: int = 6
    d_ff: int = 1536
    n_det_tokens: int = 100
    n_classes: int = 91          # real classes; one extra no-object logit
    dtype: Any = jnp.bfloat16

    @property
    def backbone(self) -> ViTConfig:
        return ViTConfig(
            image_size=self.image_size, patch=self.patch,
            d_model=self.d_model, n_layers=self.n_layers,
            n_heads=self.n_heads, d_ff=self.d_ff, dtype=self.dtype)

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch) ** 2


def init_params(rng: jax.Array, cfg: YolosConfig) -> Params:
    keys = jax.random.split(rng, 8)
    patch_dim = cfg.patch * cfg.patch * 3
    d = cfg.d_model
    seq = cfg.n_patches + cfg.n_det_tokens
    return {
        "patch_proj": dense_init(keys[0], (patch_dim, d), patch_dim, cfg.dtype),
        "det_tokens": (jax.random.normal(keys[1], (1, cfg.n_det_tokens, d),
                                         jnp.float32) * 0.02).astype(cfg.dtype),
        "pos_embed": (jax.random.normal(keys[2], (1, seq, d),
                                        jnp.float32) * 0.02).astype(cfg.dtype),
        **init_encoder(keys[3], cfg.backbone),
        "class_head": dense_init(keys[4], (d, cfg.n_classes + 1), d, cfg.dtype),
        "box_mlp": {
            "w1": dense_init(keys[5], (d, d), d, cfg.dtype),
            "b1": jnp.zeros((d,), cfg.dtype),
            "w2": dense_init(keys[6], (d, d), d, cfg.dtype),
            "b2": jnp.zeros((d,), cfg.dtype),
            "w3": dense_init(keys[7], (d, 4), d, cfg.dtype),
            "b3": jnp.zeros((4,), cfg.dtype),
        },
    }


def forward(params: Params, cfg: YolosConfig,
            images: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """images [B, H, W, 3] -> (class_logits [B, Q, n_classes+1] fp32,
    boxes [B, Q, 4] fp32 sigmoid-normalized cxcywh)."""
    b = images.shape[0]
    x = patchify(images.astype(cfg.dtype), cfg.patch)
    x = jnp.dot(x, params["patch_proj"])
    det = jnp.broadcast_to(params["det_tokens"], (b, cfg.n_det_tokens, cfg.d_model))
    x = jnp.concatenate([x, det], axis=1) + params["pos_embed"]
    x = encode(params, cfg.backbone, x)
    tok = x[:, -cfg.n_det_tokens:]
    logits = jnp.dot(tok, params["class_head"]).astype(jnp.float32)
    m = params["box_mlp"]
    h = jax.nn.relu(jnp.dot(tok, m["w1"]) + m["b1"])
    h = jax.nn.relu(jnp.dot(h, m["w2"]) + m["b2"])
    boxes = jax.nn.sigmoid((jnp.dot(h, m["w3"]) + m["b3"]).astype(jnp.float32))
    return logits, boxes


def param_count(params: Params) -> int:
    return sum(p.size for p in jax.tree.leaves(params))


# ---------------------------------------------------------------- boxes

def cxcywh_to_xyxy(b: jax.Array) -> jax.Array:
    cx, cy, w, h = b[..., 0], b[..., 1], b[..., 2], b[..., 3]
    return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], axis=-1)


def generalized_box_iou(a: jax.Array, b: jax.Array) -> jax.Array:
    """GIoU between box sets a [..., N, 4] and b [..., M, 4] (xyxy) ->
    [..., N, M]. Degenerate (zero-area) boxes yield IoU 0, not NaN."""
    a, b = a[..., :, None, :], b[..., None, :, :]
    area_a = (a[..., 2] - a[..., 0]).clip(0) * (a[..., 3] - a[..., 1]).clip(0)
    area_b = (b[..., 2] - b[..., 0]).clip(0) * (b[..., 3] - b[..., 1]).clip(0)
    lt = jnp.maximum(a[..., :2], b[..., :2])
    rb = jnp.minimum(a[..., 2:], b[..., 2:])
    inter = (rb - lt).clip(0).prod(-1)
    union = area_a + area_b - inter
    iou = inter / jnp.maximum(union, 1e-9)
    lt_c = jnp.minimum(a[..., :2], b[..., :2])
    rb_c = jnp.maximum(a[..., 2:], b[..., 2:])
    hull = (rb_c - lt_c).clip(0).prod(-1)
    return iou - (hull - union) / jnp.maximum(hull, 1e-9)


# -------------------------------------------------------------- matching

def sinkhorn_match(cost: jax.Array, target_mask: jax.Array,
                   n_iters: int = 50, temp: float = 0.01) -> jax.Array:
    """One-to-one assignment of targets to queries, jit-compatible.

    cost [Q, T] (smaller = better), target_mask [T] bool (padded targets
    False). Runs Sinkhorn on exp(-cost/temp) toward doubly-stochastic
    (queries have capacity 1, each real target needs mass 1), then
    hardens greedily: targets in order of their best remaining cost pick
    their argmax-plan query, masking taken queries. Returns ``assign``
    [T] int32 — the query index per target (undefined where mask False).

    Padded targets take no query: their cost column is +inf-like and
    they are skipped in the greedy pass (assign stays at argmin of an
    all-equal row — harmless, callers mask by ``target_mask``).
    """
    q, t = cost.shape
    big = jnp.float32(1e9)
    c = jnp.where(target_mask[None, :], cost.astype(jnp.float32), big)
    logk = -c / temp

    def sink(carry, _):
        f, g = carry
        # column update: each real target wants total mass 1
        g = -jax.nn.logsumexp(logk + f[:, None], axis=0)
        g = jnp.where(target_mask, g, -big)      # padded: no mass
        # row update: each query offers at most 1 (<= 1 capacity via min)
        f = jnp.minimum(-jax.nn.logsumexp(logk + g[None, :], axis=1), 0.0)
        return (f, g), None

    (f, g), _ = jax.lax.scan(
        sink, (jnp.zeros((q,)), jnp.zeros((t,))), None, length=n_iters)
    plan = jnp.exp(logk + f[:, None] + g[None, :])     # [Q, T]

    order = jnp.argsort(jnp.where(target_mask, c.min(axis=0), big))

    def greedy(carry, ti):
        assign, taken = carry
        score = jnp.where(taken, -jnp.inf, plan[:, ti])
        pick = jnp.argmax(score)
        live = target_mask[ti]
        assign = assign.at[ti].set(jnp.where(live, pick, assign[ti]))
        taken = taken.at[pick].set(taken[pick] | live)
        return (assign, taken), None

    (assign, _), _ = jax.lax.scan(
        greedy, (jnp.zeros((t,), jnp.int32), jnp.zeros((q,), bool)), order)
    return assign


# ------------------------------------------------------------------ loss

def set_criterion(logits: jax.Array, boxes: jax.Array,
                  target_labels: jax.Array, target_boxes: jax.Array,
                  no_object_weight: float = 0.1,
                  cost_class: float = 1.0, cost_l1: float = 5.0,
                  cost_giou: float = 2.0) -> Dict[str, jax.Array]:
    """DETR set criterion (class CE + L1 + GIoU over the optimal
    one-to-one matching), batched, static shapes.

    logits [B, Q, C+1], boxes [B, Q, 4] cxcywh; target_labels [B, T]
    int32 with -1 padding; target_boxes [B, T, 4] cxcywh. Returns a dict
    of scalar losses; ``total`` is the training objective. The matching
    cost uses the same class/L1/GIoU weights as the losses (the DETR
    recipe); no-object class index is C.
    """
    bsz, nq, nc1 = logits.shape
    if target_labels.shape[1] > nq:
        raise ValueError(
            f"{target_labels.shape[1]} targets exceed {nq} detection "
            "tokens: one-to-one matching needs T <= Q (raise "
            "n_det_tokens or truncate the target set)")
    mask = target_labels >= 0                              # [B, T]
    labels = jnp.where(mask, target_labels, 0)

    probs = jax.nn.softmax(logits, axis=-1)                # [B, Q, C+1]
    p_target = jnp.take_along_axis(
        probs, labels[:, None, :].repeat(nq, 1), axis=-1)  # [B, Q, T]
    l1 = jnp.abs(boxes[:, :, None, :] - target_boxes[:, None, :, :]).sum(-1)
    giou = generalized_box_iou(cxcywh_to_xyxy(boxes), cxcywh_to_xyxy(target_boxes))
    cost = cost_class * (-p_target) + cost_l1 * l1 + cost_giou * (-giou)

    assign = jax.vmap(sinkhorn_match)(cost, mask)          # [B, T]

    # scatter matched targets onto queries
    one_hot = (jax.nn.one_hot(assign, nq, axis=1, dtype=jnp.float32)
               * mask[:, None, :])                          # [B, Q, T]
    matched = one_hot.sum(-1)                               # [B, Q] 0/1
    # class target per query: matched target's label, else no-object (C)
    q_label = jnp.einsum("bqt,bt->bq", one_hot, labels.astype(jnp.float32))
    q_label = jnp.where(matched > 0, q_label, nc1 - 1).astype(jnp.int32)
    ce = -jnp.take_along_axis(
        jax.nn.log_softmax(logits, -1), q_label[..., None], -1)[..., 0]
    w = jnp.where(matched > 0, 1.0, no_object_weight)
    loss_class = (ce * w).sum() / jnp.maximum(w.sum(), 1e-6)

    n_matched = jnp.maximum(mask.sum(), 1).astype(jnp.float32)
    loss_l1 = (l1 * one_hot).sum() / n_matched
    loss_giou = ((1.0 - giou) * one_hot).sum() / n_matched
    total = (cost_class * loss_class + cost_l1 * loss_l1
             + cost_giou * loss_giou)
    return {"class": loss_class, "l1": loss_l1, "giou": loss_giou,
            "total": total}


def postprocess(logits: jax.Array, boxes: jax.Array,
                top_k: int = 10) -> Dict[str, jax.Array]:
    """Per image: best-class score per query (no-object excluded), top-k
    queries by that score. Returns scores/labels [B, k], boxes [B, k, 4]
    (xyxy, still normalized to [0, 1])."""
    probs = jax.nn.softmax(logits, axis=-1)[..., :-1]      # drop no-object
    scores = probs.max(-1)
    labels = probs.argmax(-1)
    top = jnp.argsort(-scores, axis=-1)[:, :top_k]
    take = lambda x: jnp.take_along_axis(x, top, axis=1)
    return {"scores": take(scores), "labels": take(labels),
            "boxes": jnp.take_along_axis(cxcywh_to_xyxy(boxes), top[..., None],
                                         axis=1)}
