"""TLS validating-admission webhook server for the real-k8s path.

Analog of the reference's webhook deployment: controller-runtime serves
TLS AdmissionReview endpoints registered via ValidatingWebhookConfiguration
(reference pkg/api/nos.nebuly.com/v1alpha1/elasticquota_webhook.go:30-80,
config/operator/webhook/manifests.yaml). On the in-process double the same
checks run as server-side admission hooks (api/webhooks.py); this module
serves them over the wire so a REAL API server (kind/GKE, or the K8sSim
envtest analog, which invokes registered webhook configurations on writes)
enforces the quota invariants when the operator runs with ``--kubeconfig``.

Protocol: admission.k8s.io/v1 AdmissionReview — POST a review request,
always answer HTTP 200 with ``response.allowed`` (denials carry a Status
with code 403 and the validator's message), echoing ``request.uid``.

Certificates: ``generate_self_signed_cert`` shells out to the openssl CLI
(present in all deploy images) producing a key/cert pair with localhost +
service-DNS SANs; the PEM doubles as the caBundle in the webhook
configuration the way cert-manager-less helm installs do it.
"""
from __future__ import annotations

import base64
import json
import logging
import ssl
import subprocess
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from typing import Optional

from nos_tpu.api.webhooks import (
    _validate_composite_elastic_quota,
    _validate_elastic_quota,
)
from nos_tpu.kube import k8s_codec as kc
from nos_tpu.kube.apiserver import AdmissionDenied

logger = logging.getLogger(__name__)

# URL path -> (kind, validator). Paths follow the controller-runtime
# convention /validate-<group>-<version>-<kind>.
VALIDATORS = {
    "/validate-nos-ai-v1alpha1-elasticquota":
        ("ElasticQuota", _validate_elastic_quota),
    "/validate-nos-ai-v1alpha1-compositeelasticquota":
        ("CompositeElasticQuota", _validate_composite_elastic_quota),
}


def generate_self_signed_cert(cert_dir: str, cn: str = "nos-tpu-webhook",
                              dns_names: Optional[list] = None) -> tuple:
    """Create key.pem/cert.pem under ``cert_dir`` via the openssl CLI.
    Returns (certfile, keyfile, ca_bundle_b64)."""
    cert_dir = Path(cert_dir)
    cert_dir.mkdir(parents=True, exist_ok=True)
    cert, key = cert_dir / "cert.pem", cert_dir / "key.pem"
    sans = ["DNS:localhost", "IP:127.0.0.1"] + [
        f"DNS:{d}" for d in (dns_names or [])]
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048",
         "-keyout", str(key), "-out", str(cert), "-days", "365", "-nodes",
         "-subj", f"/CN={cn}", "-addext", f"subjectAltName={','.join(sans)}"],
        check=True, capture_output=True, timeout=60,
    )
    bundle = base64.b64encode(cert.read_bytes()).decode()
    return str(cert), str(key), bundle


class QuotaWebhookServer:
    """Serve the quota validators as TLS AdmissionReview endpoints.

    ``client`` is anything with ``.list(kind, namespace=None)`` — the
    in-process ApiServer or the K8sApiServer REST adapter — used by the
    validators to see existing quotas."""

    def __init__(self, client, certfile: str, keyfile: str,
                 host: str = "127.0.0.1", port: int = 0):
        self.client = client
        srv = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _reply(self, payload: dict, code: int = 200) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path in ("/readyz", "/healthz"):
                    self.send_response(200)
                    self.send_header("Content-Length", "2")
                    self.end_headers()
                    self.wfile.write(b"ok")
                    return
                self._reply({"message": "POST AdmissionReview"}, 404)

            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                try:
                    review = json.loads(self.rfile.read(n) or b"{}")
                except json.JSONDecodeError:
                    self._reply({"message": "invalid JSON"}, 400)
                    return
                self._reply(srv._review(self.path, review))

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.httpd.daemon_threads = True
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(certfile, keyfile)
        self.httpd.socket = ctx.wrap_socket(self.httpd.socket, server_side=True)
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        h, p = self.httpd.server_address[:2]
        return f"https://{h}:{p}"

    def start(self) -> "QuotaWebhookServer":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()

    # ------------------------------------------------------------------
    def _review(self, path: str, review: dict) -> dict:
        req = review.get("request") or {}
        uid = req.get("uid", "")

        def respond(allowed: bool, message: str = "") -> dict:
            resp = {"uid": uid, "allowed": allowed}
            if not allowed:
                resp["status"] = {"code": 403, "reason": "Forbidden",
                                  "message": message}
            return {"apiVersion": "admission.k8s.io/v1",
                    "kind": "AdmissionReview", "response": resp}

        entry = VALIDATORS.get(path)
        if entry is None:
            return respond(False, f"no validator registered at {path}")
        kind, validator = entry
        op = req.get("operation", "CREATE")
        if op == "DELETE":
            return respond(True)
        try:
            raw = dict(req.get("object") or {})
            raw.setdefault("kind", kind)
            obj = kc.from_k8s(raw)
            old = None
            if req.get("oldObject"):
                raw_old = dict(req["oldObject"])
                raw_old.setdefault("kind", kind)
                old = kc.from_k8s(raw_old)
            validator(self.client, op, obj, old)
        except AdmissionDenied as e:
            return respond(False, str(e))
        except Exception as e:  # malformed object etc.: fail closed
            logger.warning("webhook %s errored", path, exc_info=True)
            return respond(False, f"webhook error: {e}")
        return respond(True)


def webhook_configuration_manifest(url_base: str, ca_bundle_b64: str) -> dict:
    """ValidatingWebhookConfiguration pointing at this server by URL (the
    kind/dev shape; the helm chart renders the service-reference shape)."""
    webhooks = []
    for path, (kind, _) in sorted(VALIDATORS.items()):
        plural = kc.ROUTES[kind][1]
        webhooks.append({
            "name": f"v{kind.lower()}.nos.ai",
            "admissionReviewVersions": ["v1"],
            "sideEffects": "None",
            "failurePolicy": "Fail",
            "clientConfig": {"url": f"{url_base}{path}",
                             "caBundle": ca_bundle_b64},
            "rules": [{
                "apiGroups": ["nos.ai"],
                "apiVersions": ["v1alpha1"],
                "operations": ["CREATE", "UPDATE"],
                "resources": [plural],
            }],
        })
    return {
        "apiVersion": "admissionregistration.k8s.io/v1",
        "kind": "ValidatingWebhookConfiguration",
        "metadata": {"name": "nos-tpu-validating-webhooks"},
        "webhooks": webhooks,
    }
