"""Validating webhooks for ElasticQuota / CompositeElasticQuota.

Analog of reference pkg/api/nos.nebuly.com/v1alpha1/elasticquota_webhook.go:30-80
and compositeelasticquota_webhook.go:47-87. Invariants enforced at admission:

1. at most one ElasticQuota per namespace;
2. an ElasticQuota's namespace must not be covered by any
   CompositeElasticQuota;
3. a namespace may belong to at most one CompositeElasticQuota;
4. (both kinds) every max entry must be >= the matching min entry.
"""
from __future__ import annotations

from nos_tpu.api.quota import CompositeElasticQuota, ElasticQuota
from nos_tpu.kube.apiserver import AdmissionDenied, ApiServer


def _validate_min_max(spec) -> None:
    if spec.max is None:
        return
    for resource, min_qty in spec.min.items():
        if resource in spec.max and spec.max[resource] < min_qty:
            raise AdmissionDenied(
                f"max[{resource}]={spec.max[resource]} is less than min[{resource}]={min_qty}"
            )


def _validate_elastic_quota(server: ApiServer, op: str, eq: ElasticQuota, old) -> None:
    if op == "DELETE":
        return
    _validate_min_max(eq.spec)
    ns = eq.metadata.namespace
    for other in server.list("ElasticQuota", namespace=ns):
        if other.metadata.name != eq.metadata.name:
            raise AdmissionDenied(
                f"namespace {ns!r} already has ElasticQuota {other.metadata.name!r}"
            )
    for ceq in server.list("CompositeElasticQuota"):
        if ns in ceq.spec.namespaces:
            raise AdmissionDenied(
                f"namespace {ns!r} is covered by CompositeElasticQuota "
                f"{ceq.metadata.name!r}"
            )


def _validate_composite_elastic_quota(
    server: ApiServer, op: str, ceq: CompositeElasticQuota, old
) -> None:
    if op == "DELETE":
        return
    _validate_min_max(ceq.spec)
    if len(set(ceq.spec.namespaces)) != len(ceq.spec.namespaces):
        raise AdmissionDenied("duplicate namespaces in CompositeElasticQuota")
    for other in server.list("CompositeElasticQuota"):
        if other.metadata.name == ceq.metadata.name and \
                other.metadata.namespace == ceq.metadata.namespace:
            continue
        overlap = set(ceq.spec.namespaces) & set(other.spec.namespaces)
        if overlap:
            raise AdmissionDenied(
                f"namespaces {sorted(overlap)} already belong to "
                f"CompositeElasticQuota {other.metadata.name!r}"
            )


def register_quota_webhooks(server: ApiServer) -> None:
    """Wire the validating webhooks into the API server (analog of
    SetupWebhookWithManager, cmd/operator/operator.go:92,107)."""
    server.register_admission("ElasticQuota", _validate_elastic_quota)
    server.register_admission("CompositeElasticQuota", _validate_composite_elastic_quota)
