"""CRD-equivalent API types, validating webhooks, and component configs
(analog of reference pkg/api/nos.nebuly.com/v1alpha1 and .../config/v1alpha1)."""
from nos_tpu.api.quota import (  # noqa: F401
    ElasticQuota,
    ElasticQuotaSpec,
    ElasticQuotaStatus,
    CompositeElasticQuota,
    CompositeElasticQuotaSpec,
)
from nos_tpu.api.webhooks import register_quota_webhooks  # noqa: F401
from nos_tpu.api.configs import (  # noqa: F401
    OperatorConfig,
    PartitionerConfig,
    TpuAgentConfig,
    CapacitySchedulingArgs,
)
