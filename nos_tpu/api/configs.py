"""Component configuration kinds.

Analog of reference pkg/api/nos.nebuly.com/config/v1alpha1/*.go — each binary
loads a YAML config file into one of these kinds and validates it
(cmd/gpupartitioner/gpupartitioner.go:87-101). YAML loading is provided via
``from_yaml_file`` so the cmd/ entrypoints match the reference's
``ctrl.ConfigFile().AtPath(...)`` pattern.
"""
from __future__ import annotations

from dataclasses import dataclass, field, fields
from typing import Optional

import yaml

from nos_tpu import constants


class ConfigError(ValueError):
    pass


@dataclass
class _BaseConfig:
    leader_election: bool = False
    log_level: int = 0

    @classmethod
    def from_yaml_file(cls, path: str):
        with open(path) as f:
            data = yaml.safe_load(f) or {}
        known = {f.name for f in fields(cls)}
        unknown = set(data) - known
        if unknown:
            raise ConfigError(f"{cls.__name__}: unknown config keys {sorted(unknown)}")
        cfg = cls(**data)
        cfg.validate()
        return cfg

    def validate(self) -> None:
        pass

    def leader_election_config(self, component: str):
        """LeaderElectionConfig for this component, or None when disabled
        (reference: ControllerManagerConfigurationSpec.LeaderElection,
        enabled for every manager in helm values)."""
        if not self.leader_election:
            return None
        import socket
        import uuid

        from nos_tpu.kube.leaderelection import LeaderElectionConfig

        # uuid suffix (controller-runtime does the same): hostname+pid is
        # NOT unique across two managers in one process or pid reuse
        # across container restarts — identity collision makes both
        # replicas believe they hold the lease.
        return LeaderElectionConfig(
            lease_name=f"nos-tpu-{component}-leader",
            identity=f"{socket.gethostname()}-{uuid.uuid4().hex[:8]}",
        )


@dataclass
class OperatorConfig(_BaseConfig):
    """Analog of OperatorConfig{NvidiaGpuResourceMemoryGB}."""

    tpu_resource_memory_gb: int = constants.DEFAULT_TPU_MEMORY_GB
    nvidia_gpu_resource_memory_gb: int = constants.DEFAULT_NVIDIA_GPU_MEMORY_GB

    def validate(self) -> None:
        if self.tpu_resource_memory_gb <= 0:
            raise ConfigError("tpu_resource_memory_gb must be positive")
        if self.nvidia_gpu_resource_memory_gb <= 0:
            raise ConfigError("nvidia_gpu_resource_memory_gb must be positive")


@dataclass
class PartitionerConfig(_BaseConfig):
    """Analog of GpuPartitionerConfig (batch windows, device-plugin CM,
    known-geometries override file)."""

    batch_window_timeout_seconds: float = constants.DEFAULT_BATCH_WINDOW_TIMEOUT_S
    batch_window_idle_seconds: float = constants.DEFAULT_BATCH_WINDOW_IDLE_S
    device_plugin_config_map: str = constants.DEVICE_PLUGIN_CONFIGMAP
    device_plugin_namespace: str = constants.DEVICE_PLUGIN_NAMESPACE
    device_plugin_delay_seconds: float = constants.DEFAULT_DEVICE_PLUGIN_DELAY_S
    known_generations_file: Optional[str] = None

    def validate(self) -> None:
        if self.batch_window_timeout_seconds <= 0:
            raise ConfigError("batch_window_timeout_seconds must be positive")
        if self.batch_window_idle_seconds <= 0:
            raise ConfigError("batch_window_idle_seconds must be positive")
        if self.batch_window_idle_seconds > self.batch_window_timeout_seconds:
            raise ConfigError("batch_window_idle_seconds must be <= timeout")


@dataclass
class TpuAgentConfig(_BaseConfig):
    """Analog of MigAgentConfig/GpuAgentConfig."""

    report_interval_seconds: float = constants.DEFAULT_REPORT_INTERVAL_S
    # When the REAL device plugin (nos-tpu-device-plugin DaemonSet) runs
    # on the node, the kubelet owns allocatable and the agent must not
    # also patch node.status (two writers fight); leave True only for
    # sim/dev clusters without the plugin.
    manage_allocatable: bool = True

    def validate(self) -> None:
        if self.report_interval_seconds <= 0:
            raise ConfigError("report_interval_seconds must be positive")


@dataclass
class CapacitySchedulingArgs(_BaseConfig):
    """Analog of pkg/api/scheduler/types.go:20-27 CapacitySchedulingArgs."""

    tpu_resource_memory_gb: int = constants.DEFAULT_TPU_MEMORY_GB
    nvidia_gpu_resource_memory_gb: int = constants.DEFAULT_NVIDIA_GPU_MEMORY_GB

    def validate(self) -> None:
        if self.tpu_resource_memory_gb <= 0:
            raise ConfigError("tpu_resource_memory_gb must be positive")
