"""Versioned scheduler configuration — the conversion/defaulting layer.

The reference carries CapacitySchedulingArgs inside a
KubeSchedulerConfiguration (helm
templates/scheduler/configmap_scheduler-config.yaml:10-34) and maintains
a versioned external type with generated defaulting and conversion into
the internal hub type (pkg/api/scheduler/types.go:20-27,
pkg/api/scheduler/v1beta3/{types,defaults,zz_generated.conversions}.go,
hack/generate-scheduler.sh). This module is that layer done the Python
way — explicit version schemas instead of codegen:

- **External versions** (wire, camelCase, every field optional):
  * ``v1beta2``: ``nvidiaGpuResourceMemoryGB`` — the GPU-era schema.
  * ``v1beta3``: adds ``tpuResourceMemoryGB`` — the TPU rebuild's schema.
- **Defaulting** (SetDefaults_CapacitySchedulingArgs analog): absent
  fields take the internal defaults at decode time.
- **Conversion**: every external version decodes into the ONE internal
  hub type (`nos_tpu.api.configs.CapacitySchedulingArgs`); older
  versions simply have fewer wire fields.

``load_scheduler_config`` accepts either wire shape:
- a KubeSchedulerConfiguration doc (apiVersion
  ``kubescheduler.config.k8s.io/v1beta2|v1beta3|v1``) whose
  ``profiles[].pluginConfig[name=CapacityScheduling].args`` carries the
  versioned args (the plugin-args version follows the enclosing
  document's), plus ``leaderElection.leaderElect``;
- the repo's flat snake_case ``CapacitySchedulingArgs`` YAML (no
  ``apiVersion``) — the pre-existing format stays valid.
"""
from __future__ import annotations

from typing import Optional

import yaml

from nos_tpu import constants
from nos_tpu.api.configs import CapacitySchedulingArgs, ConfigError

SCHEDULER_CONFIG_GROUP = "kubescheduler.config.k8s.io"

# external schema registry: version -> {wire key: internal field}
# (v1 follows v1beta3 — kube GA'd the schema unchanged)
_VERSIONED_ARG_FIELDS = {
    "v1beta2": {
        "nvidiaGpuResourceMemoryGB": "nvidia_gpu_resource_memory_gb",
    },
    "v1beta3": {
        "nvidiaGpuResourceMemoryGB": "nvidia_gpu_resource_memory_gb",
        "tpuResourceMemoryGB": "tpu_resource_memory_gb",
    },
}
_VERSIONED_ARG_FIELDS["v1"] = _VERSIONED_ARG_FIELDS["v1beta3"]

PLUGIN_NAME = "CapacityScheduling"


def decode_plugin_args(version: str, args: Optional[dict],
                       leader_election: bool = False) -> CapacitySchedulingArgs:
    """Decode one versioned ``pluginConfig.args`` dict into the internal
    hub type: unknown keys rejected (a v1beta3-only key in a v1beta2 doc
    is an error, not a silent drop — strict decoding is the conversion
    layer's whole point), absent keys defaulted, values validated."""
    schema = _VERSIONED_ARG_FIELDS.get(version)
    if schema is None:
        raise ConfigError(
            f"unsupported scheduler config version {version!r} "
            f"(known: {sorted(_VERSIONED_ARG_FIELDS)})")
    args = args or {}
    unknown = set(args) - set(schema)
    if unknown:
        raise ConfigError(
            f"{PLUGIN_NAME} args ({version}): unknown keys {sorted(unknown)}")
    kwargs = {"leader_election": leader_election}
    for wire_key, field in schema.items():
        if args.get(wire_key) is not None:
            kwargs[field] = int(args[wire_key])
    cfg = CapacitySchedulingArgs(**kwargs)  # dataclass defaults = defaulting
    cfg.validate()
    return cfg


def decode_scheduler_configuration(doc: dict) -> CapacitySchedulingArgs:
    """Decode a KubeSchedulerConfiguration document: find the
    CapacityScheduling pluginConfig entry across profiles (absent entry =
    all defaults, matching kube's behavior for unconfigured plugins)."""
    api_version = doc.get("apiVersion", "")
    group, _, version = api_version.partition("/")
    if group != SCHEDULER_CONFIG_GROUP:
        raise ConfigError(
            f"not a scheduler configuration: apiVersion {api_version!r}")
    if doc.get("kind") not in ("KubeSchedulerConfiguration", None):
        raise ConfigError(f"unexpected kind {doc.get('kind')!r}")
    leader = bool((doc.get("leaderElection") or {}).get("leaderElect", False))
    args: Optional[dict] = None
    for profile in doc.get("profiles") or []:
        _validate_profile(profile)
        for pc in profile.get("pluginConfig") or []:
            if pc.get("name") == PLUGIN_NAME:
                if args is not None:
                    raise ConfigError(
                        f"multiple {PLUGIN_NAME} pluginConfig entries")
                args = pc.get("args") or {}
    return decode_plugin_args(version, args, leader_election=leader)


def _validate_profile(profile: dict) -> None:
    """Reject profile settings this scheduler cannot honor — silently
    ignoring an edit (a different schedulerName, CapacityScheduling
    disabled for a phase) would let a config change deploy as a no-op.
    Only the canonical enablement (CapacityScheduling on at preFilter/
    postFilter/reserve) is accepted; plugin wiring is compiled in, not
    configurable."""
    name = profile.get("schedulerName")
    if name is not None and name != constants.SCHEDULER_NAME:
        raise ConfigError(
            f"unsupported schedulerName {name!r}: this binary schedules "
            f"pods selecting {constants.SCHEDULER_NAME!r}")
    for phase, spec in (profile.get("plugins") or {}).items():
        enabled = [p.get("name") for p in (spec or {}).get("enabled") or []]
        disabled = [p.get("name") for p in (spec or {}).get("disabled") or []]
        if enabled not in ([], [PLUGIN_NAME]) or PLUGIN_NAME in disabled:
            raise ConfigError(
                f"unsupported plugins.{phase} stanza: only "
                f"{PLUGIN_NAME!r} enablement is supported (plugin wiring "
                "is compiled into this scheduler, not configurable)")


def load_scheduler_config(path: str) -> CapacitySchedulingArgs:
    """Load scheduler args from ``path``, auto-detecting the wire shape
    (KubeSchedulerConfiguration vs flat snake_case args)."""
    with open(path) as f:
        doc = yaml.safe_load(f) or {}
    if not isinstance(doc, dict):
        raise ConfigError(f"scheduler config must be a mapping, got "
                          f"{type(doc).__name__}")
    if "apiVersion" in doc:
        return decode_scheduler_configuration(doc)
    return CapacitySchedulingArgs.from_yaml_file(path)
