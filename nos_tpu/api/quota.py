"""ElasticQuota / CompositeElasticQuota types.

Analog of reference pkg/api/nos.nebuly.com/v1alpha1/elasticquota_types.go:30-60
and compositeelasticquota_types.go:29-57:

- ``ElasticQuota``: namespace-scoped quota with ``spec.min`` (guaranteed) and
  optional ``spec.max`` (cap); ``status.used`` maintained by the operator.
  Namespaces may *borrow* unused min from other namespaces (pods beyond min
  are labeled over-quota and are preemptible).
- ``CompositeElasticQuota``: one quota spanning ``spec.namespaces``.

Quotas count TPU chips (google.com/tpu), TPU sub-slices, the derived
nos.ai/tpu-memory scalar, and (mixed clusters) GPU resources, all through
the same ResourceList machinery.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from nos_tpu.kube.objects import ObjectMeta, ResourceList


@dataclass
class ElasticQuotaSpec:
    min: ResourceList = field(default_factory=dict)
    max: Optional[ResourceList] = None


@dataclass
class ElasticQuotaStatus:
    used: ResourceList = field(default_factory=dict)


@dataclass
class ElasticQuota:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ElasticQuotaSpec = field(default_factory=ElasticQuotaSpec)
    status: ElasticQuotaStatus = field(default_factory=ElasticQuotaStatus)

    KIND = "ElasticQuota"


@dataclass
class CompositeElasticQuotaSpec:
    namespaces: List[str] = field(default_factory=list)
    min: ResourceList = field(default_factory=dict)
    max: Optional[ResourceList] = None


@dataclass
class CompositeElasticQuota:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: CompositeElasticQuotaSpec = field(default_factory=CompositeElasticQuotaSpec)
    status: ElasticQuotaStatus = field(default_factory=ElasticQuotaStatus)

    KIND = "CompositeElasticQuota"


# -- builder factories (analog of elasticquota_factory.go) -------------------

def make_elastic_quota(
    name: str,
    namespace: str,
    min: ResourceList,
    max: Optional[ResourceList] = None,
) -> ElasticQuota:
    return ElasticQuota(
        metadata=ObjectMeta(name=name, namespace=namespace),
        spec=ElasticQuotaSpec(min=dict(min), max=dict(max) if max is not None else None),
    )


def make_composite_elastic_quota(
    name: str,
    namespace: str,
    namespaces: List[str],
    min: ResourceList,
    max: Optional[ResourceList] = None,
) -> CompositeElasticQuota:
    return CompositeElasticQuota(
        metadata=ObjectMeta(name=name, namespace=namespace),
        spec=CompositeElasticQuotaSpec(
            namespaces=list(namespaces),
            min=dict(min),
            max=dict(max) if max is not None else None,
        ),
    )
