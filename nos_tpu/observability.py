"""Domain metrics for the nos-tpu control plane.

The reference has no custom domain metrics (SURVEY §5 — only stock
controller-runtime endpoints); the survey flags that as a gap since the
north-star metrics are chip utilization and schedule latency. These
instruments close it. They live on the default registry so every cmd/
binary's /metrics endpoint (nos_tpu/cmd/serve.py) exposes whichever subset
its process exercises.
"""
from __future__ import annotations

from nos_tpu.utils.metrics import default_registry

_r = default_registry()

# --- partitioning control plane (the §3.2 loop) -----------------------
PLANS_TOTAL = _r.counter(
    "nos_partitioning_plans_total",
    "Partitioning plans produced by the planner, by outcome "
    "(actuated: a new desired state was written; noop: plan matched the "
    "current state).",
    ("outcome",),
)
PLAN_DURATION = _r.histogram(
    "nos_partitioning_plan_duration_seconds",
    "Wall time of one planning pass (snapshot + plan + actuate).",
)
PLAN_BATCH_SIZE = _r.histogram(
    "nos_partitioning_batch_pods",
    "Pending pods considered per planning pass.",
    buckets=(1, 2, 5, 10, 20, 50, 100, 250),
)

# --- scheduler --------------------------------------------------------
SCHEDULE_ATTEMPTS = _r.counter(
    "nos_scheduler_attempts_total",
    "Pod scheduling attempts by result (bound | unschedulable | error | "
    "gang_wait | preempted_victims).",
    ("result",),
)
SCHEDULE_DURATION = _r.histogram(
    "nos_scheduler_e2e_duration_seconds",
    "Wall time to schedule one pod (PreFilter through Bind).",
)
SCHEDULE_SERVICE = _r.histogram(
    "nos_scheduler_service_seconds",
    "Per-pod scheduling service time: one attempt's wall time, amortized "
    "over the pods the attempt bound (a 32-worker gang placement counts "
    "as 32 samples of duration/32). The bench's scale_service_* "
    "percentiles read THIS histogram — runtime and bench report from the "
    "same counters.",
    buckets=(0.0002, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
             0.05, 0.1, 0.25, 1.0, 5.0),
)
SWEEP_WIDTH = _r.histogram(
    "nos_scheduler_sweep_nodes_visited",
    "Nodes the feasibility sweep ran the filter pipeline on, per pod "
    "attempt (nodes pruned by the free-capacity index are not counted — "
    "this is the sweep width the scheduler actually pays for).",
    buckets=(1, 2, 5, 10, 25, 50, 100, 250, 1000, 4096, 16384),
)
# NOTE: bench_sched calls enable_sample_tracking() on the two histograms
# above to read exact percentiles; production daemons never do, so they
# pay buckets only — no raw-sample buffers.
PREEMPTION_VICTIMS = _r.counter(
    "nos_scheduler_preemption_victims_total",
    "Pods deleted as preemption victims by the capacity plugin.",
)
GANGS_PLACED = _r.counter(
    "nos_scheduler_gangs_placed_total",
    "Multi-host gangs placed atomically.",
)
JOBSETS_PLACED = _r.counter(
    "nos_scheduler_jobsets_placed_total",
    "Multislice JobSets (gangs of gangs) placed co-atomically across "
    "distinct ICI domains.",
)

# --- node agent -------------------------------------------------------
AGENT_REPORTS = _r.counter(
    "nos_tpuagent_reports_total",
    "Status reports written by the tpuagent reporter, by outcome "
    "(changed | unchanged | error).",
    ("outcome",),
)
AGENT_APPLIES = _r.counter(
    "nos_tpuagent_applies_total",
    "Partition plans applied by the tpuagent actuator, by outcome "
    "(ok | error | skipped).",
    ("outcome",),
)
AGENT_UNHEALTHY_CHIPS = _r.gauge(
    "nos_tpuagent_unhealthy_chips",
    "TPU chips failing the device-health probe on this node.",
    ("node",),
)

# --- node lifecycle / slice repair (nos_tpu/lifecycle) ----------------
LIFECYCLE_EVENTS = _r.counter(
    "nos_lifecycle_events_total",
    "Lifecycle signals handled by the node-lifecycle controller, by kind "
    "(lease_expired | node_deleted | maintenance | preemption | "
    "chip_degraded | recovered).",
    ("kind",),
)
LIFECYCLE_EVICTED_PODS = _r.counter(
    "nos_lifecycle_evicted_pods_total",
    "Pods drained and recreated by the slice-repair path, by reason.",
    ("reason",),
)
LIFECYCLE_SLICE_EVICTIONS = _r.counter(
    "nos_lifecycle_slice_evictions_total",
    "Whole-gang (atomic failure domain) evictions: one dead host evicted "
    "its entire multi-host gang across the ICI domain.",
)
LIFECYCLE_NODES_NOT_READY = _r.gauge(
    "nos_lifecycle_nodes_not_ready",
    "Nodes the lifecycle controller currently holds NotReady "
    "(cordoned + tainted).",
)
LIFECYCLE_DETECTION = _r.histogram(
    "nos_lifecycle_detection_seconds",
    "Fault-injection to NotReady-detection latency (populated by the "
    "chaos harness, which knows the injection instant; units are the "
    "harness's simulated-clock seconds).",
    buckets=(0.25, 0.5, 1.0, 2.0, 3.0, 5.0, 10.0, 30.0, 60.0, 120.0),
)
LIFECYCLE_MTTR = _r.histogram(
    "nos_lifecycle_mttr_seconds",
    "Fault-injection to full-repair latency: every gang the fault "
    "displaced is atomically rebound (chaos-harness simulated-clock "
    "seconds).",
    buckets=(0.5, 1.0, 2.0, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0),
)

# --- quota ------------------------------------------------------------
QUOTA_USED = _r.gauge(
    "nos_quota_used",
    "Current status.used of each (Composite)ElasticQuota, per resource.",
    ("quota", "resource"),
)
OVERQUOTA_PODS = _r.gauge(
    "nos_quota_overquota_pods",
    "Pods currently labeled over-quota, per quota object.",
    ("quota",),
)

# --- distributed tracing (nos_tpu/obs) --------------------------------
TRACE_SPANS = _r.counter(
    "nos_trace_spans_total",
    "Tracing spans completed in this process, by control-plane component "
    "(scheduler | quota | partitioner | lifecycle | tpuagent | chaos).",
    ("component",),
)

# --- utilization (north-star) ----------------------------------------
CHIPS_ALLOCATABLE = _r.gauge(
    "nos_tpu_chips_allocatable",
    "TPU chips allocatable on partitioning-managed nodes.",
)
CHIPS_USED = _r.gauge(
    "nos_tpu_chips_used",
    "TPU chips requested by running pods on partitioning-managed nodes.",
)
