"""TPU generation facts: host grids, HBM, slice topologies, sub-slice menus.

The analog of the reference's hard-coded MIG geometry tables
(pkg/gpu/mig/known_configs.go:25-135), with two TPU-first differences:

1. **Sub-slice geometries are derived, not enumerated.** A geometry (multiset
   of ``Profile`` rectangles) is legal on a host iff the rectangles exactly
   tile the host's chip grid — that's what "sub-slice" means physically on
   the ICI mesh. ``allowed_geometries`` computes the full menu by exact-cover
   backtracking over the (tiny: ≤8-cell) grid, restricted to the generation's
   supported profile shapes. Like the reference's table it is overridable at
   runtime (``set_known_generations``; analog of mig.SetKnownGeometries,
   cmd/gpupartitioner/gpupartitioner.go:123-135).

2. **Multi-host slice topologies are a first-class table.** Each generation
   lists its legal slice shapes (GKE ``gke-tpu-topology`` values) with chip
   and host counts; the gang planner places whole topologies, since multi-host
   ICI wiring is fixed at node-pool creation (SURVEY §7 risk: TPU
   repartitioning is coarser than MIG).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from functools import lru_cache
from typing import Dict, List, Optional, Tuple

from nos_tpu.tpu.slice import Geometry, Profile


@dataclass(frozen=True)
class SliceTopology:
    """One legal multi-host slice shape, e.g. 4x4x4 on v5p."""

    dims: Tuple[int, ...]            # (x, y) for 2D generations, (x, y, z) for 3D

    @property
    def name(self) -> str:
        return "x".join(str(d) for d in self.dims)

    @property
    def chips(self) -> int:
        n = 1
        for d in self.dims:
            n *= d
        return n

    def __str__(self) -> str:
        return self.name


@dataclass(frozen=True)
class Generation:
    """One TPU generation's scheduling-relevant facts."""

    name: str                         # GKE accelerator label value
    short: str                        # v4 / v5e / v5p / v6e
    host_rows: int                    # host chip-grid shape
    host_cols: int
    hbm_gb_per_chip: int
    # sub-slice profile shapes supported for per-host partitioning
    subslice_profiles: Tuple[Profile, ...]
    # legal multi-host (and single-host) slice topologies
    topologies: Tuple[SliceTopology, ...]

    @property
    def chips_per_host(self) -> int:
        return self.host_rows * self.host_cols

    def hosts_for(self, topo: SliceTopology) -> int:
        return max(1, topo.chips // self.chips_per_host)


def _t(*dims_list: str) -> Tuple[SliceTopology, ...]:
    return tuple(SliceTopology(tuple(int(d) for d in s.split("x"))) for s in dims_list)


# ---------------------------------------------------------------------------
# The generation table. GKE accelerator label values per Cloud TPU docs;
# host grids: v4/v5p boards are 2x2 (4 chips, 3D torus between boards),
# v5e/v6e hosts are 2x4 (8 chips, 2D torus).
# ---------------------------------------------------------------------------
V4 = Generation(
    name="tpu-v4-podslice",
    short="v4",
    host_rows=2, host_cols=2,
    hbm_gb_per_chip=32,
    subslice_profiles=(Profile(1, 1), Profile(1, 2), Profile(2, 2)),
    topologies=_t(
        "2x2x1", "2x2x2", "2x2x4", "2x4x4", "4x4x4", "4x4x8",
        "4x8x8", "8x8x8", "8x8x12", "8x8x16", "8x16x16", "12x16x16",
    ),
)

V5E = Generation(
    name="tpu-v5-lite-podslice",
    short="v5e",
    host_rows=2, host_cols=4,
    hbm_gb_per_chip=16,
    subslice_profiles=(Profile(1, 1), Profile(2, 2), Profile(2, 4)),
    topologies=_t("1x1", "2x2", "2x4", "4x4", "4x8", "8x8", "8x16", "16x16"),
)

V5P = Generation(
    name="tpu-v5p-slice",
    short="v5p",
    host_rows=2, host_cols=2,
    hbm_gb_per_chip=95,
    subslice_profiles=(Profile(1, 1), Profile(1, 2), Profile(2, 2)),
    topologies=_t(
        "2x2x1", "2x2x2", "2x2x4", "2x4x4", "4x4x4", "4x4x8",
        "4x8x8", "8x8x8", "8x8x16", "8x16x16", "16x16x16", "16x16x24",
    ),
)

V6E = Generation(
    name="tpu-v6e-slice",
    short="v6e",
    host_rows=2, host_cols=4,
    hbm_gb_per_chip=32,
    subslice_profiles=(Profile(1, 1), Profile(2, 2), Profile(2, 4)),
    topologies=_t("1x1", "2x2", "2x4", "4x4", "4x8", "8x8", "8x16", "16x16"),
)

_DEFAULT_GENERATIONS: Dict[str, Generation] = {
    g.name: g for g in (V4, V5E, V5P, V6E)
}
# Also index by short name for convenience.
for _g in list(_DEFAULT_GENERATIONS.values()):
    _DEFAULT_GENERATIONS[_g.short] = _g

GENERATIONS: Dict[str, Generation] = dict(_DEFAULT_GENERATIONS)


def set_known_generations(gens: List[Generation]) -> None:
    """Override the generation table at runtime (config-file analog of
    mig.SetKnownGeometries)."""
    GENERATIONS.clear()
    for g in gens:
        GENERATIONS[g.name] = g
        GENERATIONS[g.short] = g
    allowed_geometries.cache_clear()
    find_slice_topology.cache_clear()
    host_shape.cache_clear()


def reset_known_generations() -> None:
    GENERATIONS.clear()
    GENERATIONS.update(_DEFAULT_GENERATIONS)
    allowed_geometries.cache_clear()
    find_slice_topology.cache_clear()
    host_shape.cache_clear()


def load_generations_file(path: str) -> List[Generation]:
    """Load a generation-table override from YAML (the analog of the
    reference's known-MIG-geometries file, cmd/gpupartitioner/
    gpupartitioner.go:123-135 + SetKnownGeometries). Schema:

    generations:
      - name: tpu-v5-lite-podslice
        short: v5e
        host_rows: 2
        host_cols: 4
        hbm_gb_per_chip: 16
        subslice_profiles: ["1x1", "2x2", "2x4"]
        topologies: ["1x1", "2x2", "2x4", "4x4"]
    """
    import yaml

    from nos_tpu.tpu.slice import Profile

    def dims(s: str, want: Tuple[int, ...]) -> Tuple[int, ...]:
        try:
            d = tuple(int(p) for p in str(s).split("x"))
        except ValueError as e:
            raise ValueError(f"{path}: bad topology/profile {s!r}") from e
        if len(d) not in want or any(v < 1 for v in d):
            raise ValueError(
                f"{path}: {s!r} must be {' or '.join(str(w) for w in want)} "
                f"positive dims")
        return d

    with open(path) as f:
        data = yaml.safe_load(f) or {}
    gens: List[Generation] = []
    for entry in data.get("generations", []):
        missing = {"name", "short", "host_rows", "host_cols",
                   "hbm_gb_per_chip"} - set(entry)
        if missing:
            raise ValueError(f"{path}: generation missing keys {sorted(missing)}")
        profiles = [
            Profile(*dims(p, want=(2,)))
            for p in entry.get("subslice_profiles", [])
        ]
        topos = tuple(
            SliceTopology(dims(t, want=(2, 3)))
            for t in entry.get("topologies", [])
        )
        gens.append(Generation(
            name=entry["name"],
            short=entry["short"],
            host_rows=int(entry["host_rows"]),
            host_cols=int(entry["host_cols"]),
            hbm_gb_per_chip=int(entry["hbm_gb_per_chip"]),
            subslice_profiles=tuple(profiles),
            topologies=topos,
        ))
    if not gens:
        raise ValueError(f"{path}: no generations defined")
    return gens


def get_generation(name: str) -> Optional[Generation]:
    return GENERATIONS.get(name)


def chip_memory_gb(generation_name: str, default: int = 16) -> int:
    g = get_generation(generation_name)
    return g.hbm_gb_per_chip if g else default


def host_grid(generation_name: str) -> Tuple[int, int]:
    g = GENERATIONS[generation_name]
    return (g.host_rows, g.host_cols)


def slice_topologies(generation_name: str) -> Tuple[SliceTopology, ...]:
    g = get_generation(generation_name)
    return g.topologies if g else ()


@lru_cache(maxsize=4096)
def find_slice_topology(generation_name: str, topo_name: str) -> Optional[SliceTopology]:
    """Cached: the gang sub-cuboid search resolves (generation, topology
    name) once per candidate domain per gang — the uncached linear scan
    plus SliceTopology.name string-joins measured ~1.9s of the 4096-node
    burst. Cleared by set/reset_known_generations."""
    for t in slice_topologies(generation_name):
        if t.name == topo_name:
            return t
    return None


@lru_cache(maxsize=4096)
def host_shape(generation_name: str, topo: SliceTopology) -> Optional[Tuple[int, ...]]:
    """Host-grid dims of a slice topology: how the slice's hosts tile the
    chip cuboid. 3D generations (v4/v5p, 2x2 boards): (x,y,z) chips →
    (x/2, y/2, z) hosts. 2D generations (v5e/v6e, 2x4 hosts): (x,y) →
    (x/2, y/4). A topology no larger than one host maps to a single-host
    shape of all-ones. Returns None when the chip dims don't align to host
    boundaries (no valid host tiling exists)."""
    gen = get_generation(generation_name)
    if gen is None:
        return None
    if topo.chips <= gen.chips_per_host:
        return (1,) * len(topo.dims)
    if len(topo.dims) == 3:
        per_host = (gen.host_rows, gen.host_cols, 1)
    else:
        per_host = (gen.host_rows, gen.host_cols)
    if len(per_host) != len(topo.dims):
        return None
    out = []
    for d, h in zip(topo.dims, per_host):
        if d % h != 0:
            return None
        out.append(d // h)
    return tuple(out)


def is_sub_topology(generation_name: str, small: SliceTopology,
                    big: SliceTopology) -> bool:
    """True when ``small``'s host grid is an axis-aligned sub-cuboid of
    ``big``'s host grid — i.e. a gang needing ``small`` can occupy an
    ICI-contiguous host-aligned block carved out of a ``big`` pool. (The
    carved block has mesh connectivity, not the full torus's wraparound
    links; collectives over a contiguous mesh block still ride ICI, which
    is the constraint that matters for placement.)"""
    hs = host_shape(generation_name, small)
    hb = host_shape(generation_name, big)
    if hs is None or hb is None or len(hs) != len(hb):
        return False
    return all(s <= b for s, b in zip(hs, hb))


# ---------------------------------------------------------------------------
# Sub-slice geometry derivation: exact tiling of the host grid.
# ---------------------------------------------------------------------------

@lru_cache(maxsize=None)
def allowed_geometries(generation_key: str) -> Tuple[Tuple[Tuple[Profile, int], ...], ...]:
    """All distinct geometries (as sorted (profile, count) tuples) whose
    rectangles exactly tile the generation's host grid. Cached per
    generation; the host grids are tiny (≤ 8 cells) so enumeration is
    instant."""
    gen = GENERATIONS[generation_key]
    rows, cols = gen.host_rows, gen.host_cols
    profiles = set()
    for p in gen.subslice_profiles:
        profiles.add((p.rows, p.cols))
        # a rectangle can be placed rotated on the grid if it fits
        profiles.add((p.cols, p.rows))

    results: set = set()

    def canonical(counts: Dict[Tuple[int, int], int]) -> Tuple[Tuple[Profile, int], ...]:
        # merge rotations into the generation's declared orientation
        merged: Dict[Profile, int] = {}
        for (r, c), n in counts.items():
            prof = None
            for p in gen.subslice_profiles:
                if (p.rows, p.cols) == (r, c) or (p.rows, p.cols) == (c, r):
                    prof = p
                    break
            assert prof is not None
            merged[prof] = merged.get(prof, 0) + n
        return tuple(sorted(merged.items(), key=lambda kv: (kv[0].chips, str(kv[0]))))

    grid = [[False] * cols for _ in range(rows)]
    counts: Dict[Tuple[int, int], int] = {}

    def first_free() -> Optional[Tuple[int, int]]:
        for r in range(rows):
            for c in range(cols):
                if not grid[r][c]:
                    return (r, c)
        return None

    def place(r0, c0, h, w, value: bool) -> bool:
        if r0 + h > rows or c0 + w > cols:
            return False
        if value:
            for r in range(r0, r0 + h):
                for c in range(c0, c0 + w):
                    if grid[r][c]:
                        return False
            for r in range(r0, r0 + h):
                for c in range(c0, c0 + w):
                    grid[r][c] = True
        else:
            for r in range(r0, r0 + h):
                for c in range(c0, c0 + w):
                    grid[r][c] = False
        return True

    def search() -> None:
        cell = first_free()
        if cell is None:
            results.add(canonical(counts))
            return
        r0, c0 = cell
        for (h, w) in sorted(profiles):
            if place(r0, c0, h, w, True):
                key = (h, w)
                counts[key] = counts.get(key, 0) + 1
                search()
                counts[key] -= 1
                if counts[key] == 0:
                    del counts[key]
                place(r0, c0, h, w, False)

    search()
    return tuple(sorted(results, key=lambda g: (len(g), str(g))))


def allowed_geometry_list(generation_key: str) -> List[Geometry]:
    """allowed_geometries as mutable dicts."""
    return [dict(g) for g in allowed_geometries(generation_key)]
