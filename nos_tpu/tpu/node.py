"""TpuNode — the partitionable-node view built from a Node's labels and
annotations.

Analog of reference pkg/gpu/mig/node.go:40-220 (``mig.Node``): constructed
from GKE TPU node labels (accelerator type, topology) plus nos status
annotations, it implements the planner's ``PartitionableNode`` contract —
geometry queries, ``update_geometry_for``, and recomputing the node's scalar
allocatable resources after a geometry change (node.go:180-220).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from nos_tpu import constants
from nos_tpu.kube.objects import Node, ResourceList
from nos_tpu.tpu import annotation as ann
from nos_tpu.tpu import topology
from nos_tpu.tpu.host import TpuBoard
from nos_tpu.tpu.slice import Geometry, Profile


class NotATpuNode(ValueError):
    pass


@dataclass
class TpuNode:
    name: str
    generation: str                       # GENERATIONS key
    topology_name: str                    # gke-tpu-topology label value
    boards: List[TpuBoard] = field(default_factory=list)

    @classmethod
    def from_node(cls, node: Node) -> "TpuNode":
        gen_label = node.metadata.labels.get(constants.LABEL_TPU_ACCELERATOR, "")
        gen = topology.get_generation(gen_label)
        if gen is None:
            raise NotATpuNode(f"node {node.metadata.name}: unknown accelerator {gen_label!r}")
        topo = node.metadata.labels.get(constants.LABEL_TPU_TOPOLOGY, "")
        _, statuses = ann.parse_node_annotations(node.metadata.annotations)
        board_state = ann.status_to_board_state(statuses)
        n_boards = cls._board_count(node, gen)
        boards = []
        for i in range(n_boards):
            st = board_state.get(i, {"free": {}, "used": {}})
            boards.append(
                TpuBoard(generation=gen.name, index=i, used=dict(st["used"]), free=dict(st["free"]))
            )
        return cls(
            name=node.metadata.name,
            generation=gen.name,
            topology_name=topo,
            boards=boards,
        )

    @staticmethod
    def _board_count(node: Node, gen: topology.Generation) -> int:
        """A GKE TPU node is one host = one board. Kept as a method so a
        future multi-board host only changes this."""
        return 1

    # -- PartitionableNode contract (reference core/interface.go:44-56) -----
    def clone(self) -> "TpuNode":
        return TpuNode(
            self.name,
            self.generation,
            self.topology_name,
            [b.clone() for b in self.boards],
        )

    def has_free_capacity(self) -> bool:
        gen = topology.GENERATIONS[self.generation]
        partitioned = sum(b.total_chips for b in self.boards)
        free_slices = any(b.free for b in self.boards)
        return free_slices or partitioned < gen.chips_per_host * len(self.boards)

    def update_geometry_for(self, lacking: Dict[Profile, int]) -> bool:
        """Greedy per-board geometry update (reference mig.Node.UpdateGeometryFor,
        node.go:145): boards are tried in order; each consumes the demand it
        can serve before the next board is considered."""
        changed = False
        remaining = {p: q for p, q in lacking.items() if q > 0}
        for board in self.boards:
            if not remaining:
                break
            free_before = dict(board.free)
            if board.update_geometry_for(remaining):
                changed = True
            for p in list(remaining.keys()):
                # only newly created slices count against `remaining`:
                # pre-existing free slices were already netted out of the
                # cluster-wide lacking computation
                newly = board.free.get(p, 0) - free_before.get(p, 0)
                if newly > 0:
                    remaining[p] -= newly
                    if remaining[p] <= 0:
                        del remaining[p]
        return changed

    def partitioning(self) -> Dict[int, Geometry]:
        return {b.index: b.geometry for b in self.boards if b.geometry}

    # -- scalar resources ---------------------------------------------------
    def allocatable_scalar_resources(self, base: Optional[ResourceList] = None) -> ResourceList:
        """Recompute the node's allocatable extended resources from board
        geometry (reference mig.Node scalar recompute, node.go:180-220):
        sub-slice resources replace whole-chip ones once partitioned."""
        out: ResourceList = dict(base or {})
        out = {
            k: v
            for k, v in out.items()
            if not k.startswith(constants.RESOURCE_TPU_SLICE_PREFIX)
            and k != constants.RESOURCE_TPU
        }
        gen = topology.GENERATIONS[self.generation]
        unpartitioned_chips = 0
        for b in self.boards:
            if b.has_geometry():
                for p, q in b.geometry.items():
                    out[p.resource_name] = out.get(p.resource_name, 0) + q
            else:
                unpartitioned_chips += gen.chips_per_host
        if unpartitioned_chips:
            out[constants.RESOURCE_TPU] = out.get(constants.RESOURCE_TPU, 0) + unpartitioned_chips
        return out

    def free_slices(self) -> Dict[Profile, int]:
        out: Dict[Profile, int] = {}
        for b in self.boards:
            for p, q in b.free.items():
                out[p] = out.get(p, 0) + q
        return out

    def used_slices(self) -> Dict[Profile, int]:
        out: Dict[Profile, int] = {}
        for b in self.boards:
            for p, q in b.used.items():
                out[p] = out.get(p, 0) + q
        return out
