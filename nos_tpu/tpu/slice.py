"""Sub-slice profiles and geometries.

Analog of reference pkg/gpu/partitioning.go:27-60 (`gpu.Slice`,
`gpu.Geometry`) and pkg/gpu/mig/profile.go:29-100 (profile name parsing).
A TPU sub-slice profile is a contiguous ``<rows>x<cols>`` rectangle of a
host's chip grid; its resource name is ``nos.ai/tpu-slice-<rows>x<cols>``.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict

from nos_tpu import constants


@dataclass(frozen=True)
class Profile:
    """A sub-slice shape. Ordering is by chip count then shape (so sorted()
    yields smallest-first, the packing order the planner wants — analog of
    gpu.Slice.SmallerThan, reference pkg/gpu/partitioning.go:34)."""

    rows: int
    cols: int

    def __post_init__(self):
        if self.rows <= 0 or self.cols <= 0:
            raise ValueError(f"invalid profile {self.rows}x{self.cols}")

    @property
    def chips(self) -> int:
        return self.rows * self.cols

    @property
    def resource_name(self) -> str:
        return f"{constants.RESOURCE_TPU_SLICE_PREFIX}{self.rows}x{self.cols}"

    def __str__(self) -> str:
        return f"{self.rows}x{self.cols}"

    def __lt__(self, other: "Profile") -> bool:
        return (self.chips, self.rows, self.cols) < (other.chips, other.rows, other.cols)

    def smaller_than(self, other: "Profile") -> bool:
        return self.chips < other.chips


# A geometry maps each profile to how many such sub-slices exist on a board
# (analog of gpu.Geometry = map[Slice]int).
Geometry = Dict[Profile, int]


def parse_profile(name: str) -> Profile:
    """Parse ``1x1``/``2x4`` or the full resource name
    ``nos.ai/tpu-slice-2x4`` into a Profile."""
    m = constants.TPU_SLICE_RESOURCE_REGEX.match(name)
    if m:
        return Profile(int(m.group(1)), int(m.group(2)))
    parts = name.split("x")
    if len(parts) == 2 and all(p.isdigit() for p in parts):
        return Profile(int(parts[0]), int(parts[1]))
    raise ValueError(f"invalid tpu sub-slice profile: {name!r}")


def is_slice_resource(resource_name: str) -> bool:
    return bool(constants.TPU_SLICE_RESOURCE_REGEX.match(resource_name))


def resource_chips(resources: Dict[str, float]) -> float:
    """Chip count of a resource request/allocatable dict: whole chips
    plus sub-slice resources converted by their geometry. THE
    utilization-accounting convention — the partitioning controller's
    north-star gauges and the metrics exporter both read through it, so
    a new resource shape lands in every consumer at once."""
    n = resources.get(constants.RESOURCE_TPU, 0)
    for r, qty in resources.items():
        if r.startswith(constants.RESOURCE_TPU_SLICE_PREFIX):
            try:
                n += qty * parse_profile(r).chips
            except ValueError:
                continue    # malformed resource name
    return n


def geometry_chips(g: Geometry) -> int:
    return sum(p.chips * q for p, q in g.items())


def geometry_slices(g: Geometry) -> int:
    return sum(g.values())


def fewest_slices_geometry(geometries: list[Geometry]) -> Geometry | None:
    """The geometry with the fewest slices — used to initialize virgin boards
    with the largest partitions (analog of gpu.GetFewestSlicesGeometry,
    reference pkg/gpu/partitioning.go:67)."""
    if not geometries:
        return None
    return min(geometries, key=lambda g: (geometry_slices(g), _geometry_key(g)))


def _geometry_key(g: Geometry):
    return tuple(sorted((str(p), q) for p, q in g.items()))


def format_geometry(g: Geometry) -> str:
    return ", ".join(f"{q}x[{p}]" for p, q in sorted(g.items(), key=lambda kv: str(kv[0])))
