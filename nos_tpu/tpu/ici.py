"""ICI domain modeling — which nodes share an interconnect mesh.

No GPU analog exists in the reference (SURVEY §2.7: "ICI/DCN topology
modeling ... as a first-class input to the planner and the gang-scheduler
plugin"). On GKE, a multi-host TPU slice is one node pool: every node
(host) in the pool is wired into the same ICI mesh with a fixed topology
chosen at pool creation; traffic between pools crosses DCN. So:

- an **ICI domain** = (node pool, generation, slice topology): the set of
  hosts a gang may span with full ICI bandwidth;
- a gang must be placed entirely inside one domain (DCN-crossing
  avoidance is a hard constraint here, not a score);
- within a domain, host ordering follows the worker index convention
  (host-index label, else natural name sort) so the job's mesh axes line
  up with the physical torus.
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from nos_tpu import constants
from nos_tpu.kube.objects import Node
from nos_tpu.tpu import topology

_NUM_RE = re.compile(r"(\d+)")


def host_order_key(node: Node):
    """Worker-order sort key for a pool's hosts. An explicit host-index
    label wins; otherwise NATURAL sort of the name (digit runs compared
    numerically) — plain lexicographic order would put 'w10' before 'w2'
    and scatter a 10+-host pool's worker->coordinate map across the
    torus."""
    idx = node.metadata.labels.get(constants.LABEL_TPU_HOST_INDEX)
    if idx is not None:
        try:
            return (0, int(idx), node.metadata.name)
        except ValueError:
            pass
    parts = _NUM_RE.split(node.metadata.name)
    # tag each element so int/str segments stay mutually comparable even
    # across heterogeneous name structures within one pool
    return (1,) + tuple(
        (0, int(p)) if p.isdigit() else (1, p) for p in parts
    ) + ((1, node.metadata.name),)


@dataclass
class IciDomain:
    pool: str
    generation: str                     # GENERATIONS key (label value)
    topology_name: str
    nodes: List[Node] = field(default_factory=list)   # worker order (host_order_key)
    # memo for host_shape: (generation, topology_name) are fixed at
    # construction, and node_at() resolves the shape once per candidate
    # host in the gang sub-cuboid search — hot enough to pin per-instance
    _host_shape_memo: object = field(default=False, repr=False, compare=False)
    _node_names_memo: Optional[List[str]] = field(
        default=None, repr=False, compare=False)

    def node_names(self) -> List[str]:
        """Host names in worker order, memoized after the domain is built
        (group_ici_domains sorts and then never mutates ``nodes``) — the
        gang fragmentation score iterates these per candidate domain."""
        memo = self._node_names_memo
        if memo is None:
            memo = [n.metadata.name for n in self.nodes]
            object.__setattr__(self, "_node_names_memo", memo)
        return memo

    @property
    def slice_topology(self) -> Optional[topology.SliceTopology]:
        return topology.find_slice_topology(self.generation, self.topology_name)

    @property
    def hosts(self) -> int:
        return len(self.nodes)

    @property
    def expected_hosts(self) -> Optional[int]:
        gen = topology.get_generation(self.generation)
        topo = self.slice_topology
        if gen is None or topo is None:
            return None
        return gen.hosts_for(topo)

    def is_complete(self) -> bool:
        """All hosts of the slice are present (a gang needs the whole
        slice's ICI mesh; an incomplete pool cannot host it)."""
        expected = self.expected_hosts
        return expected is not None and self.hosts == expected

    @property
    def host_shape(self) -> Optional[tuple]:
        """Host-grid dims of this domain's slice topology (see
        topology.host_shape). Worker index = row-major position in this
        grid — the TPU runtime's host ordering convention (host-index
        label when present, else natural name sort)."""
        memo = self._host_shape_memo
        if memo is False:            # False = unset (None is a valid answer)
            topo = self.slice_topology
            memo = None if topo is None \
                else topology.host_shape(self.generation, topo)
            object.__setattr__(self, "_host_shape_memo", memo)
        return memo

    def node_at(self, coord: tuple) -> Optional[Node]:
        """Node at a host-grid coordinate (row-major ravel). Requires a
        complete domain for the index↔coordinate map to be sound."""
        shape = self.host_shape
        if shape is None or len(coord) != len(shape):
            return None
        idx = 0
        for c, d in zip(coord, shape):
            if not (0 <= c < d):
                return None
            idx = idx * d + c
        if idx >= len(self.nodes):
            return None
        return self.nodes[idx]


def group_ici_domains(nodes: List[Node]) -> Dict[str, IciDomain]:
    """Group TPU nodes into ICI domains by node pool."""
    domains: Dict[str, IciDomain] = {}
    for node in nodes:
        labels = node.metadata.labels
        pool = labels.get(constants.LABEL_NODEPOOL)
        gen = labels.get(constants.LABEL_TPU_ACCELERATOR)
        topo = labels.get(constants.LABEL_TPU_TOPOLOGY)
        if not pool or not gen or not topo:
            continue
        if topology.get_generation(gen) is None:
            continue
        domain = domains.setdefault(pool, IciDomain(pool, gen, topo))
        domain.nodes.append(node)
    for domain in domains.values():
        domain.nodes.sort(key=host_order_key)
    return domains
