"""Per-board geometry state machine.

Analog of reference pkg/gpu/mig/gpu.go:97-217 (``mig.GPU``): tracks used and
free sub-slices on one TPU board (host chip grid), and answers

- ``can_apply_geometry``   — a new geometry is only applicable if it keeps
                             every *used* sub-slice (never delete used
                             devices; reference gpu.go:97-116),
- ``init_geometry``        — virgin boards get the fewest-slices geometry
                             (whole-board partition; reference gpu.go:118),
- ``apply_geometry``,
- ``update_geometry_for``  — greedy search over the generation's allowed
                             geometries for the one that (a) preserves used
                             slices and (b) provides the most lacking slices
                             (reference gpu.go:158-217).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional

from nos_tpu.tpu.slice import Geometry, Profile, fewest_slices_geometry, geometry_chips
from nos_tpu.tpu import topology


@dataclass
class TpuBoard:
    generation: str                   # key into topology.GENERATIONS
    index: int = 0
    used: Dict[Profile, int] = field(default_factory=dict)
    free: Dict[Profile, int] = field(default_factory=dict)

    # -- views --------------------------------------------------------------
    @property
    def geometry(self) -> Geometry:
        g: Geometry = {}
        for src in (self.used, self.free):
            for p, q in src.items():
                g[p] = g.get(p, 0) + q
        return g

    def has_geometry(self) -> bool:
        return bool(self.geometry)

    def clone(self) -> "TpuBoard":
        return TpuBoard(self.generation, self.index, dict(self.used), dict(self.free))

    # -- state machine ------------------------------------------------------
    def can_apply_geometry(self, g: Geometry) -> bool:
        """True iff ``g`` is a legal board geometry that keeps every used
        sub-slice."""
        if tuple(sorted(g.items(), key=lambda kv: (kv[0].chips, str(kv[0])))) \
                not in topology.allowed_geometries(self.generation):
            return False
        return all(g.get(p, 0) >= q for p, q in self.used.items() if q > 0)

    def apply_geometry(self, g: Geometry) -> None:
        if not self.can_apply_geometry(g):
            raise ValueError(
                f"board {self.index}: cannot apply geometry {g} over used {self.used}"
            )
        self.free = {
            p: q - self.used.get(p, 0) for p, q in g.items() if q - self.used.get(p, 0) > 0
        }

    def init_geometry(self) -> None:
        """Reference gpu.go:118 InitGeometry — fewest slices (largest parts)."""
        if self.has_geometry():
            return
        g = fewest_slices_geometry(topology.allowed_geometry_list(self.generation))
        if g is not None:
            self.apply_geometry(g)

    def update_geometry_for(self, lacking: Dict[Profile, int]) -> bool:
        """Try to re-partition this board to provide as many of the lacking
        sub-slices as possible without disturbing used ones. Returns True if
        the geometry changed. Greedy: pick the allowed geometry maximizing
        newly-provided lacking slices, tie-broken toward fewer total slices
        (less fragmentation). Reference pkg/gpu/mig/gpu.go:158-217."""
        if not lacking:
            return False
        def provided_by(free_slices: Dict[Profile, int]) -> int:
            return sum(
                min(want, free_slices.get(p, 0)) for p, want in lacking.items() if want > 0
            )

        current_score = provided_by(self.free)
        best: Optional[Geometry] = None
        best_score = current_score
        for cand in topology.allowed_geometry_list(self.generation):
            if cand == self.geometry or not self.can_apply_geometry(cand):
                continue
            cand_free = {
                p: q - self.used.get(p, 0)
                for p, q in cand.items()
                if q - self.used.get(p, 0) > 0
            }
            score = provided_by(cand_free)
            if score > best_score or (
                best is not None
                and score == best_score
                and sum(cand.values()) < sum(best.values())
            ):
                best = cand
                best_score = score
        if best is None:
            return False
        self.apply_geometry(best)
        return True

    # -- allocation bookkeeping (used by snapshot simulation) ---------------
    def reserve(self, p: Profile, n: int = 1) -> bool:
        if self.free.get(p, 0) < n:
            return False
        self.free[p] -= n
        if self.free[p] == 0:
            del self.free[p]
        self.used[p] = self.used.get(p, 0) + n
        return True

    def release(self, p: Profile, n: int = 1) -> None:
        have = self.used.get(p, 0)
        if have < n:
            raise ValueError(f"board {self.index}: releasing {n}x{p} but only {have} used")
        self.used[p] = have - n
        if self.used[p] == 0:
            del self.used[p]
        self.free[p] = self.free.get(p, 0) + n

    @property
    def total_chips(self) -> int:
        return geometry_chips(self.geometry)
