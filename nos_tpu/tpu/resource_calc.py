"""ResourceCalculator — pod requests with derived accelerator-memory scalars.

Analog of reference pkg/gpu/util/resource.go:28-88: the quota layer compares
namespaces by a common currency. The reference derives
``nos.nebuly.com/gpu-memory`` (N GB per whole GPU, parsed GB per MIG
profile); here we derive ``nos.ai/tpu-memory`` from whole TPU chips
(per-generation HBM, default when unknown) and from sub-slice profiles
(chips(profile) x HBM/chip), plus the GPU derivation for mixed clusters.
"""
from __future__ import annotations

import re
from dataclasses import dataclass

from nos_tpu import constants
from nos_tpu.kube.objects import Pod, ResourceList
from nos_tpu.tpu.slice import parse_profile
from nos_tpu.tpu import topology

_MIG_RE = re.compile(r"^nvidia\.com/mig-\d+g\.(\d+)gb$")
# MPS memory slice (reference pkg/gpu/slicing/profile.go:29-64)
_MPS_RE = re.compile(r"^nvidia\.com/gpu-(\d+)gb$")


@dataclass
class ResourceCalculator:
    tpu_memory_gb: int = constants.DEFAULT_TPU_MEMORY_GB
    nvidia_gpu_memory_gb: int = constants.DEFAULT_NVIDIA_GPU_MEMORY_GB
    # when the pod's target generation is known (node selector), per-chip HBM
    # comes from the generation table instead of the default
    generation: str | None = None

    def _hbm_per_chip(self) -> int:
        if self.generation:
            return topology.chip_memory_gb(self.generation, self.tpu_memory_gb)
        return self.tpu_memory_gb

    def compute_request(self, requests: ResourceList) -> ResourceList:
        out = dict(requests)
        tpu_mem = 0.0
        gpu_mem = 0.0
        for name, qty in requests.items():
            if name == constants.RESOURCE_TPU:
                tpu_mem += qty * self._hbm_per_chip()
            elif name.startswith(constants.RESOURCE_TPU_SLICE_PREFIX):
                try:
                    profile = parse_profile(name)
                except ValueError:
                    continue  # malformed user-supplied resource name
                tpu_mem += qty * profile.chips * self._hbm_per_chip()
            elif name == constants.RESOURCE_NVIDIA_GPU:
                gpu_mem += qty * self.nvidia_gpu_memory_gb
            else:
                m = _MIG_RE.match(name) or _MPS_RE.match(name)
                if m:
                    gpu_mem += qty * int(m.group(1))
        if tpu_mem:
            out[constants.RESOURCE_TPU_MEMORY] = out.get(constants.RESOURCE_TPU_MEMORY, 0) + tpu_mem
        if gpu_mem:
            out[constants.RESOURCE_GPU_MEMORY] = out.get(constants.RESOURCE_GPU_MEMORY, 0) + gpu_mem
        return out

    def compute_pod_request(self, pod: Pod) -> ResourceList:
        """Reference ResourceCalculator.ComputePodRequest (resource.go:60)."""
        hbm = self._generation_for_pod(pod)
        calc = self if hbm is None else ResourceCalculator(
            self.tpu_memory_gb, self.nvidia_gpu_memory_gb, hbm
        )
        return calc.compute_request(pod.request())

    @staticmethod
    def _generation_for_pod(pod: Pod) -> str | None:
        gen = pod.spec.node_selector.get(constants.LABEL_TPU_ACCELERATOR)
        return gen if gen and topology.get_generation(gen) else None
