"""Device model (analog of reference pkg/gpu/device.go:26-130 and
pkg/resource device types).

A ``Device`` is one advertised sub-slice resource instance on a node board,
with its usage status as observed by the node agent (via the device plugin /
pod-resources API in production; via the native tpuagent library here).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, List

from nos_tpu.tpu.slice import Profile


STATUS_FREE = "free"
STATUS_USED = "used"


@dataclass(frozen=True)
class Device:
    device_id: str
    board_index: int
    profile: Profile
    status: str = STATUS_FREE          # free | used

    def is_used(self) -> bool:
        return self.status == STATUS_USED

    def is_free(self) -> bool:
        return self.status == STATUS_FREE


class DeviceList(List[Device]):
    """Rich grouping helpers (analog of gpu.DeviceList group-bys)."""

    def group_by_board(self) -> Dict[int, "DeviceList"]:
        out: Dict[int, DeviceList] = {}
        for d in self:
            out.setdefault(d.board_index, DeviceList()).append(d)
        return out

    def group_by_profile(self) -> Dict[Profile, "DeviceList"]:
        out: Dict[Profile, DeviceList] = {}
        for d in self:
            out.setdefault(d.profile, DeviceList()).append(d)
        return out

    def used(self) -> "DeviceList":
        return DeviceList(d for d in self if d.is_used())

    def free(self) -> "DeviceList":
        return DeviceList(d for d in self if d.is_free())

    def geometry(self) -> Dict[Profile, int]:
        out: Dict[Profile, int] = {}
        for d in self:
            out[d.profile] = out.get(d.profile, 0) + 1
        return out

    @staticmethod
    def of(devices: Iterable[Device]) -> "DeviceList":
        return DeviceList(devices)
