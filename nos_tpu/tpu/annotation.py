"""Spec/status node-annotation codec — the system's wire format.

Analog of reference pkg/gpu/annotation.go:26-98 (+ list ops :150-220) and
pkg/gpu/mig/annotation.go. The partitioner writes *spec* annotations
(desired geometry per board); the node tpuagent writes *status* annotations
(observed free/used sub-slices per board) plus the plan-id handshake pair
that serializes plan application (reference
internal/controllers/gpupartitioner/partitioner_controller.go:212-232).

    nos.ai/spec-tpu-<board>-<profile>: "<count>"
    nos.ai/status-tpu-<board>-<profile>-<free|used>: "<count>"
    nos.ai/spec-partitioning-plan: "<plan-id>"
    nos.ai/status-partitioning-plan: "<plan-id>"
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterable, Tuple

from nos_tpu import constants
from nos_tpu.tpu.device import Device, DeviceList
from nos_tpu.tpu.slice import Geometry, Profile, parse_profile


@dataclass(frozen=True)
class SpecAnnotation:
    board_index: int
    profile: Profile
    quantity: int

    @property
    def key(self) -> str:
        return f"{constants.ANNOTATION_SPEC_PREFIX}{self.board_index}-{self.profile}"


@dataclass(frozen=True)
class StatusAnnotation:
    board_index: int
    profile: Profile
    status: str          # "free" | "used"
    quantity: int

    @property
    def key(self) -> str:
        return (
            f"{constants.ANNOTATION_STATUS_PREFIX}"
            f"{self.board_index}-{self.profile}-{self.status}"
        )


def parse_node_annotations(
    annotations: Dict[str, str],
) -> Tuple[list[SpecAnnotation], list[StatusAnnotation]]:
    """Reference gpu.ParseNodeAnnotations (pkg/gpu/annotation.go:26)."""
    specs: list[SpecAnnotation] = []
    statuses: list[StatusAnnotation] = []
    for key, value in annotations.items():
        m = constants.ANNOTATION_SPEC_REGEX.match(key)
        if m:
            try:
                qty = int(value)
                if qty <= 0:
                    continue
                specs.append(SpecAnnotation(int(m.group(1)), parse_profile(m.group(2)), qty))
            except ValueError:
                continue
            continue
        m = constants.ANNOTATION_STATUS_REGEX.match(key)
        if m:
            try:
                qty = int(value)
                if qty <= 0:
                    continue
                statuses.append(
                    StatusAnnotation(int(m.group(1)), parse_profile(m.group(2)), m.group(3), qty)
                )
            except ValueError:
                continue
    return specs, statuses


def spec_annotations_from_partitioning(
    boards: Dict[int, Geometry],
) -> Dict[str, str]:
    """Desired-state annotations for a node (one entry per board+profile)."""
    out: Dict[str, str] = {}
    for board_index, geometry in boards.items():
        for profile, quantity in geometry.items():
            if quantity > 0:
                sa = SpecAnnotation(board_index, profile, quantity)
                out[sa.key] = str(quantity)
    return out


def status_annotations_from_devices(devices: Iterable[Device]) -> Dict[str, str]:
    """Observed-state annotations (reference DeviceList.AsStatusAnnotation,
    pkg/gpu/device.go:101)."""
    counts: Dict[Tuple[int, Profile, str], int] = {}
    for d in devices:
        key = (d.board_index, d.profile, d.status)
        counts[key] = counts.get(key, 0) + 1
    return {
        StatusAnnotation(b, p, s, q).key: str(q) for (b, p, s), q in counts.items()
    }


def spec_from_annotations(specs: Iterable[SpecAnnotation]) -> Dict[int, Geometry]:
    out: Dict[int, Geometry] = {}
    for sa in specs:
        board = out.setdefault(sa.board_index, {})
        board[sa.profile] = board.get(sa.profile, 0) + sa.quantity
    return out


def status_to_board_state(
    statuses: Iterable[StatusAnnotation],
) -> Dict[int, Dict[str, Dict[Profile, int]]]:
    """{board: {"free": {profile: n}, "used": {profile: n}}}"""
    out: Dict[int, Dict[str, Dict[Profile, int]]] = {}
    for st in statuses:
        board = out.setdefault(st.board_index, {"free": {}, "used": {}})
        board[st.status][st.profile] = board[st.status].get(st.profile, 0) + st.quantity
    return out


def spec_matches_status(
    specs: Iterable[SpecAnnotation], statuses: Iterable[StatusAnnotation]
) -> bool:
    """True if observed geometry equals desired geometry (reference
    mig.SpecMatchesStatus, pkg/gpu/mig/annotation.go:24)."""
    desired = spec_from_annotations(specs)
    observed: Dict[int, Dict[Profile, int]] = {}
    for st in statuses:
        board = observed.setdefault(st.board_index, {})
        board[st.profile] = board.get(st.profile, 0) + st.quantity
    desired_clean = {
        b: {p: q for p, q in g.items() if q > 0} for b, g in desired.items()
    }
    desired_clean = {b: g for b, g in desired_clean.items() if g}
    observed_clean = {b: g for b, g in observed.items() if g}
    return desired_clean == observed_clean


def strip_partitioning_annotations(annotations: Dict[str, str], prefix: str) -> Dict[str, str]:
    """Remove all spec (or status) partitioning annotations, returning the rest."""
    return {k: v for k, v in annotations.items() if not k.startswith(prefix)}
