"""TPU domain library — the accelerator model the control plane plans over.

Analog of the reference's GPU domain layer (pkg/gpu, pkg/gpu/mig,
pkg/gpu/slicing, pkg/gpu/util — SURVEY §2.4), rebuilt around TPU facts:

- chips live on hosts as a 2D grid wired by ICI (v4/v5p hosts are a 2x2 board
  of a 3D torus; v5e/v6e hosts are a 2x4 grid of a 2D torus);
- *sub-slicing* a host means choosing contiguous rectangular sub-grids — the
  analog of MIG profiles, except legality is geometric (rectangles must tile
  the host grid) rather than a per-model menu
  (reference pkg/gpu/mig/known_configs.go:25-135 hard-codes menus; here
  ``topology.allowed_geometries`` *derives* them);
- *multi-host slices* have fixed legal topologies per generation
  (2x2x1 … 16x16 …) — the table the gang scheduler plans against, with ICI
  adjacency derived from slice shape.
"""
from nos_tpu.tpu.slice import Profile, Geometry, parse_profile, fewest_slices_geometry  # noqa: F401
from nos_tpu.tpu.topology import (  # noqa: F401
    Generation,
    GENERATIONS,
    SliceTopology,
    allowed_geometries,
    host_grid,
    chip_memory_gb,
    slice_topologies,
    find_slice_topology,
)
from nos_tpu.tpu.device import Device, DeviceList  # noqa: F401
from nos_tpu.tpu.host import TpuBoard  # noqa: F401
from nos_tpu.tpu.node import TpuNode  # noqa: F401
from nos_tpu.tpu.resource_calc import ResourceCalculator  # noqa: F401
