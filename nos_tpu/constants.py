"""Well-known resource names, labels, annotations and defaults.

Analog of reference pkg/constant/constants.go:23-115 and
pkg/api/nos.nebuly.com/v1alpha1/{annotations,labels,constants}.go, re-keyed
for TPUs: the partitionable resource is ``google.com/tpu`` (GKE TPU device
plugin) instead of ``nvidia.com/gpu``; MIG-profile resources
(``nvidia.com/mig-1g.10gb``) become TPU sub-slice resources
(``nos.ai/tpu-slice-1x1``); GPU-feature-discovery labels become GKE TPU
node labels (``cloud.google.com/gke-tpu-accelerator`` etc.).
"""
from __future__ import annotations

import re

# ---------------------------------------------------------------------------
# Domain / prefixes
# ---------------------------------------------------------------------------
DOMAIN = "nos.ai"

# ---------------------------------------------------------------------------
# Resource names
# ---------------------------------------------------------------------------
# The whole-chip resource advertised by the (GKE) TPU device plugin.
RESOURCE_TPU = "google.com/tpu"
# Sub-slice resources advertised after dynamic per-host partitioning
# (analog of nvidia.com/mig-1g.10gb; reference pkg/gpu/mig/profile.go:29-100).
# Format: nos.ai/tpu-slice-<X>x<Y> — a sub-slice of a host's chip grid.
RESOURCE_TPU_SLICE_PREFIX = DOMAIN + "/tpu-slice-"
# Derived scalar resource: TPU HBM memory in GB (analog of
# nos.nebuly.com/gpu-memory; reference pkg/api/nos.nebuly.com/v1alpha1/constants.go:25).
RESOURCE_TPU_MEMORY = DOMAIN + "/tpu-memory"
# Kept for mixed-cluster quota accounting (reference counts nvidia.com/gpu
# and MIG resources; we count those *and* TPU chips under one quota system).
RESOURCE_NVIDIA_GPU = "nvidia.com/gpu"
RESOURCE_GPU_MEMORY = DOMAIN + "/gpu-memory"

TPU_SLICE_RESOURCE_REGEX = re.compile(
    r"^" + re.escape(RESOURCE_TPU_SLICE_PREFIX) + r"(\d+)x(\d+)$"
)

# ---------------------------------------------------------------------------
# Node labels (reference: nvidia GFD labels, pkg/constant/constants.go)
# ---------------------------------------------------------------------------
# GKE-standard TPU node labels.
LABEL_TPU_ACCELERATOR = "cloud.google.com/gke-tpu-accelerator"   # e.g. tpu-v5-lite-podslice
LABEL_TPU_TOPOLOGY = "cloud.google.com/gke-tpu-topology"          # e.g. 2x4, 4x4x4
LABEL_NODEPOOL = "cloud.google.com/gke-nodepool"
# explicit host position in the pool's worker order (overrides natural
# name sort when the naming scheme doesn't encode it)
LABEL_TPU_HOST_INDEX = DOMAIN + "/tpu-host-index"
# nos labels (analog of nos.nebuly.com/gpu-partitioning, pkg/gpu/partitioning.go:80-128).
LABEL_PARTITIONING = DOMAIN + "/tpu-partitioning"                  # "subslicing" | "topology"
LABEL_CAPACITY = DOMAIN + "/capacity"                              # in-quota | over-quota
LABEL_DEVICE_PLUGIN_CONFIG = DOMAIN + "/device-plugin.config"

# Partitioning kinds (reference: mig / mps / hybrid).
PARTITIONING_SUBSLICING = "subslicing"   # per-host chip sub-slicing (v5e-style; MPS/MIG analog)
PARTITIONING_TOPOLOGY = "topology"       # multi-host slice placement (gang; no GPU analog)

# ---------------------------------------------------------------------------
# Gang scheduling (multi-host TPU JobSets; no reference analog — SURVEY §2.7)
# ---------------------------------------------------------------------------
# Pods of one multi-host job carry:
#   nos.ai/gang-name:   job identity (JobSet name)
#   nos.ai/gang-size:   total worker count (hosts in the slice)
#   nos.ai/gang-worker: this pod's worker index 0..size-1
# and the annotation:
#   nos.ai/tpu-topology: the slice topology the job's parallelism layout
#                        requires (e.g. "4x4" on v5e, "4x4x4" on v5p)
LABEL_GANG_NAME = DOMAIN + "/gang-name"
LABEL_GANG_SIZE = DOMAIN + "/gang-size"
LABEL_GANG_WORKER = DOMAIN + "/gang-worker"
ANNOTATION_TPU_TOPOLOGY = DOMAIN + "/tpu-topology"
# Multislice (gang-of-gangs): a JobSet spanning N DCN-connected slices.
# Each slice's pods form a normal gang (labels above, gang-name unique per
# slice); the jobset labels tie the N gangs into one co-atomic admission
# unit — no gang binds unless every slice's gang has a feasible, DISTINCT
# ICI domain (dp/fsdp ride DCN between slices; tp/sp/ep/pp never leave a
# slice's ICI — the parallel/layout.py + parallel/mesh.py contract):
#   nos.ai/jobset-name:   the JobSet this gang belongs to
#   nos.ai/jobset-slices: total slice (gang) count N
#   nos.ai/jobset-slice:  this pod's slice index 0..N-1
LABEL_JOBSET_NAME = DOMAIN + "/jobset-name"
LABEL_JOBSET_SLICES = DOMAIN + "/jobset-slices"
LABEL_JOBSET_SLICE = DOMAIN + "/jobset-slice"

CAPACITY_IN_QUOTA = "in-quota"
CAPACITY_OVER_QUOTA = "over-quota"

# ---------------------------------------------------------------------------
# Node annotations — the spec/status wire protocol
# (reference pkg/api/nos.nebuly.com/v1alpha1/annotations.go:20-42)
# ---------------------------------------------------------------------------
# Desired (written by the partitioner control plane):
#   nos.ai/spec-tpu-<hostIndex>-<profile>: "<quantity>"
# Observed (written by the node tpuagent):
#   nos.ai/status-tpu-<hostIndex>-<profile>-<free|used>: "<quantity>"
ANNOTATION_SPEC_PREFIX = DOMAIN + "/spec-tpu-"
ANNOTATION_STATUS_PREFIX = DOMAIN + "/status-tpu-"
ANNOTATION_PARTITIONING_PLAN = DOMAIN + "/spec-partitioning-plan"
ANNOTATION_REPORTED_PARTITIONING_PLAN = DOMAIN + "/status-partitioning-plan"
# failure detection: comma-separated unhealthy chip indexes reported by the
# agent's device-health probe (absent when all chips are healthy)
ANNOTATION_UNHEALTHY_CHIPS = DOMAIN + "/status-unhealthy-chips"
# device-attachment reconciliation (reference pkg/resource/lister.go joined
# with NVML truth): disagreements between the API server's bound-pod view
# and the node's native attachment truth, as "kind:pod-uid" items,
# ";"-separated — "ghost" = device held by a pod the API doesn't show
# bound/running here; "unattached" = Running pod that requested TPU but
# holds no device per the device-plugin allocation table
ANNOTATION_ATTACHMENT_DRIFT = DOMAIN + "/status-attachment-drift"

ANNOTATION_SPEC_REGEX = re.compile(
    r"^" + re.escape(ANNOTATION_SPEC_PREFIX) + r"(\d+)-([a-z0-9.x\-]+)$"
)
ANNOTATION_STATUS_REGEX = re.compile(
    r"^" + re.escape(ANNOTATION_STATUS_PREFIX) + r"(\d+)-([a-z0-9.x\-]+)-(free|used)$"
)

# ---------------------------------------------------------------------------
# Defaults (reference pkg/constant/constants.go + helm values)
# ---------------------------------------------------------------------------
DEFAULT_TPU_MEMORY_GB = 16          # HBM per chip if the generation is unknown
DEFAULT_NVIDIA_GPU_MEMORY_GB = 32   # reference helm-charts/nos/values.yaml:7
DEFAULT_BATCH_WINDOW_TIMEOUT_S = 60.0   # reference values.yaml:276
DEFAULT_BATCH_WINDOW_IDLE_S = 10.0      # reference values.yaml:283
DEFAULT_REPORT_INTERVAL_S = 10.0        # migagent report interval
DEFAULT_DEVICE_PLUGIN_DELAY_S = 5.0     # mps partitioner CM propagation delay
DEFAULT_POD_RESOURCES_TIMEOUT_S = 10.0

# ---------------------------------------------------------------------------
# Node lifecycle (nos_tpu/lifecycle) — the slice-repair control plane
# ---------------------------------------------------------------------------
# Node heartbeats ride coordination Leases named after the node, in the
# kubelet's standard lease namespace (on GKE the kubelet renews these; in
# this stack the tpuagent reporter doubles as the renewer).
NODE_LEASE_NAMESPACE = "kube-node-lease"
# GCE-style upcoming-maintenance notice: value is the window start time as
# wall-clock seconds (time.time — the one cross-host clock domain; see
# lifecycle/events.py). On a real fleet the GCE metadata watcher stamps
# this from computeMetadata/v1/instance/maintenance-event.
ANNOTATION_MAINTENANCE_START = DOMAIN + "/maintenance-window-start"
# Spot/preemptible preemption notice: value is the ACPI-shutdown deadline
# (wall-clock seconds). Pods on the node have until then to bank progress
# — the trainer's SIGTERM checkpoint path keys off this via
# lifecycle.preemption_signal_controller.
ANNOTATION_PREEMPTION_DEADLINE = DOMAIN + "/preemption-deadline"
# Marker the lifecycle controller leaves on nodes IT cordoned, so recovery
# only uncordons nodes the controller itself fenced (an operator's manual
# cordon must survive a node heartbeat coming back).
ANNOTATION_LIFECYCLE_CORDONED = DOMAIN + "/lifecycle-cordoned"
# Restart generation stamped onto pods the slice-repair path recreates
# (observability: how many times has this worker been displaced).
ANNOTATION_LIFECYCLE_RESTARTS = DOMAIN + "/lifecycle-restarts"
# Distributed-tracing context of the pod's journey (W3C traceparent
# syntax), stamped by the scheduler at quota admission and preserved by
# the slice-repair recreate, so scheduler attempt, partitioner
# plan/actuate, tpuagent apply and lifecycle evict->rebind all land in
# ONE trace (nos_tpu/obs/tracing.py).
ANNOTATION_TRACE_CONTEXT = "nos-tpu/trace-context"
# Taints applied when fencing a node (kube's own unreachable taint key for
# lease/heartbeat death; a nos key for impending maintenance).
TAINT_UNREACHABLE = "node.kubernetes.io/unreachable"
TAINT_MAINTENANCE = DOMAIN + "/maintenance"

# ---------------------------------------------------------------------------
# Serving-fleet autoscaler (nos_tpu/fleet/)
# ---------------------------------------------------------------------------
# Replica pods of one autoscaled serving fleet carry nos.ai/fleet=<name>;
# the fleet controller only ever creates, drains and deletes pods bearing
# its own fleet label.
LABEL_FLEET = DOMAIN + "/fleet"
# Stamped by the fleet controller when a replica is selected for graceful
# scale-down: the replica stops admitting (readiness flips), in-flight
# requests finish (or the drain budget expires), then the pod is deleted.
ANNOTATION_FLEET_DRAIN = DOMAIN + "/fleet-drain"
# Scale-from-zero activation signal (nos_tpu/gateway/): the gateway
# stamps its door-queue depth onto the ``nos-tpu-gateway-<fleet>``
# ConfigMap under this annotation whenever the depth changes (including
# back to zero). The fleet controller reads it as queued-at-door
# pressure — the signal that wakes a min_replicas=0 fleet — when no
# richer gateway_source (the gateway's /stats over HTTP) is wired.
ANNOTATION_GATEWAY_QUEUED = DOMAIN + "/gateway-queued"

# ---------------------------------------------------------------------------
# Diurnal chip harvesting (nos_tpu/harvest/) — one pool, two planes
# ---------------------------------------------------------------------------
# Preemptible training gangs launched by the harvest controller carry
# nos.ai/harvest=<name>; the controller only ever creates, reclaims and
# relaunches pods bearing its own label.
LABEL_HARVEST = DOMAIN + "/harvest"
# Gang-level quota-reclaim notice (the pod analog of
# ANNOTATION_PREEMPTION_DEADLINE on nodes): when the capacity scheduler
# selects an over-quota GANG as a preemption victim and a reclaim grace
# window is configured, it stamps this annotation (value = wall-clock
# deadline seconds) on every member instead of deleting them outright.
# A notice-aware controller (the harvester) uses the window to run
# checkpoint -> fence -> gang-evict; at expiry the scheduler deletes the
# gang anyway — the blunt fallback when nobody intercepts the notice.
ANNOTATION_RECLAIM_NOTICE = DOMAIN + "/reclaim-notice-deadline"
# The harvester's reclaim-protocol state, stamped on every gang member
# as one JSON object ({"id","phase","deadline","step"}) so a controller
# restart mid-reclaim re-enters idempotently from the API server's
# durable record — never a double-evict, never an orphaned fence.
ANNOTATION_HARVEST_RECLAIM = DOMAIN + "/harvest-reclaim"
# Stamped onto the Pending pods the gang-evict recreates: the durable
# checkpoint step a witnessed resume must restart from.
ANNOTATION_HARVEST_RESUME_STEP = DOMAIN + "/harvest-resume-step"
# Scheduling gate (the kube schedulingGates analog): the nos scheduler
# skips Pending pods carrying this annotation entirely. The harvester
# parks evicted gangs under it so they cannot race the serving fleet
# for the chips their own eviction just freed; stripping it is the
# relaunch decision.
ANNOTATION_SCHEDULING_HOLD = DOMAIN + "/scheduling-hold"

# Scheduler / controller names
SCHEDULER_NAME = "nos-scheduler"
DEVICE_PLUGIN_CONFIGMAP = "nos-device-plugin-config"
DEVICE_PLUGIN_NAMESPACE = "kube-system"

# Field-index keys (reference pkg/constant: pod spec.nodeName / status.phase indexes)
INDEX_POD_PHASE = "status.phase"
INDEX_POD_NODE = "spec.nodeName"
