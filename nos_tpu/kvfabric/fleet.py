"""The gateway's fleet-wide prefix index (jax-free).

A union view over every replica's ``/stats`` ``prefix_index`` section:
which chain digests live where (HBM or host tier, at what length), so
the router can turn a prefix miss on the affinity-routed replica into
ONE peer-pull fetch (``GET /v1/kvchain/<digest>``) instead of a full
re-prefill.

Freshness discipline: ``sync`` replaces each replica's entries
WHOLESALE from its latest scrape, and replicas absent from the scrape
set — departed pods, or pods whose ``/stats`` stopped answering —
drop out entirely. A stale entry here costs a wasted fetch against a
dead pod on the latency path, so the index only ever reflects the
most recent successful scrape, exactly like the router's replica set
itself.

Digests embed the tenant scope (``codec.chain_digest``), so the index
needs no scope column to stay isolation-correct: a lookup for one
scope's digest can only ever name chains published under that scope.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

__all__ = ["FleetPrefixIndex"]


class FleetPrefixIndex:
    """name -> digest -> chain row, rebuilt per discovery poll."""

    def __init__(self) -> None:
        self._replicas: Dict[str, Dict[str, dict]] = {}

    def sync(self, sections: Dict[str, Optional[dict]]) -> None:
        """Adopt the latest scrape: ``sections`` maps every CURRENTLY
        scraped replica name to its ``prefix_index`` /stats section
        (None when the replica did not report one). Names absent from
        ``sections`` age out — no tombstones, no TTLs."""
        fresh: Dict[str, Dict[str, dict]] = {}
        for name, sec in sections.items():
            rows = (sec or {}).get("chains") or []
            by_digest: Dict[str, dict] = {}
            for row in rows:
                digest = row.get("digest")
                if digest and int(row.get("len") or 0) > 0:
                    by_digest[digest] = row
            if by_digest:
                fresh[name] = by_digest
        self._replicas = fresh

    def holders(self, digest: str,
                exclude: Optional[str] = None) -> List[Tuple[str, dict]]:
        """Replicas holding ``digest`` as (name, row), the routed
        replica excluded (pulling a chain from the replica about to
        serve the request is a no-op by definition)."""
        out = []
        for name, rows in self._replicas.items():
            if name == exclude:
                continue
            row = rows.get(digest)
            if row is not None:
                out.append((name, row))
        return out

    def replica_len(self, name: str, digest: str) -> int:
        """Token length of ``digest``'s chain on ``name`` (0 = not
        held) — how the router compares a peer's chain against the
        routed replica's own warmth before offering a pull."""
        row = self._replicas.get(name, {}).get(digest)
        return int(row.get("len") or 0) if row is not None else 0

    def stats(self) -> dict:
        return {"replicas": len(self._replicas),
                "chains": sum(len(r) for r in self._replicas.values())}
