"""Fleet-wide KV fabric (ISSUE 17): tiered prefix caching across the
serving fleet instead of per-replica HBM islands.

Three pieces, deliberately jax-free (the gateway imports this plane and
must never pay a jax import, and the host tier is pure numpy-bytes
bookkeeping):

- ``codec``    — the chain wire format: a prefix chain's identity
  (``chain_digest`` over its scope + token content, the same blake2b-16
  arithmetic as the gateway's affinity ``prefix_key``) and its payload
  (``encode_chain``/``decode_chain`` wrapping the ``models/handoff.py``
  swap codec PR 15 proved adopts byte-exactly across hosts);
- ``hosttier`` — ``HostTierStore``: the bounded host-RAM tier under a
  replica's HBM arena. Prefix-chain eviction under block pressure
  DEMOTES the LRU chain's quantized bytes + scale planes here instead
  of dropping them; a later prefix miss that hits the store PROMOTES
  the chain back via the engine's batched adopt-by-scatter, bit-exact;
- ``fleet``    — ``FleetPrefixIndex``: the gateway's union of every
  replica's ``/stats`` ``prefix_index`` section (chain digests +
  lengths + tier), so a miss on the affinity-routed replica can pull
  the chain from a peer replica (one HTTP fetch of the codec payload)
  instead of re-prefilling.

Tenant scoping is preserved end to end: chains stay keyed
``(scope, tokens)`` per the ISSUE 13 side-channel rule, the digest
itself embeds the scope (two tenants' identical prompts can never
collide), and the ingest path re-derives the requester's scope before
any pulled chain enters a cache — cross-replica migration never
crosses tenant scopes.

The fabric's HTTP surfaces are fleet-internal: a replica only honors a
``kv_sources`` offer and only serves ``GET /v1/kvchain/<digest>`` when
the request carries the fleet's shared ``--kv-fabric-token`` secret in
``FABRIC_TOKEN_HEADER`` — the gateway strips client-supplied offers at
the door and stamps the token on its own.
"""
from nos_tpu.kvfabric.codec import (
    FABRIC_TOKEN_HEADER, chain_digest, chain_nbytes, decode_chain,
    encode_chain,
)
from nos_tpu.kvfabric.fleet import FleetPrefixIndex
from nos_tpu.kvfabric.hosttier import HostTierStore

__all__ = [
    "FABRIC_TOKEN_HEADER", "FleetPrefixIndex", "HostTierStore",
    "chain_digest", "chain_nbytes", "decode_chain", "encode_chain",
]
