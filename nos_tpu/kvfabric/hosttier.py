"""The host-RAM KV tier (jax-free): a bounded, LRU byte store for
demoted prefix chains.

Sits UNDER a replica's HBM arena in the demotion ladder
(HBM → host → drop): when block pressure evicts an LRU prefix chain,
the engine's eviction hook offers the chain's swap payload here
instead of dropping it; a later prefix miss that matches a stored
chain promotes it back into the arena via the batched restore
scatter, bit-exact (the bytes never changed). The store also backs
the ``GET /v1/kvchain/<digest>`` peer-pull endpoint, so a chain
demoted on one replica can still warm a peer.

Capacity is charged in PAYLOAD bytes (``chain_nbytes`` — KV planes +
scale planes), bounded by ``capacity_bytes``; inserting past the
bound evicts oldest-first, and a single chain larger than the whole
store is rejected outright (it could never be admitted). Thread-safe:
the serving loop demotes/promotes under its own lock while HTTP
handler threads serve peer pulls concurrently.

Scoping: entries are keyed ``(scope, tokens)`` exactly like
``PrefixBlockIndex`` chains, and ``match`` is scope-filtered — a
tenant's demoted chain is invisible to every other scope's misses,
the same side-channel rule the HBM index enforces (ISSUE 13).
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

from nos_tpu.kvfabric.codec import chain_digest, chain_nbytes

__all__ = ["HostTierStore"]


class HostTierStore:
    """Bounded host-RAM LRU of demoted prefix chains, keyed
    ``(scope, token tuple)``; every entry carries its payload bytes
    count and fleet-wide digest (computed once at insert)."""

    def __init__(self, capacity_bytes: int):
        if capacity_bytes < 1:
            raise ValueError(
                f"host-tier capacity_bytes must be >= 1, got "
                f"{capacity_bytes}")
        self.capacity_bytes = int(capacity_bytes)
        # insertion-ordered LRU: (scope, tokens) -> entry dict
        self._chains: Dict[tuple, dict] = {}
        self._bytes = 0
        self._lock = threading.Lock()
        self.counts = {"demoted": 0, "evicted": 0, "rejected": 0}

    # -- write side ----------------------------------------------------
    def put(self, scope: Optional[str], tokens: Sequence[int],
            swap: dict) -> bool:
        """Store one demoted chain; True iff it was admitted (False =
        larger than the whole store — the eviction that offered it
        falls through to a plain drop). Re-demoting a key that is
        already stored refreshes its LRU position without copying."""
        key = (scope, tuple(int(t) for t in tokens))
        nbytes = chain_nbytes(swap)
        with self._lock:
            if nbytes > self.capacity_bytes:
                self.counts["rejected"] += 1
                return False
            if key in self._chains:
                ent = self._chains.pop(key)     # pop-then-set: LRU refresh
                self._chains[key] = ent
                return True
            while self._chains and self._bytes + nbytes > self.capacity_bytes:
                self._evict_one_locked()
            self._chains[key] = {
                "swap": dict(swap),
                "nbytes": nbytes,
                "digest": chain_digest(key[1], scope),
            }
            self._bytes += nbytes
            self.counts["demoted"] += 1
            return True

    def _evict_one_locked(self) -> None:
        key = next(iter(self._chains))
        ent = self._chains.pop(key)
        self._bytes -= ent["nbytes"]
        self.counts["evicted"] += 1

    # -- read side -----------------------------------------------------
    def match(self, scope: Optional[str], prompt: Sequence[int],
              cap: int) -> Optional[tuple]:
        """Key of the LONGEST stored chain in ``scope`` whose tokens
        are a prefix of ``prompt`` with length <= ``cap`` (the caller
        passes its block-aligned usable bound), or None. Linear scan:
        the store holds at most a handful of system-prompt chains —
        same reasoning as ``PrefixBlockIndex.match``."""
        head = tuple(int(t) for t in prompt[:max(0, cap)])
        best: Optional[tuple] = None
        with self._lock:
            for key in self._chains:
                kscope, toks = key
                if kscope != scope:
                    continue        # another tenant's chain: invisible
                n = len(toks)
                if n > len(head) or (best is not None
                                     and n <= len(best[1])):
                    continue
                if head[:n] == toks:
                    best = key
        return best

    def get(self, key: tuple) -> Optional[dict]:
        """The entry for ``key`` (LRU refresh), or None."""
        with self._lock:
            ent = self._chains.pop(key, None)
            if ent is None:
                return None
            self._chains[key] = ent
            return ent

    def pop(self, key: tuple) -> Optional[dict]:
        """Remove and return ``key``'s entry (promotion back to HBM —
        the chain lives in exactly one tier at a time)."""
        with self._lock:
            ent = self._chains.pop(key, None)
            if ent is not None:
                self._bytes -= ent["nbytes"]
            return ent

    def find(self, digest: str) -> Optional[Tuple[tuple, dict]]:
        """(key, entry) for the chain named ``digest`` (the peer-pull
        endpoint's lookup), or None."""
        with self._lock:
            for key, ent in self._chains.items():
                if ent["digest"] == digest:
                    return key, ent
        return None

    def clear(self) -> None:
        with self._lock:
            self._chains.clear()
            self._bytes = 0

    # -- introspection -------------------------------------------------
    @property
    def nbytes(self) -> int:
        return self._bytes

    def __len__(self) -> int:
        return len(self._chains)

    def digests(self) -> List[dict]:
        """Per-chain rows for the ``/stats`` ``prefix_index`` section
        (digest + length + bytes + scope; the caller tags the tier)."""
        with self._lock:
            return [{"digest": ent["digest"], "len": len(key[1]),
                     "nbytes": ent["nbytes"], "scope": key[0]}
                    for key, ent in self._chains.items()]

    def stats(self) -> dict:
        with self._lock:
            return {"chains": len(self._chains),
                    "bytes": self._bytes,
                    "capacity_bytes": self.capacity_bytes,
                    **self.counts}
