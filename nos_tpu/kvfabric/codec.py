"""KV-fabric chain identity and wire format (jax-free).

A fabric *chain* is one prefix-cache entry in transit: the scope +
token content that key it (``PrefixBlockIndex`` chains are keyed
``(scope, tokens)``) plus the swap payload of its KV blocks — the
``_swap_payload`` schema (``nblk`` + k/v planes, per-block scale
planes under int8) that preemption, supervised restart and the
prefill→decode handoff already move byte-exactly. Reusing the
``models/handoff.py`` codec verbatim means the fabric inherits its
proven properties: deterministic bytes for a deterministic chain, and
bit-exact adoption through the engine's batched restore scatter.

``chain_digest`` is the chain's fleet-wide name: blake2b-16 over the
scope and the token content, mirroring the gateway's affinity
``prefix_key`` arithmetic (``scope + \\x00 + comma-joined tokens``).
The scope is INSIDE the hash on purpose — two tenants publishing the
same system prompt get different digests, so no lookup table anywhere
in the fleet can alias one tenant's chain to another's, even before
the ingest path's explicit scope check.
"""
from __future__ import annotations

import hashlib
from typing import Dict, Optional, Sequence

import numpy as np

from nos_tpu.models.handoff import (
    decode_handoff, encode_handoff, handoff_nbytes,
)

__all__ = ["FABRIC_TOKEN_HEADER", "chain_digest", "chain_nbytes",
           "decode_chain", "encode_chain"]

# The fleet-internal trust marker for the fabric's HTTP surfaces: the
# gateway stamps it on dispatches carrying ``kv_sources`` offers, and
# replicas require it both to HONOR an offer (kv_sources steers the
# replica's outbound fetcher and seeds its prefix cache — a client-
# supplied offer would be blind SSRF plus cache poisoning) and to
# SERVE ``GET /v1/kvchain/<digest>`` (digests are public arithmetic
# over scope + tokens, so an open export would hand any client another
# tenant's KV bytes and a cache-residency oracle). The value is the
# shared ``--kv-fabric-token`` secret.
FABRIC_TOKEN_HEADER = "X-NOS-KV-Fabric-Token"


def chain_digest(tokens: Sequence[int], scope: Optional[str] = None) -> str:
    """The chain's fleet-wide identity: blake2b-16 over scope + token
    content — the same construction as ``ring.prefix_key`` so the two
    surfaces cannot drift, but over the FULL chain (a digest names one
    exact chain, not an affinity bucket)."""
    toks = b",".join(str(int(t)).encode() for t in tokens)
    if scope is not None:
        toks = scope.encode() + b"\x00" + toks
    return hashlib.blake2b(toks, digest_size=16).hexdigest()


def chain_nbytes(swap: Dict[str, np.ndarray]) -> int:
    """Structural size of one chain payload: the swap arrays' bytes
    (KV planes + int8 scale planes), independent of wire framing —
    the unit ``HostTierStore``'s capacity bound is charged in."""
    return handoff_nbytes({"swap": swap})


def encode_chain(scope: Optional[str], tokens: Sequence[int],
                 swap: Dict[str, np.ndarray]) -> bytes:
    """Serialize one chain for the host tier's disk-shape or the
    ``GET /v1/kvchain/<digest>`` peer-pull hop. Deterministic bytes
    (uncompressed ``np.savez``, sorted meta) — the bench pins
    byte-identical reruns on this."""
    return encode_handoff({
        "fabric": 1,
        "scope": scope,
        "tokens": [int(t) for t in tokens],
        "swap": dict(swap),
    })


def decode_chain(data: bytes) -> dict:
    """Inverse of ``encode_chain``. Raises ``ValueError`` on anything
    that is not a fabric chain payload (a handoff state, junk bytes) —
    the ingest path treats that as a rejected pull, never a crash."""
    try:
        state = decode_handoff(data)
    except Exception as exc:
        raise ValueError(f"not a KV-fabric chain payload: {exc}") from exc
    if state.get("fabric") != 1 or "swap" not in state \
            or not isinstance(state.get("tokens"), list):
        raise ValueError("not a KV-fabric chain payload")
    return state
