"""Zero-dependency distributed tracing for the nos-tpu control plane.

Dapper-style (Sigelman et al., 2010) tracing modeled on OpenTelemetry
semantics, but with no external dependency and a cost profile cheap
enough to leave on in production: one trace per *pod journey*, spans for
each control-plane phase the pod passes through (quota admission,
scheduler attempt, gang/JobSet domain search, partitioner plan+actuate,
tpuagent apply, lifecycle eviction -> rebind), and a bounded in-memory
flight recorder of recently completed traces served at ``/debug/traces``
next to ``/metrics`` (nos_tpu/cmd/serve.py).

Cross-process propagation rides a pod annotation
(``nos-tpu/trace-context``, W3C ``traceparent`` syntax) stamped at quota
admission by the scheduler — the first component to touch a pending pod.
Every later component (partitioner, tpuagent, lifecycle) parents its
spans on the annotation's context, and the lifecycle controller's
evict-and-recreate preserves annotations, so a chaos rebind lands in the
SAME trace as the original placement.

Design constraints honored here:

- **hot-path cost**: an unsampled/disabled span is a shared no-op
  singleton (no allocation); a sampled span is one small object + two
  clock reads. No locks on the span itself — a span is owned by one
  attempt.
- **bounded memory**: the flight recorder is a ring of traces
  (``max_traces``) with per-trace span caps; slow/error traces are
  *pinned* so the interesting evidence survives a busy ring (bounded
  pinned set, FIFO demotion).
- **deterministic clocks**: every span accepts explicit
  ``start_time``/``end_time`` so the lifecycle controller and chaos
  harness can stamp simulated-clock instants; the tracer's own clock is
  swappable (``set_clock``) for whole-process simulated time.
"""
from __future__ import annotations

import os
import random
import threading
from collections import OrderedDict
from contextvars import ContextVar
from dataclasses import dataclass
from functools import wraps
from time import time as _wall
from typing import Any, Callable, Dict, List, Optional

__all__ = [
    "Span",
    "SpanContext",
    "Tracer",
    "FlightRecorder",
    "tracer",
    "recorder",
    "configure",
    "set_clock",
    "span",
    "start_span",
    "current",
    "traced",
    "pod_trace_context",
    "stamp_trace_context",
]

# dedicated RNG: trace/span ids must not perturb (or be perturbed by)
# seeded simulation RNGs like the chaos harness's random.Random(seed)
_ids = random.Random()

_W3C_VERSION = "00"
_W3C_FLAGS = "01"


def _new_trace_id() -> str:
    return f"{_ids.getrandbits(128):032x}"


def _new_span_id() -> str:
    return f"{_ids.getrandbits(64):016x}"


@dataclass(frozen=True)
class SpanContext:
    """The portable identity of a span: what crosses process boundaries."""

    trace_id: str
    span_id: str

    def encode(self) -> str:
        """W3C ``traceparent`` syntax: ``00-<trace>-<span>-01``."""
        return f"{_W3C_VERSION}-{self.trace_id}-{self.span_id}-{_W3C_FLAGS}"

    @staticmethod
    def decode(value: Optional[str]) -> Optional["SpanContext"]:
        """Tolerant parse — ``None`` on anything malformed (a bad
        annotation must never break scheduling)."""
        if not value:
            return None
        parts = value.split("-")
        if len(parts) != 4:
            return None
        _, trace_id, span_id, _ = parts
        if len(trace_id) != 32 or len(span_id) != 16:
            return None
        try:
            int(trace_id, 16)
            int(span_id, 16)
        except ValueError:
            return None
        return SpanContext(trace_id=trace_id, span_id=span_id)


class Span:
    """One timed operation. Not thread-safe by design — a span belongs to
    the single attempt that created it."""

    __slots__ = ("name", "component", "trace_id", "span_id", "parent_id",
                 "start", "end_time", "attrs", "events", "status",
                 "status_message", "_tracer")

    def __init__(self, name: str, component: str, trace_id: str,
                 span_id: str, parent_id: Optional[str], start: float,
                 attrs: Optional[Dict[str, Any]] = None,
                 _tracer: Optional["Tracer"] = None):
        self.name = name
        self.component = component
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start = start
        self.end_time: Optional[float] = None
        # callers pass a fresh literal dict (or None); adopting it
        # avoids one dict copy per span on the hot path
        self.attrs: Dict[str, Any] = attrs if attrs is not None else {}
        # lazily allocated: most spans carry no events
        self.events: Optional[List[tuple]] = None
        self.status = "ok"
        self.status_message = ""
        self._tracer = _tracer

    # -- recording ------------------------------------------------------
    @property
    def recording(self) -> bool:
        return True

    @property
    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def set_attr(self, key: str, value: Any) -> "Span":
        self.attrs[key] = value
        return self

    def add_event(self, name: str, ts: Optional[float] = None,
                  **attrs: Any) -> "Span":
        if ts is None:
            ts = self._tracer.clock() if self._tracer else _wall()
        if self.events is None:
            self.events = []
        self.events.append((ts, name, attrs))
        return self

    def set_error(self, message: str = "") -> "Span":
        self.status = "error"
        self.status_message = message
        return self

    def end(self, end_time: Optional[float] = None) -> None:
        """Idempotent: the first end wins (the lifecycle controller and
        the chaos harness may both try to close an episode root)."""
        if self.end_time is not None:
            return
        self.end_time = (end_time if end_time is not None
                         else (self._tracer.clock() if self._tracer
                               else _wall()))
        if self._tracer is not None:
            self._tracer._on_end(self)

    @property
    def duration(self) -> Optional[float]:
        if self.end_time is None:
            return None
        return self.end_time - self.start

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "component": self.component,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start": self.start,
            "end": self.end_time,
            "duration_s": self.duration,
            "status": self.status,
            "status_message": self.status_message,
            "attrs": self.attrs,
            "events": [
                {"ts": ts, "name": n, "attrs": a}
                for ts, n, a in (self.events or ())
            ],
        }

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"Span({self.name!r} component={self.component} "
                f"trace={self.trace_id[:8]} span={self.span_id[:8]})")


class _NoopSpan:
    """Shared do-nothing span for unsampled/disabled tracing. All methods
    are no-ops; ``context`` is None so propagation never stamps ids that
    lead nowhere."""

    __slots__ = ()

    recording = False
    context = None
    trace_id = ""
    span_id = ""
    parent_id = None
    duration = None
    status = "ok"

    def set_attr(self, key, value):
        return self

    def add_event(self, name, ts=None, **attrs):
        return self

    def set_error(self, message=""):
        return self

    def end(self, end_time=None):
        pass


NOOP_SPAN = _NoopSpan()

# process-wide "current span" (contextvars: correct across threads and
# any future async use; ~100ns per get/set)
_current: ContextVar[Optional[Span]] = ContextVar("nos_tpu_span",
                                                 default=None)


class _SpanScope:
    """``with tracer.span(...) as sp`` — sets the context-local current
    span on enter (the noop sentinel included: children of an unsampled
    root must inherit the not-sampled decision rather than re-rolling
    sampling as fresh roots), marks error status on exception, ends the
    span on exit."""

    __slots__ = ("span", "_token")

    def __init__(self, span):
        self.span = span

    def __enter__(self):
        self._token = _current.set(self.span)
        return self.span

    def __exit__(self, exc_type, exc, tb):
        _current.reset(self._token)
        if exc is not None:
            self.span.set_error(f"{exc_type.__name__}: {exc}")
        self.span.end()
        return False


class FlightRecorder:
    """Bounded in-memory ring of recently *completed* spans, grouped by
    trace. Slow and error traces are pinned so they survive ring churn;
    the pinned set is itself bounded (oldest pinned demotes back to the
    ring). Served as JSON at ``/debug/traces``."""

    def __init__(self, max_traces: int = 256,
                 max_spans_per_trace: int = 512,
                 slow_threshold_s: float = 1.0,
                 max_pinned: int = 64):
        self.max_traces = max_traces
        self.max_spans_per_trace = max_spans_per_trace
        self.slow_threshold_s = slow_threshold_s
        self.max_pinned = max_pinned
        self._lock = threading.Lock()
        # trace_id -> list[Span]; OrderedDict recency = last span end
        self._traces: "OrderedDict[str, List[Span]]" = OrderedDict()
        self._pinned: "OrderedDict[str, str]" = OrderedDict()  # id -> why
        self._dropped_spans = 0
        self._evicted_traces = 0

    # -- ingest ---------------------------------------------------------
    def record(self, sp: Span) -> None:
        # hot path: called once per completed span; the common case is
        # one lock, two dict ops and a float compare
        with self._lock:
            traces = self._traces
            spans = traces.get(sp.trace_id)
            new = spans is None
            if new:
                traces[sp.trace_id] = [sp]
            else:
                traces.move_to_end(sp.trace_id)
                if len(spans) >= self.max_spans_per_trace:
                    self._dropped_spans += 1
                else:
                    spans.append(sp)
            # pin BEFORE evicting: a slow/error span must protect its
            # own trace even when it is the one that filled the ring
            if sp.status == "error":
                self._pin(sp.trace_id, "error")
            elif sp.end_time - sp.start >= self.slow_threshold_s:
                self._pin(sp.trace_id, "slow")
            if new:
                while len(traces) > self.max_traces:
                    self._evict_one()

    def pin(self, trace_id: str, why: str = "manual") -> None:
        """Public pin: protect ``trace_id`` from ring churn for reasons
        the recorder cannot infer from span timing alone — the serving
        loop pins SLO-breaching request traces so the evidence behind a
        breached ``nos_tpu_serve_slo_total`` increment survives to be
        read at ``/debug/traces``. Subject to the same bounded-pinned-set
        FIFO demotion as slow/error pins."""
        with self._lock:
            self._pin(trace_id, why)

    def _pin(self, trace_id: str, why: str) -> None:
        if trace_id in self._pinned:
            self._pinned.move_to_end(trace_id)
            return
        self._pinned[trace_id] = why
        while len(self._pinned) > self.max_pinned:
            self._pinned.popitem(last=False)   # demote oldest pin

    def _evict_one(self) -> None:
        for tid in self._traces:
            if tid not in self._pinned:
                del self._traces[tid]
                self._evicted_traces += 1
                return
        # everything is pinned: demote the oldest pin
        tid, _ = self._pinned.popitem(last=False)
        self._traces.pop(tid, None)
        self._evicted_traces += 1

    # -- read -----------------------------------------------------------
    def trace(self, trace_id: str) -> List[Span]:
        with self._lock:
            return list(self._traces.get(trace_id, ()))

    def trace_ids(self) -> List[str]:
        with self._lock:
            return list(self._traces)

    def spans(self) -> List[Span]:
        with self._lock:
            return [sp for spans in self._traces.values() for sp in spans]

    def pinned(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._pinned)

    def to_json(self) -> Dict[str, Any]:
        with self._lock:
            traces = [
                {
                    "trace_id": tid,
                    "pinned": self._pinned.get(tid),
                    "components": sorted({sp.component for sp in spans}),
                    "spans": [sp.to_dict() for sp in spans],
                }
                for tid, spans in self._traces.items()
            ]
            return {
                "traces": traces,
                "trace_count": len(traces),
                "dropped_spans": self._dropped_spans,
                "evicted_traces": self._evicted_traces,
                "max_traces": self.max_traces,
                "slow_threshold_s": self.slow_threshold_s,
            }

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()
            self._pinned.clear()
            self._dropped_spans = 0
            self._evicted_traces = 0


class Tracer:
    """Creates spans, applies head sampling at trace roots, and feeds
    completed spans to the flight recorder."""

    def __init__(self, recorder: Optional[FlightRecorder] = None,
                 sampling: float = 1.0, enabled: bool = True,
                 clock: Callable[[], float] = _wall):
        self.recorder = recorder
        self.sampling = sampling
        self.enabled = enabled
        self.clock = clock
        self._sampler = random.Random()

    # -- span factory ---------------------------------------------------
    def start_span(self, name: str, component: str = "nos-tpu",
                   parent: Optional[object] = None,
                   attrs: Optional[Dict[str, Any]] = None,
                   start_time: Optional[float] = None):
        """``parent`` may be a Span, a SpanContext, an encoded
        traceparent string, or None (a new root, subject to sampling).
        Falls back to the context-local current span when None."""
        if not self.enabled:
            return NOOP_SPAN
        if parent is None:
            # hot path: inherit the context-local current span
            parent = _current.get()
            if parent is None:
                # head sampling: decided once, at the trace root
                if self.sampling < 1.0 \
                        and self._sampler.random() >= self.sampling:
                    return NOOP_SPAN
                trace_id, parent_id = _new_trace_id(), None
            elif parent.__class__ is Span:
                trace_id, parent_id = parent.trace_id, parent.span_id
            else:       # noop sentinel: inherit the not-sampled decision
                return NOOP_SPAN
        else:
            if isinstance(parent, _NoopSpan):
                return NOOP_SPAN
            if isinstance(parent, str):
                parent = SpanContext.decode(parent)
                if parent is None:
                    trace_id, parent_id = _new_trace_id(), None
                else:
                    trace_id, parent_id = parent.trace_id, parent.span_id
            else:   # Span or SpanContext
                trace_id, parent_id = parent.trace_id, parent.span_id
        return Span(
            name=name, component=component, trace_id=trace_id,
            span_id=_new_span_id(), parent_id=parent_id,
            start=start_time if start_time is not None else self.clock(),
            attrs=attrs, _tracer=self,
        )

    def span(self, name: str, component: str = "nos-tpu",
             parent: Optional[object] = None,
             attrs: Optional[Dict[str, Any]] = None) -> "_SpanScope":
        """Context manager: hand-rolled (not @contextmanager) — this is
        the hot-path entry and a generator-based CM costs ~3x more per
        use than a __slots__ object."""
        return _SpanScope(
            self.start_span(name, component, parent=parent, attrs=attrs))

    def current(self) -> Optional[Span]:
        sp = _current.get()
        return sp if isinstance(sp, Span) else None

    def _on_end(self, sp: Span) -> None:
        if self.recorder is not None:
            self.recorder.record(sp)
        _metrics_on_span_end(sp)

    def set_clock(self, clock: Callable[[], float]) -> None:
        """Swap the time source (the chaos harness points this at its
        simulated clock so every span in the episode shares one
        timeline). Spans created before the swap keep their stamps."""
        self.clock = clock


# ---------------------------------------------------------------------------
# Module-level default tracer + convenience API
# ---------------------------------------------------------------------------

def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


_default_recorder = FlightRecorder(
    max_traces=int(_env_float("NOS_TPU_TRACE_RECORDER_SIZE", 256)),
    slow_threshold_s=_env_float("NOS_TPU_TRACE_SLOW_THRESHOLD_S", 1.0),
)
_default_tracer = Tracer(
    recorder=_default_recorder,
    sampling=_env_float("NOS_TPU_TRACE_SAMPLING", 1.0),
    enabled=os.environ.get("NOS_TPU_TRACING", "1") not in ("0", "false"),
)


def tracer() -> Tracer:
    return _default_tracer


def recorder() -> FlightRecorder:
    return _default_recorder


def configure(sampling: Optional[float] = None,
              enabled: Optional[bool] = None,
              recorder_size: Optional[int] = None,
              slow_threshold_s: Optional[float] = None) -> Tracer:
    """Apply cmd-line/Helm observability settings to the default tracer
    (nos_tpu/cmd/serve.py flags; helm values ``observability.tracing``)."""
    if sampling is not None:
        _default_tracer.sampling = max(0.0, min(1.0, sampling))
    if enabled is not None:
        _default_tracer.enabled = enabled
    if recorder_size is not None:
        _default_recorder.max_traces = max(1, int(recorder_size))
    if slow_threshold_s is not None:
        _default_recorder.slow_threshold_s = slow_threshold_s
    return _default_tracer


def set_clock(clock: Optional[Callable[[], float]]) -> None:
    _default_tracer.set_clock(clock if clock is not None else _wall)


def span(name: str, component: str = "nos-tpu",
         parent: Optional[object] = None,
         attrs: Optional[Dict[str, Any]] = None):
    return _default_tracer.span(name, component, parent=parent, attrs=attrs)


def start_span(name: str, component: str = "nos-tpu",
               parent: Optional[object] = None,
               attrs: Optional[Dict[str, Any]] = None,
               start_time: Optional[float] = None):
    return _default_tracer.start_span(name, component, parent=parent,
                                      attrs=attrs, start_time=start_time)


def current() -> Optional[Span]:
    return _default_tracer.current()


def traced(name: Optional[str] = None, component: str = "nos-tpu"):
    """Decorator form: the wrapped callable runs inside a span named
    after it (or ``name``), parented on the context-local current span."""

    def deco(fn):
        span_name = name or fn.__qualname__

        @wraps(fn)
        def wrapper(*args, **kwargs):
            with _default_tracer.span(span_name, component):
                return fn(*args, **kwargs)

        return wrapper

    return deco


# ---------------------------------------------------------------------------
# Pod-annotation propagation (the cross-process half)
# ---------------------------------------------------------------------------

def pod_trace_context(pod) -> Optional[SpanContext]:
    """The pod-journey trace context stamped at quota admission, or None.
    Accepts any object with ``metadata.annotations``."""
    from nos_tpu import constants

    return SpanContext.decode(
        pod.metadata.annotations.get(constants.ANNOTATION_TRACE_CONTEXT))


def stamp_trace_context(pod, ctx: SpanContext) -> None:
    """Write the journey context onto the pod (in-memory mutation — the
    caller folds this into whatever API patch it is already making, so
    propagation costs zero extra writes)."""
    from nos_tpu import constants

    if ctx is not None:
        pod.metadata.annotations.setdefault(
            constants.ANNOTATION_TRACE_CONTEXT, ctx.encode())


# ---------------------------------------------------------------------------
# Self-metrics (lazy: observability.py registers on the default registry)
# ---------------------------------------------------------------------------

# per-component counter children cached flat: Counter.labels() walks a
# lock + dict per call, which is measurable at one inc per span
_span_counter_children: Dict[str, Any] = {}


def _metrics_on_span_end(sp: Span) -> None:
    child = _span_counter_children.get(sp.component)
    if child is None:
        from nos_tpu import observability as _obs

        child = _obs.TRACE_SPANS.labels(sp.component)
        _span_counter_children[sp.component] = child
    child.inc()
