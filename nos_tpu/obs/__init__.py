"""nos_tpu.obs — distributed tracing & observability plumbing.

- ``tracing``: zero-dependency spans, cross-process pod-annotation
  propagation, and the bounded flight recorder behind ``/debug/traces``.
- ``trace_export``: Perfetto / Chrome trace-event JSON export for the
  benches (``bench_logs/*.trace.json``).

Domain *metrics* stay in ``nos_tpu/observability.py`` (the histogram /
counter registry every ``/metrics`` endpoint serves); this package is
the trace half of the observability story, with OpenMetrics exemplars
(utils/metrics.py) linking the two.
"""
from nos_tpu.obs import tracing  # noqa: F401
from nos_tpu.obs.tracing import (  # noqa: F401
    FlightRecorder,
    Span,
    SpanContext,
    Tracer,
    configure,
    current,
    pod_trace_context,
    recorder,
    span,
    stamp_trace_context,
    start_span,
    traced,
    tracer,
)
