"""nos_tpu.obs — distributed tracing & observability plumbing.

- ``tracing``: zero-dependency spans, cross-process pod-annotation
  propagation, and the bounded flight recorder behind ``/debug/traces``.
- ``trace_export``: Perfetto / Chrome trace-event JSON export for the
  benches (``bench_logs/*.trace.json``).
- ``slo``: the per-tenant chip-second attribution ledger and the
  multi-window SLO error-budget engine (ISSUE 20) — jax-free policy
  objects; the serving loop owns their metric/span export.

Domain *metrics* stay in ``nos_tpu/observability.py`` (the histogram /
counter registry every ``/metrics`` endpoint serves); this package is
the trace half of the observability story, with OpenMetrics exemplars
(utils/metrics.py) linking the two.
"""
from nos_tpu.obs import tracing  # noqa: F401
from nos_tpu.obs.slo import (  # noqa: F401
    IDLE_TENANT,
    ChipLedger,
    SloBudgetEngine,
    aggregate_slo,
    objectives_from_quota,
)
from nos_tpu.obs.tracing import (  # noqa: F401
    FlightRecorder,
    Span,
    SpanContext,
    Tracer,
    configure,
    current,
    pod_trace_context,
    recorder,
    span,
    stamp_trace_context,
    start_span,
    traced,
    tracer,
)
