"""Perfetto / Chrome trace-event exporter.

Converts recorded spans into the Chrome Trace Event JSON format
(``chrome://tracing`` and https://ui.perfetto.dev both load it
directly): one "complete" (``ph: "X"``) event per span, grouped into one
process row per control-plane component (scheduler, quota, partitioner,
lifecycle, tpuagent, chaos) with span events as instant markers. The
benches (bench_sched.py, bench_chaos.py) write
``bench_logs/*.trace.json`` through this module so a scale4k run or a
chaos MTTR episode opens straight in a trace viewer.

Timestamps: trace-event ``ts``/``dur`` are microseconds. Span stamps may
be wall-clock epoch seconds or a simulated clock's small floats; either
way the export rebases onto the earliest span so the viewer opens at
t=0 instead of 50 years into the timeline.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, Iterable, List, Optional

from nos_tpu.obs.tracing import FlightRecorder, Span

__all__ = ["to_chrome_trace", "export_chrome_trace", "export_recorder"]


def _us(seconds: float) -> float:
    return round(seconds * 1e6, 3)


def to_chrome_trace(spans: Iterable[Span]) -> Dict[str, Any]:
    """Chrome trace-event JSON object for ``spans`` (open spans are
    skipped — they have no duration to draw)."""
    done = [sp for sp in spans if sp.end_time is not None]
    pids: Dict[str, int] = {}
    events: List[Dict[str, Any]] = []
    t0 = min((sp.start for sp in done), default=0.0)
    for sp in sorted(done, key=lambda s: (s.start, s.trace_id, s.span_id)):
        pid = pids.setdefault(sp.component, len(pids) + 1)
        args = {
            "trace_id": sp.trace_id,
            "span_id": sp.span_id,
            "parent_id": sp.parent_id or "",
            "status": sp.status,
        }
        args.update({k: str(v) for k, v in sp.attrs.items()})
        if sp.status_message:
            args["status_message"] = sp.status_message
        events.append({
            "name": sp.name,
            "cat": sp.component,
            "ph": "X",
            "ts": _us(sp.start - t0),
            "dur": max(_us(sp.end_time - sp.start), 1.0),
            "pid": pid,
            # one row per trace within the component's process: the
            # pod-journey / episode reads as a lane
            "tid": int(sp.trace_id[:8], 16),
            "args": args,
        })
        for ts, name, attrs in (sp.events or ()):
            events.append({
                "name": name,
                "cat": sp.component,
                "ph": "i",
                "s": "t",            # thread-scoped instant
                "ts": _us(ts - t0),
                "pid": pid,
                "tid": int(sp.trace_id[:8], 16),
                "args": {k: str(v) for k, v in attrs.items()},
            })
    for component, pid in pids.items():
        events.append({
            "name": "process_name",
            "ph": "M",
            "pid": pid,
            "args": {"name": component},
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def export_chrome_trace(spans: Iterable[Span], path: str) -> str:
    """Write ``spans`` as a Perfetto-loadable file; returns ``path``."""
    doc = to_chrome_trace(spans)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(doc, f)
    return path


def export_recorder(rec: Optional[FlightRecorder], path: str) -> str:
    """Export everything a flight recorder currently holds."""
    from nos_tpu.obs import tracing

    rec = rec if rec is not None else tracing.recorder()
    return export_chrome_trace(rec.spans(), path)
