"""Per-tenant chip-second attribution + SLO error-budget engine
(ISSUE 20 tentpole), deliberately jax-free.

Two pieces, both clock-injectable so identical event sequences are
identical verdicts (the determinism bar the quota scheduler and the
bench's byte-identical reruns already hold):

``ChipLedger`` — the attribution ledger. The serving engine feeds it
one call per quantum with the two timestamps the tick profiler already
pays for (one-clock-read discipline: the ledger NEVER reads a clock
itself), plus the quantum's structural work weights: decode tokens
emitted per (tenant, phase) and prefill tokens advanced per tenant.
The measured quantum duration is split across those weights
token-proportionally; time between quanta, and quanta that moved no
tokens, land in an explicit ``_idle`` bucket. All accounting is
INTEGER nanoseconds with the split's rounding residual assigned to the
last bucket, so the conservation invariant

    sum over (tenant, phase) charges  ==  wall engine time

holds EXACTLY — structurally, on any clock, through preempt/resume,
tenant reclaim, handoff adopt and supervised engine swaps (a swap
births a fresh ledger; the serving loop delta-mirrors both into the
same monotone counters, the PR 13 tenant-counter pattern). KV
residency rides the same call: resident HBM bytes per tenant accrue
byte-seconds over each quantum's full span (residency persists through
idle gaps between quanta).

``SloBudgetEngine`` — per-tenant objectives (TTFT/TPOT p99 targets, a
goodput floor) evaluated as SRE multi-burn-rate windows: a fast window
(~5m) for paging/trip decisions and a slow window (~1h) for budget
remaining. ``burn = bad_fraction / allowed`` where ``allowed`` is the
objective's error budget (0.01 for a p99 target, ``1 - floor`` for
goodput). A fast-window burn at/over the trip threshold fires at most
once per ``capture_interval_s`` per (tenant, objective) — the rate
limit that keeps a sustained breach from wedging the flight recorder.

Neither object registers metrics or spans; the serving loop owns the
export surface (and only builds these when the tenant config carries
``slo`` objectives — unconfigured means zero new per-tick work).
"""
from __future__ import annotations

from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

__all__ = ["IDLE_TENANT", "ChipLedger", "SloBudgetEngine",
           "objectives_from_quota", "aggregate_slo", "P99_ALLOWED"]

#: the bucket un-attributed engine time is charged to — making idle an
#: explicit tenant is what makes the ledger conservation-CHECKABLE
IDLE_TENANT = "_idle"

#: error budget of a p99 objective: 1% of requests may breach
P99_ALLOWED = 0.01

_NS = 1_000_000_000


class ChipLedger:
    """Integer-nanosecond per-(tenant, phase) chip-time charges plus
    per-tenant KV byte-seconds. Phases: ``decode``, ``prefill``,
    ``idle`` (the ``_idle`` tenant only)."""

    def __init__(self):
        # (tenant, phase) -> charged ns; invariant: sum == wall_ns
        self._ns: Dict[Tuple[str, str], int] = {}
        self.wall_ns: int = 0
        self._kv_byte_s: Dict[str, float] = {}
        self._cursor: Optional[float] = None

    def note_quantum(self, t0: float, t1: float,
                     work: Optional[Dict[Tuple[str, str], int]] = None,
                     kv_bytes: Optional[Dict[str, int]] = None) -> None:
        """Charge one engine quantum ``[t0, t1]``. ``work`` maps
        (tenant, phase) to the quantum's token count for that bucket
        (decode tokens emitted / prefill tokens advanced) — the
        structural batch-share weights the measured duration splits
        over. ``kv_bytes`` maps tenant to HBM bytes resident across the
        quantum. Both timestamps come from the caller's existing clock
        reads; this method never reads a clock."""
        if t1 < t0:
            t1 = t0
        if self._cursor is None:
            self._cursor = t0
        gap_ns = max(0, round((t0 - self._cursor) * _NS))
        work_ns = max(0, round((t1 - max(t0, self._cursor)) * _NS))
        span_ns = gap_ns + work_ns
        if kv_bytes and span_ns:
            span_s = span_ns / _NS
            for tenant, nbytes in kv_bytes.items():
                if nbytes:
                    self._kv_byte_s[tenant] = self._kv_byte_s.get(
                        tenant, 0.0) + nbytes * span_s
        idle_ns = gap_ns
        total_w = sum(work.values()) if work else 0
        if total_w > 0 and work_ns > 0:
            # deterministic exact split: sorted buckets take their
            # floored proportional share, the last takes the residual —
            # the quantum's charges sum to work_ns by construction
            items = sorted(work.items())
            remaining = work_ns
            for i, (key, w) in enumerate(items):
                share = remaining if i == len(items) - 1 \
                    else work_ns * w // total_w
                remaining -= share
                if share:
                    self._ns[key] = self._ns.get(key, 0) + share
        else:
            idle_ns += work_ns
        if idle_ns:
            key = (IDLE_TENANT, "idle")
            self._ns[key] = self._ns.get(key, 0) + idle_ns
        self.wall_ns += span_ns
        if self._cursor is None or t1 > self._cursor:
            self._cursor = t1

    # -- introspection ---------------------------------------------------
    def totals_ns(self) -> Dict[Tuple[str, str], int]:
        """Raw charge counters for the loop's delta-mirror."""
        return dict(self._ns)

    def kv_byte_seconds(self) -> Dict[str, float]:
        return dict(self._kv_byte_s)

    def conserved(self) -> bool:
        """The invariant, checkable at any instant: every wall
        nanosecond is attributed to exactly one (tenant, phase)."""
        return sum(self._ns.values()) == self.wall_ns

    def snapshot(self) -> dict:
        """/stats ``chip_ledger`` block (per-engine; the loop overlays
        its swap-surviving cumulative totals)."""
        per: Dict[str, Dict[str, float]] = {}
        for (tenant, phase), ns in sorted(self._ns.items()):
            per.setdefault(tenant, {})[phase] = round(ns / 1e6, 3)
        return {
            "wall_ms": round(self.wall_ns / 1e6, 3),
            "chip_ms": per,
            "kv_byte_seconds": {
                t: round(v, 3)
                for t, v in sorted(self._kv_byte_s.items())},
            "conserved": self.conserved(),
        }


def objectives_from_quota(quota) -> Dict[str, Dict[str, float]]:
    """tenant -> {objective: allowed bad fraction} from a parsed
    ``TenantQuotaConfig`` (tenants without an ``slo`` block contribute
    nothing). Empty result == SLO accounting off."""
    out: Dict[str, Dict[str, float]] = {}
    for name, spec in getattr(quota, "tenants", {}).items():
        slo = getattr(spec, "slo", None)
        if slo is None:
            continue
        objs: Dict[str, float] = {}
        if slo.ttft_p99_ms > 0:
            objs["ttft_p99"] = P99_ALLOWED
        if slo.tpot_p99_ms > 0:
            objs["tpot_p99"] = P99_ALLOWED
        if slo.goodput_floor > 0:
            # rounded: the budget fraction travels through /stats and
            # the bench's byte-identical artifacts
            objs["goodput"] = round(1.0 - slo.goodput_floor, 6)
        if objs:
            out[name] = objs
    return out


class _Window:
    """One rolling (t, bad) event window with O(1) running counts."""

    __slots__ = ("span_s", "events", "total", "bad")

    def __init__(self, span_s: float):
        self.span_s = span_s
        self.events: Deque[Tuple[float, int]] = deque()
        self.total = 0
        self.bad = 0

    def add(self, now: float, bad: bool) -> None:
        self.events.append((now, 1 if bad else 0))
        self.total += 1
        self.bad += 1 if bad else 0
        self.prune(now)

    def prune(self, now: float) -> None:
        cutoff = now - self.span_s
        ev = self.events
        while ev and ev[0][0] <= cutoff:
            _, b = ev.popleft()
            self.total -= 1
            self.bad -= b


class SloBudgetEngine:
    """Multi-window burn-rate evaluation over per-tenant objectives.
    ``note`` returns True when this event fires a (rate-limited)
    fast-window trip — the caller mints the ``slo.breach`` span and
    pins the trace."""

    def __init__(self, objectives: Dict[str, Dict[str, float]],
                 fast_window_s: float = 300.0,
                 slow_window_s: float = 3600.0,
                 burn_threshold: float = 14.4,
                 capture_interval_s: float = 300.0,
                 min_events: int = 10):
        self.objectives = {
            t: dict(objs) for t, objs in objectives.items()}
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.burn_threshold = float(burn_threshold)
        self.capture_interval_s = float(capture_interval_s)
        self.min_events = int(min_events)
        self._fast: Dict[Tuple[str, str], _Window] = {}
        self._slow: Dict[Tuple[str, str], _Window] = {}
        self._last_trip: Dict[Tuple[str, str], float] = {}
        self.trips: Dict[Tuple[str, str], int] = {}

    def tracked(self, tenant: str) -> Dict[str, float]:
        return self.objectives.get(tenant, {})

    def _wins(self, key: Tuple[str, str]) -> Tuple[_Window, _Window]:
        f = self._fast.get(key)
        if f is None:
            f = self._fast[key] = _Window(self.fast_window_s)
            self._slow[key] = _Window(self.slow_window_s)
        return f, self._slow[key]

    @staticmethod
    def _burn(win: _Window, allowed: float) -> float:
        if win.total == 0:
            return 0.0
        return (win.bad / win.total) / max(allowed, 1e-9)

    def note(self, tenant: str, objective: str, bad: bool,
             now: float) -> bool:
        """Record one judged event; True == fast-window trip fired
        (burn over threshold, enough events, rate limit clear)."""
        allowed = self.objectives.get(tenant, {}).get(objective)
        if allowed is None:
            return False
        key = (tenant, objective)
        fast, slow = self._wins(key)
        fast.add(now, bad)
        slow.add(now, bad)
        if not bad or fast.total < self.min_events:
            return False
        if self._burn(fast, allowed) < self.burn_threshold:
            return False
        last = self._last_trip.get(key)
        if last is not None and now - last < self.capture_interval_s:
            return False
        self._last_trip[key] = now
        self.trips[key] = self.trips.get(key, 0) + 1
        return True

    # -- introspection ---------------------------------------------------
    def rows(self, now: float) -> List[dict]:
        """One row per configured (tenant, objective): burn rates per
        window, budget remaining, and the raw window counts the gateway
        re-aggregates fleet-wide."""
        out = []
        for tenant in sorted(self.objectives):
            for objective, allowed in sorted(
                    self.objectives[tenant].items()):
                key = (tenant, objective)
                fast, slow = self._wins(key)
                fast.prune(now)
                slow.prune(now)
                budget = 1.0
                if slow.total:
                    budget = max(0.0, 1.0 - slow.bad
                                 / (allowed * slow.total))
                out.append({
                    "tenant": tenant,
                    "objective": objective,
                    "allowed": allowed,
                    "burn_fast": round(self._burn(fast, allowed), 3),
                    "burn_slow": round(self._burn(slow, allowed), 3),
                    "budget_remaining_ratio": round(budget, 4),
                    "windows": {
                        "fast": {"total": fast.total, "bad": fast.bad},
                        "slow": {"total": slow.total, "bad": slow.bad},
                    },
                    "trips": self.trips.get(key, 0),
                })
        return out

    def snapshot(self, now: float) -> dict:
        """/stats ``slo_budget`` block."""
        return {
            "fast_window_s": self.fast_window_s,
            "slow_window_s": self.slow_window_s,
            "burn_threshold": self.burn_threshold,
            "capture_interval_s": self.capture_interval_s,
            "min_events": self.min_events,
            "objectives": self.rows(now),
        }


def aggregate_slo(blocks: List[dict],
                  burn_threshold: float = 14.4) -> List[dict]:
    """Fleet roll-up: merge per-replica ``slo_budget`` blocks (their
    ``objectives`` rows) by (tenant, objective), recomputing burn and
    budget remaining from the SUMMED window counts — a fleet-wide bad
    fraction, not an average of ratios."""
    acc: Dict[Tuple[str, str], dict] = {}
    for block in blocks:
        for row in (block or {}).get("objectives", []):
            key = (row["tenant"], row["objective"])
            a = acc.get(key)
            if a is None:
                a = acc[key] = {
                    "tenant": row["tenant"],
                    "objective": row["objective"],
                    "allowed": row["allowed"],
                    "fast_total": 0, "fast_bad": 0,
                    "slow_total": 0, "slow_bad": 0,
                    "trips": 0, "replicas": 0,
                }
            w = row["windows"]
            a["fast_total"] += w["fast"]["total"]
            a["fast_bad"] += w["fast"]["bad"]
            a["slow_total"] += w["slow"]["total"]
            a["slow_bad"] += w["slow"]["bad"]
            a["trips"] += row.get("trips", 0)
            a["replicas"] += 1
    out = []
    for key in sorted(acc):
        a = acc[key]
        allowed = max(a["allowed"], 1e-9)
        burn_fast = (a["fast_bad"] / a["fast_total"] / allowed
                     if a["fast_total"] else 0.0)
        burn_slow = (a["slow_bad"] / a["slow_total"] / allowed
                     if a["slow_total"] else 0.0)
        budget = 1.0
        if a["slow_total"]:
            budget = max(0.0, 1.0 - a["slow_bad"]
                         / (allowed * a["slow_total"]))
        out.append({
            "tenant": a["tenant"],
            "objective": a["objective"],
            "allowed": a["allowed"],
            "burn_fast": round(burn_fast, 3),
            "burn_slow": round(burn_slow, 3),
            "budget_remaining_ratio": round(budget, 4),
            "breaching": burn_fast >= burn_threshold,
            "windows": {
                "fast": {"total": a["fast_total"],
                         "bad": a["fast_bad"]},
                "slow": {"total": a["slow_total"],
                         "bad": a["slow_bad"]},
            },
            "trips": a["trips"],
            "replicas": a["replicas"],
        })
    return out
