"""Entry-point binaries (analog of the reference's cmd/ tree, SURVEY §2.1).

Each module exposes ``build(args)`` (wire the component, return it without
running — used by tests) and ``main(argv)`` (parse flags, run the daemon).
Run as ``python -m nos_tpu.cmd <binary> [flags]``:

  apiserver        the coordination backbone all binaries point at (the
                   kube-apiserver stand-in; hosts admission webhooks)
  operator         ElasticQuota/CompositeElasticQuota reconcilers
  scheduler        quota- and gang-aware pod scheduler
  partitioner      dynamic TPU partitioning control plane
  tpuagent         per-node daemon: reporter + actuator
  metricsexporter  one-shot cluster telemetry snapshot
"""
