"""Entry-point binaries (analog of the reference's cmd/ tree, SURVEY §2.1).

Each module exposes ``build(args)`` (wire the component, return it without
running — used by tests) and ``main(argv)`` (parse flags, run the daemon).
Run as ``python -m nos_tpu.cmd <binary> [flags]``:

  apiserver        the coordination backbone all binaries point at (the
                   kube-apiserver stand-in; hosts admission webhooks)
  operator         ElasticQuota/CompositeElasticQuota reconcilers
  scheduler        quota- and gang-aware pod scheduler
  partitioner      dynamic TPU partitioning control plane
  tpuagent         per-node daemon: reporter + actuator
  metricsexporter  one-shot cluster telemetry snapshot

Shared logging lives here: every binary takes ``--log-format json`` and
routes through :func:`setup_logging`, which (in json mode) emits one JSON
object per line with ``trace_id``/``span_id`` injected whenever a tracing
span is active — so logs and /debug/traces correlate on the same ids.
"""
from __future__ import annotations

import json
import logging
import time


class JsonLogFormatter(logging.Formatter):
    """One JSON object per line; trace correlation fields injected from
    the context-local tracing span (nos_tpu/obs/tracing.py) when one is
    active, so ``jq 'select(.trace_id=="…")'`` replays one pod journey
    straight out of the daemon logs."""

    def format(self, record: logging.LogRecord) -> str:
        from nos_tpu.obs import tracing

        out = {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S",
                                time.gmtime(record.created))
            + f".{int(record.msecs):03d}Z",
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        sp = tracing.current()
        if sp is not None:
            out["trace_id"] = sp.trace_id
            out["span_id"] = sp.span_id
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, ensure_ascii=False)


def setup_logging(level: int = 0, log_format: str = "text",
                  numeric_level: int = None) -> None:
    """Root logging for a cmd/ binary. ``log_format`` is ``text`` (the
    classic human-readable line) or ``json`` (structured, one object per
    line, trace-correlated). ``level`` is the kube-style -v verbosity
    (0 = INFO, >0 = DEBUG); binaries whose config carries a real logging
    level name (trainer/server/generate ``log_level: warning``) pass it
    via ``numeric_level``, which takes precedence."""
    root = logging.getLogger()
    for h in list(root.handlers):
        root.removeHandler(h)
    handler = logging.StreamHandler()
    if log_format == "json":
        handler.setFormatter(JsonLogFormatter())
    else:
        handler.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)s %(name)s: %(message)s"))
    root.addHandler(handler)
    if numeric_level is not None:
        root.setLevel(numeric_level)
    else:
        root.setLevel(logging.DEBUG if level > 0 else logging.INFO)
