"""Shared daemon plumbing for the cmd/ binaries.

Every reference binary serves healthz/readyz probes and a metrics endpoint
(cmd/operator/operator.go:112-119; metrics.bindAddress in the component
ConfigMaps). ``HealthServer`` provides those three endpoints for any
Manager-hosting process; ``common_flags``/``connect`` standardize the
--api / --health-port flags.
"""
from __future__ import annotations

import argparse
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from nos_tpu.kube.httpapi import RemoteApiServer
from nos_tpu.utils.metrics import default_registry

logger = logging.getLogger(__name__)


class HealthServer:
    """Serves /healthz, /readyz, /metrics for one binary."""

    def __init__(self, manager=None, host: str = "127.0.0.1", port: int = 0):
        mgr = manager

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _send(self, status: int, text: str) -> None:
                body = text.encode()
                self.send_response(status)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    ok = mgr.healthz() if mgr is not None else True
                    self._send(200 if ok else 500, "ok" if ok else "unhealthy")
                elif self.path == "/readyz":
                    ok = mgr.readyz() if mgr is not None else True
                    self._send(200 if ok else 500, "ok" if ok else "not ready")
                elif self.path == "/metrics":
                    self._send(200, default_registry().expose())
                else:
                    self._send(404, "not found")

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> str:
        host, port = self.httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "HealthServer":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="health-http", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        if self._thread:
            self._thread.join(timeout=5)


def common_flags(parser: argparse.ArgumentParser, config: bool = True) -> None:
    parser.add_argument(
        "--api", default="http://127.0.0.1:8001",
        help="URL of the nos-tpu apiserver binary",
    )
    parser.add_argument(
        "--kubeconfig", default=None,
        help="kubeconfig path: run against a REAL Kubernetes API server "
             "(GKE/kind) instead of the nos-tpu apiserver double",
    )
    parser.add_argument(
        "--in-cluster", action="store_true",
        help="use the pod service-account to reach the real API server "
             "(the in-cluster deployment path)",
    )
    parser.add_argument(
        "--kube-context", default=None,
        help="kubeconfig context override",
    )
    parser.add_argument(
        "-v", "--log-level", type=int, default=None, dest="log_level",
        help="log verbosity override (kube component convention; takes "
             "precedence over the config file's log_level — needed when "
             "the config is a KubeSchedulerConfiguration, which carries "
             "no log level)",
    )
    parser.add_argument(
        "--health-port", type=int, default=0,
        help="healthz/readyz/metrics port (0 = ephemeral)",
    )
    parser.add_argument(
        "--health-host", default="0.0.0.0",
        help="healthz bind address (kubelet probes the pod IP, so the "
             "default binds all interfaces)",
    )
    if config:
        parser.add_argument(
            "-config", "--config", dest="config", default=None,
            help="component config YAML (reference: ctrl.ConfigFile().AtPath)",
        )


def connect(args):
    """API-server binding per flags: --kubeconfig/--in-cluster selects the
    real-Kubernetes REST adapter (nos_tpu.kube.rest.K8sApiServer); the
    default is the nos-tpu apiserver double. Both duck-type the same
    surface, so every controller runs unchanged against either."""
    if getattr(args, "kubeconfig", None) or getattr(args, "in_cluster", False):
        from nos_tpu.kube.rest import K8sApiServer

        remote = K8sApiServer(
            kubeconfig=getattr(args, "kubeconfig", None),
            context=getattr(args, "kube_context", None),
        )
        if not remote.healthz():
            raise SystemExit("real API server is not reachable/ready")
        return remote
    remote = RemoteApiServer(args.api)
    if not remote.healthz():
        raise SystemExit(f"apiserver at {args.api} is not reachable")
    return remote


def setup_logging(level: int = 0) -> None:
    logging.basicConfig(
        level=logging.DEBUG if level > 0 else logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )


def run_daemon(manager, health_port: int, health_host: str) -> None:
    health = HealthServer(manager, host=health_host, port=health_port).start()
    logger.info("health endpoints at %s", health.address)
    try:
        manager.run()
    except KeyboardInterrupt:
        pass
    finally:
        manager.stop()
        health.stop()
