"""Shared daemon plumbing for the cmd/ binaries.

Every reference binary serves healthz/readyz probes and a metrics endpoint
(cmd/operator/operator.go:112-119; metrics.bindAddress in the component
ConfigMaps). ``HealthServer`` provides those endpoints — plus the tracing
flight recorder at ``/debug/traces`` — for any Manager-hosting process;
``common_flags``/``connect`` standardize the --api / --health-port flags.
"""
from __future__ import annotations

import argparse
import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from nos_tpu.cmd import setup_logging as _setup_logging
from nos_tpu.kube.httpapi import RemoteApiServer
from nos_tpu.obs import tracing
from nos_tpu.utils.metrics import default_registry

logger = logging.getLogger(__name__)

OPENMETRICS_CONTENT_TYPE = \
    "application/openmetrics-text; version=1.0.0; charset=utf-8"


def metrics_payload(accept: str = "") -> tuple:
    """(body, content_type) for a /metrics scrape, content-negotiated:
    an ``Accept`` header asking for ``application/openmetrics-text``
    gets the OpenMetrics dialect (histogram buckets carry trace
    exemplars); everything else gets the classic Prometheus text
    format. Shared by HealthServer and the serving binary's bespoke
    HTTP surface (cmd/server.py) so the two expose identically."""
    if "application/openmetrics-text" in (accept or ""):
        return (default_registry().expose(openmetrics=True),
                OPENMETRICS_CONTENT_TYPE)
    return default_registry().expose(), "text/plain; version=0.0.4"


class HealthServer:
    """Serves /healthz, /readyz, /metrics and /debug/traces for one
    binary — plus /stats when the hosted manager exposes a live
    introspection snapshot (``stats() -> dict``). /metrics
    content-negotiates: an ``Accept`` header asking for
    ``application/openmetrics-text`` gets the OpenMetrics dialect with
    trace exemplars on histogram buckets; everything else gets the
    classic Prometheus text format."""

    def __init__(self, manager=None, host: str = "127.0.0.1", port: int = 0):
        mgr = manager

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _send(self, status: int, text: str,
                      content_type: str = "text/plain; version=0.0.4") -> None:
                body = text.encode()
                self.send_response(status)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    ok = mgr.healthz() if mgr is not None else True
                    self._send(200 if ok else 500, "ok" if ok else "unhealthy")
                elif self.path == "/readyz":
                    ok = mgr.readyz() if mgr is not None else True
                    self._send(200 if ok else 500, "ok" if ok else "not ready")
                elif self.path == "/metrics":
                    body, ctype = metrics_payload(
                        self.headers.get("Accept", ""))
                    self._send(200, body, ctype)
                elif self.path == "/stats":
                    # live introspection: any manager exposing stats()
                    # serves its JSON snapshot here (the serving binary
                    # has its own richer handler in cmd/server.py)
                    stats = getattr(mgr, "stats", None)
                    if stats is None:
                        self._send(404, "not found")
                    else:
                        self._send(200, json.dumps(stats()),
                                   "application/json")
                elif self.path == "/debug/traces":
                    self._send(200, json.dumps(tracing.recorder().to_json()),
                               "application/json")
                elif self.path.startswith("/debug/traces/"):
                    tid = self.path.rsplit("/", 1)[1]
                    spans = tracing.recorder().trace(tid)
                    if not spans:
                        self._send(404, json.dumps({"error": "unknown trace",
                                                    "trace_id": tid}),
                                   "application/json")
                    else:
                        self._send(200, json.dumps({
                            "trace_id": tid,
                            "spans": [sp.to_dict() for sp in spans],
                        }), "application/json")
                else:
                    self._send(404, "not found")

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> str:
        host, port = self.httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "HealthServer":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="health-http", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        if self._thread:
            self._thread.join(timeout=5)


def common_flags(parser: argparse.ArgumentParser, config: bool = True) -> None:
    parser.add_argument(
        "--api", default="http://127.0.0.1:8001",
        help="URL of the nos-tpu apiserver binary",
    )
    parser.add_argument(
        "--kubeconfig", default=None,
        help="kubeconfig path: run against a REAL Kubernetes API server "
             "(GKE/kind) instead of the nos-tpu apiserver double",
    )
    parser.add_argument(
        "--in-cluster", action="store_true",
        help="use the pod service-account to reach the real API server "
             "(the in-cluster deployment path)",
    )
    parser.add_argument(
        "--kube-context", default=None,
        help="kubeconfig context override",
    )
    parser.add_argument(
        "-v", "--log-level", type=int, default=None, dest="log_level",
        help="log verbosity override (kube component convention; takes "
             "precedence over the config file's log_level — needed when "
             "the config is a KubeSchedulerConfiguration, which carries "
             "no log level)",
    )
    parser.add_argument(
        "--health-port", type=int, default=0,
        help="healthz/readyz/metrics/debug-traces port (0 = ephemeral)",
    )
    parser.add_argument(
        "--health-host", default="0.0.0.0",
        help="healthz bind address (kubelet probes the pod IP, so the "
             "default binds all interfaces)",
    )
    observability_flags(parser)
    if config:
        parser.add_argument(
            "-config", "--config", dest="config", default=None,
            help="component config YAML (reference: ctrl.ConfigFile().AtPath)",
        )


def observability_flags(parser: argparse.ArgumentParser) -> None:
    """The shared structured-logging + tracing flags (folded into
    common_flags; binaries with bespoke parsers call this directly)."""
    parser.add_argument(
        "--log-format", choices=("text", "json"), default="text",
        help="log line format; json emits one object per line with "
             "trace_id/span_id injected when a tracing span is active",
    )
    parser.add_argument(
        "--trace-sampling", type=float, default=None,
        help="fraction of new pod-journey traces to record (0 disables, "
             "1 records all; default from NOS_TPU_TRACE_SAMPLING or 1.0)",
    )
    parser.add_argument(
        "--trace-recorder-size", type=int, default=None,
        help="flight-recorder capacity: recently completed traces kept "
             "in memory for /debug/traces (default 256)",
    )
    parser.add_argument(
        "--trace-slow-threshold", type=float, default=None,
        help="seconds over which a completed span pins its whole trace "
             "in the flight recorder (default 1.0)",
    )


def connect(args):
    """API-server binding per flags: --kubeconfig/--in-cluster selects the
    real-Kubernetes REST adapter (nos_tpu.kube.rest.K8sApiServer); the
    default is the nos-tpu apiserver double. Both duck-type the same
    surface, so every controller runs unchanged against either."""
    if getattr(args, "kubeconfig", None) or getattr(args, "in_cluster", False):
        from nos_tpu.kube.rest import K8sApiServer

        remote = K8sApiServer(
            kubeconfig=getattr(args, "kubeconfig", None),
            context=getattr(args, "kube_context", None),
        )
        if not remote.healthz():
            raise SystemExit("real API server is not reachable/ready")
        return remote
    remote = RemoteApiServer(args.api)
    if not remote.healthz():
        raise SystemExit(f"apiserver at {args.api} is not reachable")
    return remote


def setup_logging(level: int = 0, log_format: str = "text") -> None:
    _setup_logging(level, log_format)


def setup_observability(args, level: Optional[int] = None) -> None:
    """Apply the shared observability flags: logging format plus the
    tracing sampler / flight-recorder knobs. Every cmd/ main calls this
    right after parse_args; ``level`` overrides the -v flag for binaries
    whose config file carries its own log level."""
    if level is None:
        level = getattr(args, "log_level", 0) or 0
    setup_logging(level, getattr(args, "log_format", "text"))
    tracing.configure(
        sampling=getattr(args, "trace_sampling", None),
        recorder_size=getattr(args, "trace_recorder_size", None),
        slow_threshold_s=getattr(args, "trace_slow_threshold", None),
    )


def run_daemon(manager, health_port: int, health_host: str) -> None:
    health = HealthServer(manager, host=health_host, port=health_port).start()
    logger.info("health endpoints at %s", health.address)
    try:
        manager.run()
    except KeyboardInterrupt:
        pass
    finally:
        manager.stop()
        health.stop()
