"""nos-tpu-server — the serving binary a gang-scheduled inference pod
runs: the continuous-batching engine (models/serving.py) behind a
minimal HTTP API.

    POST /v1/generate   {"prompt": [ids], "max_new_tokens": N,
                         "temperature": T?, "top_k": K?, "top_p": P?,
                         "seed": S?}
                        -> {"tokens": [full sequence]}
    POST /admin/drain   begin graceful drain (stop admitting, flip
                        /readyz; the fleet controller's scale-down hook)
    POST /admin/undrain revert a drain (resume admitting)
    GET  /healthz       -> ok          GET /readyz  -> ok | draining
    GET  /metrics       Prometheus text (OpenMetrics + exemplars when
                        Accept asks for it)
    GET  /stats         live JSON snapshot: active slots, pending
                        queue, pipeline window, prefix cache,
                        SLO/goodput, rolling request/token rates
    GET  /debug/traces  tracing flight recorder (serve.request spans;
                        SLO-breaching requests pinned)

Requests batch continuously: concurrent POSTs share the engine's decode
ticks (one compiled program per tick serves every active slot), each
blocking only until its own slot completes. Params load exactly like
``nos-tpu-generate`` (checkpoint restore, optional int8).
"""
from __future__ import annotations

import argparse
import json
import logging
import math
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, fields
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Sequence

from nos_tpu.cmd.serve import metrics_payload
from nos_tpu.kvfabric import FABRIC_TOKEN_HEADER  # jax-free plane
from nos_tpu.models.errors import (  # jax-free module: keeps this file
    DeadlineExceeded, DeadlineUnmeetable, EngineRecovering, Infeasible,
    QueueFull,                       # importable without jax
)
from nos_tpu.models.supervision import EngineSupervisor  # jax-free too
from nos_tpu.models.tenantquota import (   # jax-free (quota math only)
    TenantQuotaConfig, validate_tenant_name,
)
from nos_tpu.obs import tracing
from nos_tpu.obs.slo import (  # jax-free (budget/ledger policy only)
    IDLE_TENANT,
    SloBudgetEngine,
    objectives_from_quota,
)
from nos_tpu.utils.metrics import default_registry

logger = logging.getLogger("nos_tpu.server")

# terminal request outcomes: every request that enters the serving loop
# leaves through exactly ONE of these, incrementing
# nos_tpu_serve_requests_total{outcome} exactly once (pinned by tests).
# ``deadline`` covers both shed-at-admission (rolling estimates said the
# deadline could not be met) and cancelled-mid-flight expiry.
OUTCOMES = ("finished", "cancelled", "abandoned", "rejected", "failed",
            "deadline")

# TTFT spans prefill (ms on warm buckets) through queueing storms (s);
# TPOT is per-token (sub-ms fused to ~100ms on big models); compiles
# run seconds to minutes on real toolchains
TTFT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                5.0, 10.0, 30.0)
TPOT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                0.25, 0.5, 1.0)
COMPILE_BUCKETS = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
                   120.0, 300.0)

# rolling-rate window for the /stats snapshot
RATE_WINDOW_S = 60.0

# decode-tick phases, in tick order: assemble (admission + batch
# assembly inside step_begin), dispatch (device dispatch of the decode
# program), wait (host blocked on the in-flight step + result fetch),
# sample (consume/commit in step_finish), bookkeep (ledger, rates,
# gauges, deadline sweep). Non-split engines can't separate the first
# four — their whole step lands under ``dispatch``.
TICK_PHASES = ("assemble", "dispatch", "wait", "sample", "bookkeep")

# sub-ms phase buckets: a healthy pipelined tick spends microseconds on
# its host phases, so the default request-scale buckets would flatline
TICK_PHASE_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                      0.01, 0.025, 0.05, 0.1, 0.25, 1.0)

# bound on the recovery capture phase: swap snapshots are device->host
# copies that can HANG (not just raise) on a lost device — the capture
# runs on a helper thread joined with this timeout, and on expiry the
# recovery falls back to a host-only capture (every slot resumes by
# recompute). Capture is read-only, so the abandoned hung thread races
# nothing.
CAPTURE_TIMEOUT_S = 10.0

# deadline-shed probe cadence: every Nth CONSECUTIVE estimate-based
# shed is admitted anyway. The EWMA estimates only update on completed
# requests, so an estimate inflated past every deadline would otherwise
# shed 100% of traffic forever (zero admissions -> zero completions ->
# no estimate decay); the probe's completion is the decay path.
DEADLINE_PROBE_EVERY = 8


@dataclass
class ServerConfig:
    # model (must match the checkpoint's training config)
    vocab: int = 32000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: int = 0
    d_ff: int = 1408
    max_seq: int = 512
    n_experts: int = 0
    bf16: bool = True
    checkpoint_dir: str = ""
    int8: bool = False
    # serving
    max_batch: int = 8
    # admission bound (0 = unbounded): beyond max_batch active slots, at
    # most this many requests wait; past it, POST /v1/generate answers
    # 429 so clients shed load instead of queueing into timeouts
    max_pending: int = 0
    # tensor-parallel serving: shard params (transformer.param_shardings,
    # or quant.quant_param_shardings when int8) and the KV cache
    # (generate.cache_shardings — KV heads over tp; a paged arena
    # shards the same head axis via paged_cache_shardings, scale
    # planes included) across the first ``tp`` local devices. 0/1 =
    # single device. Tokens are invariant to tp — greedy AND sampled,
    # slot-static and paged, bf16 and int8 alike (tested; sampling
    # decisions run on a replicated f32 logit row so the mesh cannot
    # perturb the stream); requires kv_heads % tp == 0.
    tp: int = 0
    # prefill/decode disaggregation role: "colocated" (default — one
    # engine prefills and decodes), "prefill" (requests leave after
    # their first token as a KV handoff shipped to a decode replica;
    # requires kv_blocks > 0 and a decode_pool), "decode" (adopts
    # handoffs via POST /v1/handoff and serves /v1/result//v1/stream;
    # requires kv_blocks > 0 with the SAME kv_block_size/kv_dtype/
    # model geometry as its prefill peers — restore validates and
    # rejects mismatches). int8 KV halves the handoff bytes over DCN.
    # The gateway routes new requests to prefill replicas and streams
    # from the decode replica after handoff.
    role: str = "colocated"
    # comma-separated decode-replica base URLs a prefill-role server
    # round-robins its handoffs across (e.g.
    # "http://decode-0:8000,http://decode-1:8000"); required (non-empty)
    # when role=prefill, ignored otherwise
    decode_pool: str = ""
    # pusher health memory: after a failed handoff push the target
    # decode replica is skipped for this many seconds before the
    # round-robin retries it (0 disables — every push re-probes dead
    # replicas and eats the connect timeout on the serving thread).
    # Skips are counted in nos_tpu_serve_handoff_skipped_total; if the
    # whole pool is cooling down the pusher ignores the cooldown
    # rather than dropping the handoff
    handoff_cooldown_s: float = 5.0
    # prefill-side decode-pool health view (role=prefill; 0 = off): at
    # most every this-many seconds the handoff pusher refreshes a
    # health snapshot of the decode pool from each target's /stats
    # (queue depth, draining/recovering flags) and prefers healthy,
    # least-loaded replicas — a draining replica is skipped BEFORE the
    # first failed attempt instead of being discovered by one. Off,
    # the pusher is the blind round-robin with only the failure
    # cooldown above.
    handoff_health_interval_s: float = 0.0
    # prefix cache (0 = off). Slot-static KV: ENTRIES — each holds one
    # prompt's KV on device (flagship: ~64 MB per 1k tokens). Paged KV
    # (kv_blocks > 0): BLOCKS — the budget for block-granular prefix
    # chains shared by refcount, so size it in units of kv_block_size
    # tokens (a 512-token system prompt at kv_block_size=16 needs 32).
    prefix_cache_size: int = 0
    # chunked prefill (0 = off): power-of-two chunk size; a long
    # prompt's prefill interleaves with decode ticks one chunk per tick,
    # bounding the latency hit admission inflicts on active requests
    # (under speculative decoding the draft cache chunks alongside the
    # target: one target chunk + one cheap draft chunk per tick).
    prefill_chunk: int = 0
    # per-tick chunked-prefill budget in prompt tokens (0 = the
    # unconditional one-chunk-per-tick rule; requires prefill_chunk):
    # each decode tick the engine spends at most this many prompt
    # tokens on chunk forwards, picking WHICH chunked admissions
    # advance by deadline slack (EDF on estimated TTFT; the budget
    # clamps to zero while any decode slot's TPOT slack is negative),
    # so N concurrent long prompts can no longer multiply every decode
    # tick by N chunk forwards. Outputs stay token-identical to the
    # unbudgeted run for every budget (scheduling changes WHEN a chunk
    # runs, never its contents). Config-echoed for fleet drift
    # detection; see docs/workload-plane/performance-tuning.md
    # "Stall-free colocated serving".
    prefill_budget: int = 0
    # pipelined decode dispatch: up to this many decode ticks in flight
    # before the host blocks on a token fetch (1 = host-serial). Greedy
    # outputs stay bit-identical to generate() at any depth; streaming
    # granularity coarsens to ~depth*decode_steps tokens per SSE frame.
    # The speculative engine pins this to 1 (its verify burst already
    # amortizes dispatch overhead).
    pipeline_depth: int = 1
    # fused multi-step decode: this many decode steps compiled into ONE
    # dispatch (lax.scan), [batch, decode_steps] tokens per device sync.
    # Pays in decode-bound phases; 1 = off. Pinned to 1 under
    # speculative decoding.
    decode_steps: int = 1
    # paged KV cache (kv_blocks > 0 = on): KV lives in one pooled HBM
    # arena of kv_blocks x kv_block_size tokens mapped per slot by
    # block tables, instead of max_batch x max_seq worst-case rows —
    # concurrency is then bound by tokens in use, with COW
    # block-granular prefix sharing and memory-aware admission.
    # kv_block_size must be a power of two >= 8 dividing max_seq.
    # Budget: kv_blocks * kv_block_size tokens of KV resident; size it
    # to HBM after weights (docs/workload-plane/performance-tuning.md
    # "Paged KV cache"). Pinned off under speculative decoding.
    kv_block_size: int = 0
    kv_blocks: int = 0
    # under block-pool pressure the lowest-priority slot is preempted:
    # kv_swap true = swap its KV to host RAM and restore byte-exact;
    # false = drop the KV and recompute it from the tokens on resume
    # (no host RAM, more FLOPs). Both are bit-exact.
    kv_swap: bool = True
    # paged-KV storage dtype: bf16 (the model dtype) or int8 —
    # quantized on the paged scatter with per-block scales, dequantized
    # on the gather. int8 roughly halves KV bytes per token, so a fixed
    # HBM budget holds ~2x the blocks and sustains ~2x the concurrent
    # slots; greedy serving stays self-consistent (token-identical to a
    # reference generate through the same int8 KV path — tested), at a
    # small bounded numeric delta vs bf16. Requires kv_blocks > 0: the
    # slot-static engine has no per-block scale storage and the server
    # rejects the combination with a clear error.
    kv_dtype: str = "bf16"
    # paged attention formulation: "on" = the fused Pallas kernel
    # (paged_decode_attention walks the block table in-kernel for
    # EVERY query shape — decode steps, speculative verify bursts,
    # prefix-hit suffix prefill — streams KV blocks HBM->VMEM and
    # fuses the int8 dequant into the attention inner loop: no
    # materialized gather), "off" = the XLA gather formulation, which
    # stays the escape hatch and the parity oracle. Plumbed as
    # NOS_TPU_PAGED_KERNEL for the engine (the flag is authoritative
    # on a server: a restart must trace the same formulation).
    # Default ON since the parity burn-in the config echo was built
    # for: every serving configuration (speculative, tp-sharded,
    # disaggregated) runs the kernel; --paged-kernel=off is the
    # documented escape hatch. Inert without kv_blocks (the kernel
    # walks per-slot block tables; slot-static engines have none).
    paged_kernel: str = "on"
    # HBM backstop on admission (0 = off): defer admitting while
    # device bytes_in_use / bytes_limit exceeds this fraction, per the
    # same memory_stats() the HBM gauges sample (backends without
    # memory stats skip the check)
    kv_hbm_admit_frac: float = 0.95
    # host-RAM KV tier (ISSUE 17, 0 = off): bytes of host RAM bounding
    # the kvfabric HostTierStore under the HBM arena. With it on,
    # prefix-chain eviction under block pressure DEMOTES the LRU
    # chain's swap payload (quantized bytes + scales) to host RAM
    # instead of dropping it, and a later prefix miss that matches the
    # stored chain PROMOTES it back bit-exactly — re-prefill chip-
    # seconds traded for one host-RAM round trip. Requires kv_blocks
    # AND prefix_cache_size > 0 (the tier stores prefix chains, which
    # only the paged prefix index produces); the store empties on a
    # supervised engine rebuild (host RAM is replica-local state, not
    # durable). Size it a few multiples of the hot system prompts'
    # payload bytes; the demotion ladder is HBM -> host -> drop.
    kv_host_tier_bytes: int = 0
    # shared fleet secret gating the KV fabric's HTTP surfaces ("" =
    # fabric HTTP disabled): a replica only HONORS a kv_sources
    # peer-pull offer and only SERVES GET /v1/kvchain/<digest> when
    # the request carries this value in the X-NOS-KV-Fabric-Token
    # header. kv_sources steers the replica's outbound fetcher and
    # seeds its prefix cache, and chain digests are public arithmetic
    # over scope + tokens — without the gate, any client reaching the
    # serving port gets blind SSRF, cross-tenant KV exfiltration and
    # prefix-cache poisoning. Set the SAME value on every replica and
    # on the gateway (--kv-fabric-token); the host tier itself
    # (demote/promote on this replica) needs no token.
    kv_fabric_token: str = ""
    # speculative decoding (draft_checkpoint_dir set = on): a smaller
    # draft model proposes draft_n_tokens per tick, the target verifies
    # them in one wide forward. Greedy requests stay bit-identical to
    # plain decoding; sampled requests keep the exact target
    # distribution (accept-reject). The speculative engine rides the
    # FULL dispatch template: pipeline_depth/decode_steps/paged-KV/
    # kv_dtype all apply (a fused dispatch commits up to
    # decode_steps * draft_n_tokens tokens per slot, accept/reject
    # resolves in-graph so pipelined windows never wait on the host).
    # Draft dims below must match the draft checkpoint's training
    # config.
    draft_checkpoint_dir: str = ""
    draft_d_model: int = 256
    draft_n_layers: int = 2
    draft_n_heads: int = 4
    draft_n_kv_heads: int = 0
    draft_d_ff: int = 704
    draft_n_tokens: int = 4
    default_max_new_tokens: int = 64
    port: int = 8000
    seed: int = 0
    log_level: str = "info"
    # request-level SLO targets (0 = unset): a completed request meets
    # its SLO when TTFT (submit -> first token observed) and mean TPOT
    # (inter-token, first token excluded) are within these bounds.
    # Feeds nos_tpu_serve_slo_total{slo,outcome} and the goodput gauge;
    # a breach pins the request's trace in the flight recorder.
    slo_ttft_ms: float = 0.0
    slo_tpot_ms: float = 0.0
    # per-tenant SLO error budgets (ISSUE 20; active only when the
    # tenant config below carries ``slo`` objectives): SRE
    # multi-burn-rate windows — the fast window pages/trips breach
    # capture, the slow window measures budget remaining. A fast-window
    # burn at/over the threshold emits an slo.breach span and pins the
    # breaching request's trace, at most once per capture interval per
    # (tenant, objective).
    slo_fast_window_s: float = 300.0
    slo_slow_window_s: float = 3600.0
    slo_burn_threshold: float = 14.4
    slo_capture_interval_s: float = 300.0
    # request-level elastic quota (empty = off): per-tenant token-rate
    # min/max with borrowing — a file path or inline JSON (see
    # models/tenantquota.TenantQuotaConfig). With it set, requests
    # carry a tenant (JSON field ``tenant`` / header ``X-Tenant``;
    # unlabeled traffic is the default tenant), admission is the
    # weighted tenant pick instead of FIFO, a guaranteed tenant
    # reclaims slots by bit-exact preemption (paged engines), tenants
    # at/over max shed 429 reason=tenant_quota under contention, and
    # the prefix cache is tenant-scoped (share_prefix opts out).
    tenant_config: str = ""
    # device-runtime telemetry cadence (seconds; 0 disables): samples
    # device.memory_stats() into the HBM gauges at most this often —
    # guarded, so backends without memory stats (CPU) just skip.
    device_stats_interval_s: float = 10.0
    # supervised engine restarts (0 = off, engine failure is terminal as
    # before): on a decode-tick failure the serving loop captures every
    # live request's resumable state (committed tokens; swap-to-host KV
    # snapshot on a paged engine, recompute re-prefill otherwise — both
    # bit-exact), rebuilds the engine (fresh compile) after exponential
    # backoff + jitter, and re-admits the captured requests at the
    # front of the queue. The budget bounds TOTAL rebuild attempts over
    # the process lifetime; once exhausted, the next failure is
    # terminal (/healthz flips) and orchestration restarts the pod.
    restart_budget: int = 2
    restart_backoff_s: float = 0.5
    restart_backoff_max_s: float = 10.0
    # stuck-tick watchdog (0 = off): a dispatched decode tick blocked
    # in its device wait longer than this with no arrival consumed
    # counts as an engine failure and takes the same supervised-restart
    # path (the blocked thread is superseded and exits when it
    # unblocks). Dispatch-time XLA compiles do NOT count — the clock
    # arms after dispatch returns — so size it above the slowest
    # expected device WAIT, not compile time.
    watchdog_s: float = 0.0
    # default per-request deadline in seconds (0 = none): a request
    # must finish within this budget of submission or it is shed at
    # admission (rolling TTFT/TPOT estimates say it cannot make it —
    # 429 + Retry-After) or cancelled at the next tick barrier
    # (terminal outcome ``deadline``, HTTP 504). Per-request override:
    # JSON field ``deadline_s`` / header ``X-Request-Deadline-S``.
    default_deadline_s: float = 0.0
    # SIGTERM → stop admitting (503 + readyz flips so the Service pulls
    # this endpoint), let in-flight requests finish up to this budget,
    # then exit — the Kubernetes termination contract. Keep it under
    # the pod's terminationGracePeriodSeconds.
    drain_timeout_s: float = 30.0
    # per-socket read/write timeout. daemon_threads=False means process
    # exit JOINS handler threads; without a socket timeout a thread
    # blocked reading a stalled client's request body would outlive the
    # drain budget indefinitely (only SIGKILL would end it). Any blocking
    # socket op now fails within this bound, so exit is bounded by
    # drain_timeout_s + socket_timeout_s.
    socket_timeout_s: float = 30.0

    @classmethod
    def from_yaml_file(cls, path: str) -> "ServerConfig":
        import yaml

        with open(path) as f:
            data = yaml.safe_load(f) or {}
        known = {f.name for f in fields(cls)}
        unknown = set(data) - known
        if unknown:
            raise ValueError(
                f"{path}: unknown server config keys {sorted(unknown)}")
        return cls(**data)


class DrainingError(RuntimeError):
    """Submission refused because the server is draining for termination
    (its own error type so the HTTP layer can answer 503, not 500)."""


class ServingLoop:
    """Thread-safe wrapper around DecodeServer: handlers submit and wait;
    one background thread ticks the engine whenever there is work.

    With an ``engine_factory`` and restart budget, a tick failure (XLA
    OOM, device loss, a wedged allocator) is no longer terminal: the
    loop captures every live request's resumable state from the dead
    engine, rebuilds the engine through the factory (exponential
    backoff + seeded jitter between attempts), and re-admits the
    captured requests at the front of the fresh queue — swap-restored
    byte-exact on a paged engine, recompute-re-prefilled otherwise,
    both bit-exact, so a greedy request's tokens are indistinguishable
    from an undisturbed run. While recovery is in flight, submissions
    get ``EngineRecovering`` (HTTP 503 + Retry-After) and /readyz
    reports ``degraded``; /healthz flips only on TERMINAL failure —
    budget exhausted (or no factory, the pre-supervision behavior) —
    so orchestration restarts the pod exactly when self-healing has
    given up. A stuck-tick watchdog (``watchdog_s``) counts a tick in
    flight past the threshold as a failure and takes the same path.

    Requests may carry a deadline (``deadline_s``; ``default_deadline_s``
    otherwise): unmeetable deadlines are shed at admission against
    rolling TTFT/TPOT estimates (DeadlineUnmeetable — don't burn a slot
    on an answer the client will discard), and expired ones are
    cancelled at the next tick barrier — either way the request's one
    terminal outcome is ``deadline``."""

    def __init__(self, engine, slo_ttft_ms: float = 0.0,
                 slo_tpot_ms: float = 0.0,
                 device_stats_interval_s: float = 0.0,
                 engine_factory=None, restart_budget: int = 2,
                 restart_backoff_s: float = 0.5,
                 restart_backoff_max_s: float = 10.0,
                 watchdog_s: float = 0.0,
                 default_deadline_s: float = 0.0, seed: int = 0,
                 config_echo: Optional[dict] = None,
                 tenant_quota: Optional[TenantQuotaConfig] = None,
                 role: str = "colocated",
                 handoff_targets: Optional[list] = None,
                 handoff_send=None,
                 handoff_cooldown_s: float = 5.0,
                 handoff_health_interval_s: float = 0.0,
                 adopt_ttl_s: float = 600.0,
                 fabric_token: str = "",
                 slo_fast_window_s: float = 300.0,
                 slo_slow_window_s: float = 3600.0,
                 slo_burn_threshold: float = 14.4,
                 slo_capture_interval_s: float = 300.0,
                 slo_min_events: int = 10,
                 slo_clock=None):
        reg = default_registry()
        # register() is idempotent per (name, type, labels) and raises on
        # a mismatched re-registration — exactly what we want at startup
        self.m_requests = reg.counter(
            "nos_tpu_serve_requests_total",
            "Requests leaving the serving loop, by terminal outcome "
            "(finished | cancelled | abandoned | rejected | failed); "
            "every request increments exactly one outcome exactly once",
            ("outcome",))
        self.m_tokens = reg.counter(
            "nos_tpu_serve_tokens_total", "Tokens emitted by decode ticks")
        self.m_ticks = reg.counter(
            "nos_tpu_serve_ticks_total", "Decode ticks executed")
        self.g_active = reg.gauge(
            "nos_tpu_serve_active_slots", "Slots decoding right now")
        self.g_pending = reg.gauge(
            "nos_tpu_serve_pending_requests",
            "Requests waiting for a slot")
        self.m_prefix_hits = reg.gauge(
            "nos_tpu_serve_prefix_hits",
            "Prefill requests served from the prefix cache")
        self.m_prefix_saved = reg.gauge(
            "nos_tpu_serve_prefix_tokens_saved",
            "Prompt tokens whose prefill was skipped via the prefix cache")
        # per-tick economics (buckets carry trace exemplars when a
        # serve.tick span is sampled): service time is the whole
        # quantum (dispatch + wait + bookkeeping); the dispatch gap
        # mirrors the engine's structural dispatch_gap_s — time with NO
        # decode tick in flight while decodable slots existed, i.e. the
        # accelerator host-blocked. pipeline_depth >= 2 drives the gap
        # to ~0 (the window never empties outside barriers); the two
        # histograms together make the win measurable.
        self.h_tick = reg.histogram(
            "nos_tpu_serve_tick_seconds",
            "Serving-loop tick service time (dispatch + wait + host "
            "bookkeeping)")
        self.h_gap = reg.histogram(
            "nos_tpu_serve_dispatch_gap_seconds",
            "Per-tick dispatch gap: time the engine had no decode tick "
            "in flight while decodable slots existed (the accelerator "
            "host-blocked behind bookkeeping)")
        # decode-tick phase profiler: the tick decomposed into named
        # phases — assemble (admission/batch assembly inside
        # step_begin), dispatch (device dispatch of the decode step),
        # wait (host blocked on the in-flight step + fetch), sample
        # (consume/commit in step_finish), bookkeep (ledger, rates,
        # gauges). Derived from the clock reads the loop ALREADY takes
        # per tick plus two new ones (PR 5 discipline: no per-phase
        # clock spam inside the engine hot path).
        self.h_tick_phase = reg.histogram(
            "nos_tpu_serve_tick_phase_seconds",
            "Serving-loop tick time decomposed by phase",
            labelnames=("phase",), buckets=TICK_PHASE_BUCKETS)
        for _ph in TICK_PHASES:
            self.h_tick_phase.labels(_ph)
        # rolling per-tick phase samples for /stats and /debug/profile:
        # (monotonic tick start, {phase: seconds})
        self._tick_phases: deque = deque(maxlen=256)
        # request-level latency ledger surface (engine stamps, this loop
        # observes at completion — nothing here runs per token on the
        # hot tick path; buckets carry trace exemplars of the request's
        # serve.request span when sampled)
        self.h_queue = reg.histogram(
            "nos_tpu_serve_queue_seconds",
            "Submit -> admitted-to-slot wait per request")
        self.h_ttft = reg.histogram(
            "nos_tpu_serve_ttft_seconds",
            "Time to first token: submit -> first token observed on the "
            "host (includes queueing and prefill)",
            buckets=TTFT_BUCKETS)
        self.h_tpot = reg.histogram(
            "nos_tpu_serve_tpot_seconds",
            "Time per output token (inter-token, first token excluded); "
            "tokens observed in one arrival share the arrival gap evenly",
            buckets=TPOT_BUCKETS)
        self.h_e2e = reg.histogram(
            "nos_tpu_serve_e2e_seconds",
            "Submit -> terminal per request (finished or cancelled)")
        self.m_slo = reg.counter(
            "nos_tpu_serve_slo_total",
            "Completed requests judged against the configured SLO "
            "targets, by slo (ttft | tpot) and outcome (met | breached)",
            ("slo", "outcome"))
        self.g_goodput = reg.gauge(
            "nos_tpu_serve_goodput_ratio",
            "Fraction of completed requests meeting every configured "
            "SLO target (0 until the first completion; absent when no "
            "SLO is configured)")
        # paged-KV block pool (registered only when the engine pages —
        # a slot-static server must not export dead zero series)
        self._preempt_seen = {"swap": 0, "recompute": 0}
        if getattr(engine, "paged", False):
            self.g_kv_free = reg.gauge(
                "nos_tpu_serve_kv_blocks_free",
                "Paged-KV blocks currently unreferenced (admission "
                "headroom)")
            self.g_kv_used = reg.gauge(
                "nos_tpu_serve_kv_blocks_used",
                "Paged-KV blocks referenced by at least one holder "
                "(slot tables + prefix index)")
            self.g_kv_shared = reg.gauge(
                "nos_tpu_serve_kv_blocks_cow_shared",
                "Paged-KV blocks referenced by MORE than one holder — "
                "each is a cache copy COW sharing avoided")
            self.m_preempt = reg.counter(
                "nos_tpu_serve_preempt_total",
                "Slots preempted under KV block pressure, by mode "
                "(swap = KV swapped to host and restored byte-exact; "
                "recompute = KV re-prefilled from the tokens)",
                ("mode",))
            for mode in ("swap", "recompute"):
                self.m_preempt.labels(mode).inc(0)
        # prefix-cache eviction tiers + KV fabric (ISSUE 17), both
        # registered whenever the engine has a paged prefix index —
        # evict_lru dropped chains SILENTLY before this, fabric on or
        # off, and a replica serves/adopts peer-pull chains even
        # without its own host tier. Engine-side events delta-mirror
        # (and reset with the _preempt_seen family on a supervised
        # engine swap); pull_hit/pull_miss are counted loop-side in
        # prefetch_chain.
        self._prefix_evict_seen = {"drop": 0, "demote": 0}
        self._fabric_seen = {"demote": 0, "promote": 0}
        if getattr(engine, "_pindex", None) is not None:
            self.m_prefix_evict = reg.counter(
                "nos_tpu_serve_prefix_evict_total",
                "Prefix chains evicted from the HBM index under block "
                "pressure, by tier (drop = thrown away — the next hit "
                "re-prefills; demote = swap payload captured into the "
                "host-RAM KV tier for bit-exact promotion later)",
                ("tier",))
            for tier in ("drop", "demote"):
                self.m_prefix_evict.labels(tier).inc(0)
            self.m_kvfabric = reg.counter(
                "nos_tpu_serve_kvfabric_total",
                "KV-fabric tier transitions, by event (demote = chain "
                "captured into the host tier instead of dropped; "
                "promote = chain scattered back into the arena on a "
                "prefix miss, bit-exact; pull_hit / pull_miss = "
                "gateway-offered peer chains adopted vs failed/"
                "rejected; pull_denied = kv_sources offers without "
                "the fleet's fabric token, never honored)",
                ("event",))
            for ev in ("demote", "promote", "pull_hit", "pull_miss",
                       "pull_denied"):
                self.m_kvfabric.labels(ev).inc(0)
        # speculative decoding (registered only on a speculative
        # engine — a plain decode server must not export dead zero
        # series): proposals drafted vs accepted by verify, plus the
        # accepted-per-verify-window distribution. accepted/draft is
        # the live acceptance rate; a sagging rate means the draft has
        # drifted from the traffic and speculation is burning draft
        # FLOPs for rollbacks.
        self._spec_seen = {"drafted": 0, "accepted": 0}
        if hasattr(engine, "spec_drafted"):
            self.m_spec_draft = reg.counter(
                "nos_tpu_serve_spec_draft_total",
                "Draft-model proposals submitted to verify windows "
                "(n_draft per round per active slot)")
            self.m_spec_accepted = reg.counter(
                "nos_tpu_serve_spec_accepted_total",
                "Draft proposals accepted by target verification; "
                "divided by nos_tpu_serve_spec_draft_total this is the "
                "live acceptance rate")
            self.h_spec_window = reg.histogram(
                "nos_tpu_serve_spec_accepted_per_window",
                "Accepted proposals per verify window (0..n_draft); "
                "mass near n_draft means speculation is paying, mass "
                "at 0 means the draft is guessing wrong",
                buckets=(0.0, 1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0))
            self.m_spec_draft.inc(0)
            self.m_spec_accepted.inc(0)
        # request-level elastic quota (registered only when tenancy is
        # configured — a single-tenant server must not export dead
        # per-tenant series). Labels are the CONFIGURED tenant names:
        # unknown wire tenants resolve to the default tenant, so
        # cardinality is operator-bounded, never client-controlled.
        self._tenant_cfg = tenant_quota
        self._tenant_of: dict = {}          # loop rid -> tenant label
        self._tenant_goodput: dict = {}     # label -> [judged, good]
        self._tenant_preempt_seen: dict = {}
        if tenant_quota is not None:
            self.m_tenant_tokens = reg.counter(
                "nos_tpu_serve_tenant_tokens_total",
                "Output tokens delivered per tenant — the goodput "
                "numerator the quota's min/max rates govern",
                ("tenant",))
            self.m_tenant_shed = reg.counter(
                "nos_tpu_serve_tenant_shed_total",
                "Admission sheds per tenant by machine-readable reason "
                "(tenant_quota = the tenant is at/over its own max "
                "token-rate under contention; queue_full / "
                "hbm_admission / deadline_unmeetable = the shared "
                "capacity reasons, attributed to the tenant that hit "
                "them)",
                ("tenant", "reason"))
            self.m_tenant_preempt = reg.counter(
                "nos_tpu_serve_tenant_preempt_total",
                "Slots preempted per (victim) tenant by mode (swap | "
                "recompute) — quota reclaim for a guaranteed tenant "
                "and block-pool pressure both count; every preemption "
                "resumes bit-exactly",
                ("tenant", "mode"))
            self.g_tenant_goodput = reg.gauge(
                "nos_tpu_serve_tenant_goodput_ratio",
                "Per-tenant goodput: finished-and-SLO-met requests "
                "over all server-judged terminal outcomes (finished, "
                "failed, deadline — client cancels excluded); with no "
                "SLO configured, finished requests count as good",
                ("tenant",))
            self.g_tenant_borrowed = reg.gauge(
                "nos_tpu_serve_tenant_borrowed_tokens_per_s",
                "Token-rate each tenant currently runs ABOVE its "
                "guaranteed min — the lent idle capacity the elastic "
                "quota exists to hand out (and reclaim)",
                ("tenant",))
            for t in tenant_quota.names():
                self.m_tenant_tokens.labels(t).inc(0)
                for mode in ("swap", "recompute"):
                    self.m_tenant_preempt.labels(t, mode).inc(0)
        # per-tenant SLO error budgets + chip-second attribution
        # (ISSUE 20): ON only when the tenant config carries ``slo``
        # objectives — an unconfigured fleet registers none of these
        # series and pays zero new per-tick work (the engine's ledger
        # is None too; the config echo's ``slo_accounting`` block is
        # the mode proof)
        self.slo_engine = None
        self._slo_clock = slo_clock or time.monotonic
        self._chip_cum_ns: dict = {}        # (tenant, phase) -> ns
        self._chip_seen_ns: dict = {}       # current engine's mirror
        self._chip_cum_kvbs: dict = {}      # tenant -> byte-seconds
        self._chip_seen_kvbs: dict = {}
        self._chip_cum_wall_ns = 0
        self._chip_seen_wall_ns = 0
        self._slo_targets: dict = {}        # tenant -> TenantSloSpec
        if tenant_quota is not None and tenant_quota.slo_enabled():
            self._slo_targets = {
                n: s.slo for n, s in tenant_quota.tenants.items()
                if s.slo is not None}
            self.slo_engine = SloBudgetEngine(
                objectives_from_quota(tenant_quota),
                fast_window_s=slo_fast_window_s,
                slow_window_s=slo_slow_window_s,
                burn_threshold=slo_burn_threshold,
                capture_interval_s=slo_capture_interval_s,
                min_events=slo_min_events)
            self.g_slo_budget = reg.gauge(
                "nos_tpu_serve_slo_budget_remaining_ratio",
                "Slow-window error budget left per (tenant, "
                "objective): 1 = untouched, 0 = exhausted "
                "(bad-event fraction at/over the objective's allowance)",
                ("tenant", "objective"))
            self.g_slo_burn = reg.gauge(
                "nos_tpu_serve_slo_burn_rate",
                "SRE multi-window burn rate per (tenant, objective, "
                "window = fast | slow): bad-event fraction over the "
                "window divided by the objective's error-budget "
                "allowance; a fast-window burn at/over the trip "
                "threshold emits an slo.breach span and pins the "
                "breaching request's trace",
                ("tenant", "objective", "window"))
            self.m_chip_ms = reg.counter(
                "nos_tpu_serve_tenant_chip_ms_total",
                "Engine wall milliseconds attributed per (tenant, "
                "phase = decode | prefill | idle): each quantum's "
                "measured duration split over its structural token "
                "weights, idle time under the _idle tenant — the "
                "ledger conserves (sum over series == engine wall "
                "time), across supervised engine swaps too "
                "(delta-mirrored)",
                ("tenant", "phase"))
            self.m_kv_byte_s = reg.counter(
                "nos_tpu_serve_tenant_kv_byte_seconds_total",
                "HBM byte-seconds of paged-KV residency per tenant "
                "(block-table + prefix-chain references; _shared = "
                "unscoped prefix chains), accrued over each engine "
                "quantum",
                ("tenant",))
            for t, objs in sorted(
                    self.slo_engine.objectives.items()):
                for obj in sorted(objs):
                    self.g_slo_budget.labels(t, obj).set(1.0)
                    for w in ("fast", "slow"):
                        self.g_slo_burn.labels(t, obj, w).set(0.0)
            for t in tenant_quota.names():
                for ph in ("decode", "prefill"):
                    self.m_chip_ms.labels(t, ph).inc(0)
                self.m_kv_byte_s.labels(t).inc(0)
            self.m_chip_ms.labels(IDLE_TENANT, "idle").inc(0)
        # prefill/decode disaggregation (registered only on a
        # prefill-role loop — colocated and decode servers must not
        # export dead zero series): handoffs shipped to the decode
        # pool by outcome, payload bytes per handoff (the int8-halves-
        # the-wire claim is readable straight off this histogram), and
        # capture+ship wall time per handoff
        self.role = role
        self._handoff_targets = list(handoff_targets or [])
        self._handoff_send = handoff_send
        self._handoff_rr = 0
        self._handoff_done: dict = {}       # loop rid -> descriptor
        self._handoff_gone: set = set()     # client departed pre-push
        # pusher health memory: a decode replica that refused/failed a
        # push is skipped for this cooldown window before being
        # retried (blind round-robin would keep burning an attempt on
        # a dead replica every lap). target url -> abs monotonic the
        # cooldown ends; cleared on the next successful push. When the
        # WHOLE pool is cooling the pusher falls back to probing every
        # target — a cooldown degrades to blind round-robin, never to
        # dropping the handoff.
        self._handoff_cooldown_s = handoff_cooldown_s or 0.0
        self._handoff_unhealthy: dict = {}  # target -> abs monotonic
        # pusher health VIEW (beyond the reactive cooldown above): at
        # a bounded cadence the pusher scrapes each decode target's
        # /stats so pushes prefer healthy, least-loaded replicas and a
        # draining/recovering replica is skipped BEFORE the first
        # failed attempt. ``pool_stats_fetch`` is the injectable
        # fetcher (target url -> parsed stats dict) so tests and
        # benches drive the view without sockets — same seam as
        # chain_fetch; None = the urllib default.
        self._handoff_health_interval_s = handoff_health_interval_s \
            or 0.0
        self.pool_stats_fetch = None
        self._pool_health: dict = {}    # target -> health row
        self._pool_health_at: Optional[float] = None
        # prefill-side deadline carry: the prefill server doesn't
        # ENFORCE deadlines (phase 1 is short; the decode side owns
        # expiry) but must not DROP them — the pusher attaches the
        # remaining seconds at ship time so the adopting decode
        # replica can shed expired phase-2 work early.
        self._prefill_deadlines: dict = {}  # loop rid -> abs monotonic
        # trace carry over the same seam: the prefill-side request
        # span's encoded context ships in the handoff meta plane so the
        # adopting decode replica's serve.request parents into the SAME
        # journey instead of minting a fresh trace_id
        self._prefill_traceparents: dict = {}   # loop rid -> traceparent
        # adopted-request TTL (decode role): an adopted handoff whose
        # consumer never shows up — the gateway crashed mid-resume, or
        # phase 2 exhausted its attempts — must not decode-and-park
        # forever. adopt() arms rid -> abs monotonic expiry here; a
        # consumer attach (result/watch) disarms it (the consumer's
        # own timeout/disconnect discipline owns the lifecycle from
        # there); _reap_orphans cancels whatever expires unclaimed.
        self._handoff_deadline: dict = {}   # loop rid -> abs monotonic
        self._adopt_ttl_s = adopt_ttl_s
        self._adopted: dict = {}            # loop rid -> prompt tokens
        # finished adopted results kept for re-fetch (same TTL): a
        # gateway retry of /v1/result after a socket timeout races the
        # abandoned first handler for the single engine pop — the
        # winner parks the tokens here so the loser still answers
        # instead of failing a fully-decoded request as "vanished"
        self._adopted_final: dict = {}      # loop rid -> full tokens
        # live _deltas consumers per rid: only the LAST one's teardown
        # forgets the request — an abandoned handler timing out must
        # not cancel the rid a retried resume is still attached to
        self._watchers: dict = {}           # loop rid -> consumer count
        if role == "prefill":
            self.m_handoff = reg.counter(
                "nos_tpu_serve_handoff_total",
                "Prefill->decode handoffs leaving this prefill-role "
                "server, by outcome (sent = adopted by a decode "
                "replica | failed = every decode-pool target refused "
                "or was unreachable; the request's one terminal "
                "outcome follows it)",
                ("outcome",))
            self.h_handoff_bytes = reg.histogram(
                "nos_tpu_serve_handoff_bytes",
                "KV payload bytes per handoff (quantized blocks + "
                "per-block scales under int8 — roughly half the bf16 "
                "bytes per request over DCN)",
                buckets=(1e3, 1e4, 1e5, 1e6, 1e7, 1e8, 1e9))
            self.h_handoff = reg.histogram(
                "nos_tpu_serve_handoff_seconds",
                "Wall time per handoff: KV swap-out capture plus the "
                "ship to the decode replica")
            self.m_handoff_skipped = reg.counter(
                "nos_tpu_serve_handoff_skipped_total",
                "Decode-pool targets skipped by the pusher: cooling "
                "down after a failed push (--handoff-cooldown-s) or "
                "reported draining/recovering by the scraped health "
                "view (--handoff-health-interval-s — skipped BEFORE "
                "the first failed attempt); a sustained rate means "
                "part of the decode pool is down or rolling")
            for outcome in ("sent", "failed"):
                self.m_handoff.labels(outcome).inc(0)
            self.m_handoff_skipped.inc(0)
        # budgeted chunked prefill (registered only when the engine
        # runs a per-tick budget — an unbudgeted loop must not export
        # dead zero series); mirrored seen-delta style like the
        # preempt/spec counters, reset on a supervised engine swap
        if getattr(engine, "prefill_budget", 0) > 0:
            self.m_psched_spent = reg.counter(
                "nos_tpu_serve_prefill_budget_tokens_total",
                "Prompt tokens of chunked prefill charged against the "
                "per-tick budget (--prefill-budget), including "
                "TTFT-critical overdraws")
            self.m_psched_clamp = reg.counter(
                "nos_tpu_serve_prefill_clamp_total",
                "Ticks the prefill budget clamped to zero because an "
                "active decode slot's TPOT slack went negative — "
                "decode drains first, prefill rides its banked credit")
            self.m_psched_override = reg.counter(
                "nos_tpu_serve_prefill_override_total",
                "Over-budget chunk forwards granted to a prefill "
                "whose TTFT slack was inside one decode tick (at most "
                "one per tick; the overdraw pays back from later "
                "budget)")
            self.m_psched_spent.inc(0)
            self.m_psched_clamp.inc(0)
            self.m_psched_override.inc(0)
        self._psched_seen = {"spent": 0, "clamped": 0, "overrides": 0}
        self.m_compiles = reg.counter(
            "nos_tpu_serve_compiles_total",
            "XLA compiles observed by the engine (first dispatch per "
            "shape: prefill buckets, decode program variants)")
        self.h_compile = reg.histogram(
            "nos_tpu_serve_compile_seconds",
            "Wall time of each first-dispatch-per-shape call (traces + "
            "compiles synchronously)",
            buckets=COMPILE_BUCKETS)
        # supervised-restart surface (registered only when a factory
        # makes restarts possible — a supervisor-less loop must not
        # export dead zero series)
        self._sup: Optional[EngineSupervisor] = None
        if engine_factory is not None:
            self._sup = EngineSupervisor(
                engine_factory, restart_budget=restart_budget,
                backoff_s=restart_backoff_s,
                backoff_max_s=restart_backoff_max_s, seed=seed)
            self.m_restarts = reg.counter(
                "nos_tpu_serve_engine_restarts_total",
                "Supervised engine restarts begun, by cause "
                "(step_error = a decode tick raised; watchdog = a tick "
                "exceeded --watchdog-s in flight)",
                ("cause",))
            self.m_resumed = reg.counter(
                "nos_tpu_serve_requests_resumed_total",
                "Requests resumed across an engine restart, by mode "
                "(swap = KV snapshot restored byte-exact; recompute = "
                "re-prefilled from the committed tokens — both "
                "bit-exact)",
                ("mode",))
            self.m_lost = reg.counter(
                "nos_tpu_serve_requests_lost_total",
                "Requests that could NOT be resumed across an engine "
                "restart (capture or restore failed); each is drained "
                "as outcome=failed exactly once")
            for cause in ("step_error", "watchdog"):
                self.m_restarts.labels(cause).inc(0)
            for mode in ("swap", "recompute"):
                self.m_resumed.labels(mode).inc(0)
            self.m_lost.inc(0)
        if watchdog_s > 0:
            # the watchdog works WITHOUT a supervisor too (a validated
            # trip is then a terminal failure — /healthz flips and the
            # pod restarts), so its counter keys on watchdog_s alone:
            # registered exactly when a trip is possible, no dead zero
            # series when the watchdog is off
            self.m_watchdog = reg.counter(
                "nos_tpu_serve_watchdog_trips_total",
                "Stuck-tick watchdog trips: a decode tick stayed "
                "blocked in its device wait past --watchdog-s with no "
                "arrival consumed (counted only when the trip is "
                "validated and starts the failure path)")
            self.m_watchdog.inc(0)
        self.engine = engine
        # /stats restart + drift detectors for the fleet controller: a
        # scrape whose uptime went BACKWARDS means the process (not
        # just the engine) restarted between scrapes — its empty rates
        # are a fresh ledger, not collapsed load — and the config echo
        # lets the controller spot a replica running drifted knobs
        # without shelling into the pod
        self._started = time.monotonic()
        self._config_echo = dict(config_echo) if config_echo else None
        self._slo_ttft_s = (slo_ttft_ms or 0.0) / 1e3
        self._slo_tpot_s = (slo_tpot_ms or 0.0) / 1e3
        self._goodput_done = 0
        self._goodput_good = 0
        self._spans: dict = {}          # rid -> serve.request span
        self._failed_drained: set = set()   # rids accounted as failed
        # rolling request/token rates for /stats: (monotonic t,
        # tokens_cum, finished_cum) appended per tick/completion,
        # pruned to the last RATE_WINDOW_S seconds
        self._rates: deque = deque()
        self._tokens_cum = 0
        self._finished_cum = 0
        # rolling TTFT samples over recent completions: /stats serves
        # the p99 the fleet controller's latency trigger reads (the
        # histogram buckets can't answer a percentile cheaply in-process)
        self._ttfts: deque = deque(maxlen=256)
        self._dev_interval = device_stats_interval_s or 0.0
        self._dev_next = 0.0
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._stop = False
        self._stop_event = threading.Event()    # wakes backoff/monitor
        self._draining = False
        self._failed: Optional[BaseException] = None
        self._abandoned: set = set()        # rids whose client timed out
        # recovery/deadline bookkeeping, all keyed by the ORIGINAL rid
        # a client holds (streams survive restarts; _rid_map translates
        # to the current engine's rid):
        self._recovering = False
        self._gen = 0               # ticker generation: bumped per
        #                             recovery so superseded (stuck)
        #                             ticker threads exit untouched
        self._tick_started: Optional[float] = None  # watchdog's clock
        self._watchdog_s = watchdog_s or 0.0
        # the LOOP owns the rid namespace a client holds: engines hand
        # out their own rids, and a rebuilt engine restarts its counter
        # — without the loop's own monotonic counter, a post-restart
        # submission could collide with a pre-restart stream's rid and
        # corrupt the map (caught by the chaos soak). Every admitted
        # request has an entry here; absent restarts the two sequences
        # advance in lockstep, so loop rid == engine rid numerically.
        self._next_rid = 0
        self._rid_map: dict = {}            # loop rid -> engine rid
        self._live: set = set()             # admitted, not yet terminal
        self._lost_rids: set = set()        # dropped in a restart
        self._default_deadline_s = default_deadline_s or 0.0
        self._deadlines: dict = {}          # orig rid -> abs monotonic
        self._deadline_hit: set = set()     # accounted outcome=deadline
        self._deadline_shed = 0             # shed at admission
        self._deadline_expired = 0          # cancelled mid-flight
        self._shed_streak = 0               # consecutive estimate sheds
        # rolling completion estimates feeding deadline admission
        # (EWMA over finished requests' ledgers; None until the first).
        # _est_out_tokens tracks how long requests ACTUALLY run:
        # max_new_tokens is routinely a ceiling (stop_tokens end most
        # requests early), and estimating against the ceiling would
        # systematically shed traffic that comfortably meets its
        # deadline.
        self._est_ttft_s: Optional[float] = None
        self._est_tpot_s: Optional[float] = None
        self._est_out_tokens: Optional[float] = None
        # KV-fabric peer pull: injectable fetcher (url -> payload
        # bytes) so tests/benches pull chains without a socket; None =
        # the urllib default in _fetch_chain_bytes. Pull outcomes are
        # loop-side counters (the engine only sees decoded payloads).
        # Pulls are single-flight per digest (_pull_inflight): a burst
        # of requests sharing one cold prefix rides the first fetch
        # instead of thundering-herding the peer's export path.
        self.chain_fetch = None
        self.chain_fetch_timeout_s = 2.0
        self.fabric_token = fabric_token or ""
        self._pull_lock = threading.Lock()
        self._pull_inflight: dict = {}      # digest -> flight record
        self._pull_counts = {"pull_hit": 0, "pull_miss": 0,
                             "pull_denied": 0}
        for outcome in OUTCOMES:        # export 0s, not absent series
            self.m_requests.labels(outcome).inc(0)
        self._mirror_engine_gauges()
        self._sample_device_stats()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        self._handoff_thread: Optional[threading.Thread] = None
        if role == "prefill" and self._handoff_send is not None:
            self._handoff_thread = threading.Thread(
                target=self._push_handoffs, daemon=True)
            self._handoff_thread.start()
        self._monitor_thread: Optional[threading.Thread] = None
        if self._watchdog_s > 0:
            # no supervisor needed: without one, a validated trip goes
            # terminal (_recover routes to _fail) — strictly better
            # than a silently wedged loop with a green /healthz
            self._monitor_thread = threading.Thread(
                target=self._monitor, daemon=True)
            self._monitor_thread.start()
        self._orphan_thread: Optional[threading.Thread] = None
        if role == "decode" and adopt_ttl_s > 0:
            self._orphan_thread = threading.Thread(
                target=self._reap_orphans, daemon=True)
            self._orphan_thread.start()

    @property
    def healthy(self) -> bool:
        return self._failed is None

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def recovering(self) -> bool:
        """True while the supervisor is mid-restart: submissions get
        503 + Retry-After and /readyz reports ``degraded`` (the Service
        pulls the endpoint until the rebuilt engine is serving)."""
        return self._recovering

    def begin_drain(self) -> None:
        """Stop admitting; in-flight requests keep decoding. The k8s
        termination sequence: SIGTERM → readiness flips (Service stops
        routing here) → new submits 503 → wait_idle → exit."""
        with self._work:
            self._draining = True
            self._work.notify_all()

    def cancel_drain(self) -> None:
        """Resume admitting after a drain that is NOT followed by
        termination (an operator reverting a mistaken or unwanted
        POST /admin/drain — the drain endpoint shares the serving
        port's trust domain, so reversibility is the recovery path)."""
        with self._work:
            self._draining = False
            self._work.notify_all()

    def wait_idle(self, timeout: float) -> bool:
        """Block until the engine has no queued or decoding work (or
        ``timeout``/loop death). Returns True when fully drained."""
        deadline = time.monotonic() + timeout
        with self._work:
            while self.engine.has_work():
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._failed is not None \
                        or self._stop:
                    return not self.engine.has_work()
                self._work.wait(timeout=min(remaining, 1.0))
            return True

    def _fail(self, e: BaseException) -> None:
        """Mark the loop TERMINALLY dead (caller holds the lock):
        /healthz flips BEFORE the single notify_all, so every
        wait_idle/stream waiter — re-checking under this same lock —
        observes healthy == False by the time it returns. Exactly one
        wakeup; the ticker thread exits right after. Abandoned requests
        are drained as ``failed`` here: the ticker that would have
        reaped them is the thing dying, so nothing else will ever
        account for them. Reached directly when no supervisor is
        configured, or from _recover once the restart budget is
        exhausted / shutdown cancels a recovery."""
        logger.error("serving loop terminally failed: %s", e,
                     exc_info=e)
        self._failed = e
        for rid in self._abandoned:
            erid = self._rid_map.get(rid)
            self._account(rid, "failed",
                          self._pop_ledger(erid)
                          if erid is not None else None)
            self._failed_drained.add(rid)
        self._abandoned.clear()
        self._work.notify_all()

    # -- request-level accounting (the latency ledger's consumer) -------
    def _pop_ledger(self, rid: int) -> Optional[dict]:
        pop = getattr(self.engine, "pop_ledger", None)
        return pop(rid) if pop is not None else None

    def _account(self, rid: int, outcome: str,
                 ledger: Optional[dict]) -> None:
        """Terminal accounting for ONE request (caller holds the lock):
        increments exactly one requests_total outcome, feeds the
        TTFT/TPOT/queue/e2e histograms from the engine's ledger, judges
        the SLO targets, and closes the request's serve.request span —
        an SLO breach marks the span and pins its trace in the flight
        recorder, so a breached counter always has a trace to open."""
        self.m_requests.labels(outcome).inc()
        self._live.discard(rid)
        self._deadlines.pop(rid, None)
        self._prefill_deadlines.pop(rid, None)
        self._prefill_traceparents.pop(rid, None)
        self._rid_map.pop(rid, None)
        # an adopted (decode-role) request's prompt leaves with its
        # terminal outcome: the streaming attach path never calls
        # result(), so accounting is the one hook both paths share
        self._adopted.pop(rid, None)
        self._handoff_deadline.pop(rid, None)
        sp = self._spans.pop(rid, None)
        tid = (sp.trace_id or None) if sp is not None else None
        breaches = []
        decode_tokens = 0
        gap_sum = 0.0
        if ledger:
            if ledger.get("queue_s") is not None:
                self.h_queue.observe(max(0.0, ledger["queue_s"]),
                                     trace_id=tid)
            ttft = ledger.get("ttft_s")
            if ttft is not None:
                self.h_ttft.observe(ttft, trace_id=tid)
                if outcome == "finished":
                    self._ttfts.append(ttft)
            for gap, n in ledger.get("tpot") or ():
                # one weighted observe per arrival: n tokens sharing the
                # arrival gap must not pay n bucket walks under the lock
                self.h_tpot.observe(gap / n, trace_id=tid, count=n)
                decode_tokens += n
                gap_sum += gap
            if ledger.get("e2e_s") is not None:
                self.h_e2e.observe(ledger["e2e_s"], trace_id=tid)
            if outcome == "finished":
                # rolling completion estimates for deadline admission:
                # EWMA, cheap and recency-weighted — an estimate that
                # lags a load spike sheds a little late, never forever
                if ttft is not None:
                    self._est_ttft_s = ttft if self._est_ttft_s is None \
                        else 0.8 * self._est_ttft_s + 0.2 * ttft
                if decode_tokens:
                    tpot = gap_sum / decode_tokens
                    self._est_tpot_s = tpot if self._est_tpot_s is None \
                        else 0.8 * self._est_tpot_s + 0.2 * tpot
                out_toks = ledger.get("output_tokens") or 0
                if out_toks:
                    self._est_out_tokens = float(out_toks) \
                        if self._est_out_tokens is None \
                        else 0.8 * self._est_out_tokens + 0.2 * out_toks
            if outcome == "finished" \
                    and (self._slo_ttft_s or self._slo_tpot_s):
                good = True
                if self._slo_ttft_s and ttft is not None:
                    met = ttft <= self._slo_ttft_s
                    self.m_slo.labels(
                        "ttft", "met" if met else "breached").inc()
                    if not met:
                        good = False
                        breaches.append("ttft")
                if self._slo_tpot_s and decode_tokens:
                    met = gap_sum / decode_tokens <= self._slo_tpot_s
                    self.m_slo.labels(
                        "tpot", "met" if met else "breached").inc()
                    if not met:
                        good = False
                        breaches.append("tpot")
                self._goodput_done += 1
                if good:
                    self._goodput_good += 1
                self.g_goodput.set(
                    self._goodput_good / self._goodput_done)
        slo_trips: list = []
        slo_tenant = None
        if self._tenant_cfg is not None:
            t = self._tenant_of.pop(
                rid, self._tenant_cfg.default_tenant)
            slo_tenant = t
            slo_trips = self._judge_tenant_slo(
                t, outcome, ledger, decode_tokens, gap_sum)
            if ledger and ledger.get("output_tokens"):
                self.m_tenant_tokens.labels(t).inc(
                    ledger["output_tokens"])
            if outcome in ("finished", "failed", "deadline"):
                # per-tenant goodput over SERVER-judged outcomes: a
                # client walking away (cancelled/abandoned) is not a
                # quality verdict on the quota, a shed/failure/breach
                # is. No SLO targets -> finishing IS good.
                gp = self._tenant_goodput.setdefault(t, [0, 0])
                gp[0] += 1
                if outcome == "finished" and not breaches:
                    gp[1] += 1
                self.g_tenant_goodput.labels(t).set(gp[1] / gp[0])
        if sp is not None and sp.recording:
            sp.set_attr("outcome", outcome)
            if ledger:
                if ledger.get("ttft_s") is not None:
                    sp.set_attr("ttft_ms",
                                round(ledger["ttft_s"] * 1e3, 3))
                if ledger.get("queue_s") is not None:
                    sp.set_attr(
                        "queue_ms",
                        round(max(0.0, ledger["queue_s"]) * 1e3, 3))
                sp.set_attr("output_tokens",
                            ledger.get("output_tokens", 0))
            if breaches:
                sp.set_attr("slo_breach", ",".join(breaches))
                tracing.recorder().pin(sp.trace_id, "slo")
            if slo_trips:
                # fast-window burn trip (ISSUE 20): mint the
                # registry-linted slo.breach span under the breaching
                # request and pin its stitched trace ONCE — the budget
                # engine's per-(tenant, objective) capture interval is
                # the rate limit keeping a sustained breach from
                # wedging the flight recorder
                for obj in slo_trips:
                    bsp = tracing.start_span(
                        "slo.breach", component="server", parent=sp,
                        attrs={"tenant": slo_tenant, "objective": obj,
                               "burn_threshold":
                                   self.slo_engine.burn_threshold})
                    bsp.end()
                # pin through the tracer's ACTIVE recorder — the same
                # sink the request's spans landed in
                rec = tracing.tracer().recorder
                if rec is not None:
                    rec.pin(sp.trace_id, "slo_burn")
            sp.end()
        if outcome in ("finished", "abandoned"):
            self._finished_cum += 1
            self._note_rates()

    def _judge_tenant_slo(self, tenant: str, outcome: str,
                          ledger: Optional[dict], decode_tokens: int,
                          gap_sum: float) -> list:
        """Feed one terminal request into the tenant's error-budget
        windows (ISSUE 20) and refresh its burn/budget gauges. TTFT and
        TPOT objectives judge finished requests against the tenant's
        p99 targets; the goodput objective judges every server-decided
        outcome (finished good, failed/deadline bad — client cancels
        are not a quality verdict, same convention as the tenant
        goodput gauge). Returns the objectives whose fast window
        TRIPPED on this event (rate-limited by the engine)."""
        if self.slo_engine is None:
            return []
        targets = self._slo_targets.get(tenant)
        tracked = self.slo_engine.tracked(tenant)
        if targets is None or not tracked:
            return []
        now = self._slo_clock()
        trips = []
        judged = False
        if outcome == "finished" and ledger:
            ttft = ledger.get("ttft_s")
            if "ttft_p99" in tracked and ttft is not None:
                bad = ttft > targets.ttft_p99_ms / 1e3
                if self.slo_engine.note(tenant, "ttft_p99", bad, now):
                    trips.append("ttft_p99")
                judged = True
            if "tpot_p99" in tracked and decode_tokens:
                bad = gap_sum / decode_tokens \
                    > targets.tpot_p99_ms / 1e3
                if self.slo_engine.note(tenant, "tpot_p99", bad, now):
                    trips.append("tpot_p99")
                judged = True
        if "goodput" in tracked \
                and outcome in ("finished", "failed", "deadline"):
            bad = outcome != "finished"
            if self.slo_engine.note(tenant, "goodput", bad, now):
                trips.append("goodput")
            judged = True
        if judged:
            for row in self.slo_engine.rows(now):
                if row["tenant"] != tenant:
                    continue
                obj = row["objective"]
                self.g_slo_budget.labels(tenant, obj).set(
                    row["budget_remaining_ratio"])
                self.g_slo_burn.labels(tenant, obj, "fast").set(
                    row["burn_fast"])
                self.g_slo_burn.labels(tenant, obj, "slow").set(
                    row["burn_slow"])
        return trips

    def _note_rates(self) -> None:
        """Append a (t, tokens, requests) mark and prune the rolling
        window — /stats reads request/token rates from the ends."""
        now = time.monotonic()
        self._rates.append((now, self._tokens_cum, self._finished_cum))
        cutoff = now - RATE_WINDOW_S
        while len(self._rates) > 1 and self._rates[0][0] < cutoff:
            self._rates.popleft()

    def _note_tick_phases(self, t0: float, t1: float, t2: float,
                          t3: float, t4: float, eng,
                          tid: Optional[str] = None) -> None:
        """Decompose one tick into TICK_PHASES from the clock reads the
        quantum already takes plus the two post-wait reads (caller
        holds the lock). ``eng`` is the split-protocol engine — its
        ``last_assemble_s`` splits step_begin into assemble vs device
        dispatch — or None for step()-only engines, whose whole step
        lands under ``dispatch``. ``sample`` covers step_finish plus
        the loop-lock reacquisition after the device wait."""
        if eng is not None:
            begin = max(0.0, t1 - t0)
            assemble = max(
                0.0, float(getattr(eng, "last_assemble_s", 0.0) or 0.0))
            assemble = min(assemble, begin)
            phases = {
                "assemble": assemble,
                "dispatch": begin - assemble,
                "wait": max(0.0, t2 - t1),
                "sample": max(0.0, t3 - t2),
                "bookkeep": max(0.0, t4 - t3),
            }
            if getattr(eng, "prefill_budget", 0) > 0:
                # the budgeted prefill scheduler's TPOT cost model
                # samples the decode half of the tick (assemble +
                # dispatch + wait); step_finish — which runs the
                # prefill chunks themselves — is excluded so prefill
                # work cannot inflate its own clamp threshold
                eng.note_tick_seconds(max(0.0, t2 - t0))
        else:
            phases = {
                "assemble": 0.0,
                "dispatch": max(0.0, t1 - t0),
                "wait": 0.0,
                "sample": 0.0,
                "bookkeep": max(0.0, t4 - t1),
            }
        for ph, v in phases.items():
            self.h_tick_phase.labels(ph).observe(v, trace_id=tid)
        self._tick_phases.append((t0, phases))

    def _tick_phase_snapshot(self) -> dict:
        """Rolling per-phase totals over the ring window for /stats
        (caller holds the lock): where recent tick time went, without
        scraping histogram buckets."""
        totals = {ph: 0.0 for ph in TICK_PHASES}
        for _t, phases in self._tick_phases:
            for ph, v in phases.items():
                totals[ph] += v
        return {
            "window": len(self._tick_phases),
            "seconds": {ph: round(v, 6) for ph, v in totals.items()},
        }

    def profile_trace(self, last_n: int = 64) -> dict:
        """Chrome trace-event JSON of the last N decode ticks, each
        tick a slice with its phase children — the /debug/profile
        payload, rendered by obs/trace_export.to_chrome_trace. The
        synthesized spans share ONE fixed valid-hex trace id so every
        tick lands on the same Perfetto lane, and none feed the flight
        recorder (constructed with _tracer=None)."""
        from nos_tpu.obs.trace_export import to_chrome_trace
        from nos_tpu.obs.tracing import Span, _new_span_id
        with self._lock:
            ticks = list(self._tick_phases)[-max(1, int(last_n)):]
        if not ticks:
            return {"traceEvents": [],
                    "displayTimeUnit": "ms"}
        tid = "70726f66696c6500" + "0" * 16   # "profile" in hex, padded
        spans = []
        for i, (t0, phases) in enumerate(ticks):
            root = Span("serve.tick", "server", tid, _new_span_id(),
                        None, t0, attrs={"tick": i}, _tracer=None)
            cursor = t0
            for ph in TICK_PHASES:
                dur = phases.get(ph, 0.0)
                if dur <= 0.0:
                    continue
                child = Span("tick." + ph, "server", tid,
                             _new_span_id(), root.span_id, cursor,
                             _tracer=None)
                child.end(end_time=cursor + dur)
                cursor += dur
                spans.append(child)
            root.end(end_time=max(cursor, t0))
            spans.append(root)
        return to_chrome_trace(spans)

    def _drain_compile_events(self) -> None:
        """Engine-side compile accounting -> metrics (caller holds the
        lock; the engine appends events under the same lock)."""
        events = getattr(self.engine, "compile_events", None)
        if events:
            self.engine.compile_events = []
            for dt in events:
                self.m_compiles.inc()
                self.h_compile.observe(dt)

    def _sample_device_stats(self) -> None:
        """Bounded-cadence device-runtime telemetry: HBM bytes in
        use/limit per local device via device.memory_stats(). Guarded —
        the CPU backend (and any backend without memory stats) just
        never exports the gauges; a telemetry failure must never take
        the serving loop down."""
        if self._dev_interval <= 0:
            return
        now = time.monotonic()
        if now < self._dev_next:
            return
        self._dev_next = now + self._dev_interval
        try:
            import jax

            devices = jax.devices()
        except Exception:
            self._dev_interval = 0.0    # no runtime: stop trying
            return
        reg = default_registry()
        for d in devices:
            try:
                stats = d.memory_stats()
            except Exception:
                stats = None
            if not stats:
                continue
            label = f"{d.platform}:{d.id}"
            in_use = stats.get("bytes_in_use")
            if in_use is not None:
                reg.gauge(
                    "nos_tpu_device_hbm_bytes_in_use",
                    "Device memory (HBM) bytes currently allocated, per "
                    "local device (absent on backends without "
                    "memory_stats, e.g. CPU)",
                    ("device",)).labels(label).set(in_use)
            limit = stats.get("bytes_limit") \
                or stats.get("bytes_reservable_limit")
            if limit:
                reg.gauge(
                    "nos_tpu_device_hbm_bytes_limit",
                    "Device memory (HBM) byte capacity, per local device",
                    ("device",)).labels(label).set(limit)

    def stats(self) -> dict:
        """The /stats snapshot: the engine's live introspection (slots,
        pending queue, pipeline window, prefix cache, compiles) plus
        loop-level health, SLO/goodput state and rolling rates."""
        with self._work:
            engine_stats = getattr(self.engine, "stats", None)
            snap = dict(engine_stats()) if engine_stats is not None \
                else {}
            if "slots" not in snap:
                occupancy = getattr(self.engine, "occupancy", None)
                if occupancy is not None:
                    active, pending = occupancy()
                    snap["active_slots"] = active
                    snap["pending"] = {"depth": pending}
            elif "active_slots" not in snap:
                # normalize: the engine reports a per-slot LIST; scrape
                # consumers (the fleet controller's drain-idle check)
                # need the count under one key whatever the engine
                snap["active_slots"] = len(snap["slots"])
            # rates age against NOW, not the last mark: marks are only
            # appended on ticks/completions, so an idle server's window
            # must decay to zero here rather than freeze at the last
            # active minute's throughput
            now = time.monotonic()
            window = [m for m in self._rates
                      if m[0] >= now - RATE_WINDOW_S]
            if window and now > window[0][0]:
                dt = now - window[0][0]
                rates = {
                    "window_s": round(dt, 3),
                    "tokens_per_s": round(
                        (self._tokens_cum - window[0][1]) / dt, 3),
                    "requests_per_s": round(
                        (self._finished_cum - window[0][2]) / dt, 3),
                }
            else:
                rates = {"window_s": 0.0, "tokens_per_s": 0.0,
                         "requests_per_s": 0.0}
            snap.update({
                "healthy": self.healthy,
                "draining": self._draining,
                "recovering": self._recovering,
                "uptime_s": round(now - self._started, 3),
                "config": self._config_echo or {},
                "per_request": {
                    "window": len(self._ttfts),
                    "ttft_p99_s": (
                        round(sorted(self._ttfts)[
                            min(len(self._ttfts) - 1,
                                math.ceil(0.99 * len(self._ttfts)) - 1)],
                            6)
                        if self._ttfts else None),
                },
                "supervisor": (
                    dict(self._sup.stats(),
                         watchdog_s=self._watchdog_s)
                    if self._sup is not None else None),
                "deadline": {
                    "default_s": self._default_deadline_s,
                    "active": len(self._deadlines),
                    "shed": self._deadline_shed,
                    "expired": self._deadline_expired,
                    "est_ttft_s": (round(self._est_ttft_s, 6)
                                   if self._est_ttft_s is not None
                                   else None),
                    "est_tpot_s": (round(self._est_tpot_s, 6)
                                   if self._est_tpot_s is not None
                                   else None),
                },
                "slo": {
                    "ttft_ms": round(self._slo_ttft_s * 1e3, 3),
                    "tpot_ms": round(self._slo_tpot_s * 1e3, 3),
                    "completed": self._goodput_done,
                    "goodput": (round(self._goodput_good
                                      / self._goodput_done, 4)
                                if self._goodput_done else None),
                },
                "rates": rates,
                # KV-fabric peer-pull outcomes (loop-side: the engine
                # only sees decoded payloads, never fetches)
                "kv_fabric_pulls": dict(self._pull_counts),
                "tick_phases": self._tick_phase_snapshot(),
                # ISSUE 20: None when SLO accounting is off — the
                # stable-key contract the /stats drift guard pins
                "slo_budget": (
                    self.slo_engine.snapshot(self._slo_clock())
                    if self.slo_engine is not None else None),
                "chip_ledger": self._chip_ledger_block(),
            })
        return snap

    def _run(self) -> None:
        """Ticker thread: one ``_run_quantum`` per scheduling quantum
        until stopped, terminally failed, superseded by a recovery
        (generation bump), or handed off INTO a recovery (an engine
        failure — _recover spawns the successor ticker itself)."""
        with self._work:
            gen = self._gen
        while self._run_quantum(gen):
            pass

    def _run_quantum(self, gen: int) -> bool:
        # engines exposing the split-step protocol (DecodeServer) run
        # the blocking device wait OUTSIDE the condition lock, so
        # handlers submit/stream/cancel while the device computes;
        # step()-only engines (test stubs) tick under the lock as
        # before. The engine reference is snapshotted per quantum: a
        # watchdog recovery swaps self.engine while this thread is
        # blocked in step_wait, and a superseded thread must only ever
        # touch the OLD engine — then exit on the generation check.
        failure = None
        with self._work:
            # also exit on terminal failure: the watchdog monitor can
            # _fail the loop while this thread is blocked — without
            # this check a revived ticker would keep dispatching
            # device work against a loop /healthz already reports dead
            if self._gen != gen or self._failed is not None:
                return False
            while not self._stop and not self.engine.has_work():
                self._work.wait()
                if self._gen != gen or self._failed is not None:
                    return False
            if self._stop:
                return False
            eng = self.engine
            split = hasattr(eng, "step_begin") \
                and hasattr(eng, "step_wait") \
                and hasattr(eng, "step_finish")
            t0 = time.monotonic()
            sp = tracing.start_span("serve.tick", component="server")
            handle = None
            emitted = 0
            gap0 = getattr(eng, "dispatch_gap_s", None)
            try:
                if split:
                    handle = eng.step_begin()
                    # the watchdog arms for the BLOCKING wait phase
                    # only: step_begin compiles synchronously under
                    # this lock on a first dispatch — seconds of XLA
                    # work that must not read as a stuck tick (and a
                    # hang there holds the lock, which no watchdog can
                    # recover anyway). What the watchdog guards is the
                    # device wait below — the phase a lost device
                    # actually wedges.
                    t1 = time.monotonic()
                    self._tick_started = t1
                else:
                    emitted = eng.step()
                    t1 = time.monotonic()
            except BaseException as e:
                sp.end()
                self._tick_started = None
                failure = e
        if failure is not None:
            self._recover(failure, "step_error", gen)
            return False
        t2 = t1
        if split:
            # the only blocking device wait — lock released, so a
            # concurrent submit's barrier flush may consume the
            # handle under us (step_finish is idempotent on it)
            try:
                eng.step_wait(handle)
            except BaseException as e:
                with self._work:
                    sp.end()
                    if self._gen != gen:
                        return False    # superseded while blocked
                    self._tick_started = None
                self._recover(e, "step_error", gen)
                return False
            t2 = time.monotonic()
        with self._work:
            if self._gen != gen or self._failed is not None:
                # superseded while blocked (watchdog recovery took the
                # loop over — or failed it terminally): this thread's
                # tick belongs to the discarded engine and must not
                # touch loop state
                sp.end()
                return False
            t3 = t2
            try:
                if split:
                    emitted = eng.step_finish(handle)
                    t3 = time.monotonic()
                    if gap0 is not None:
                        # the engine's structural gap counter: time
                        # this tick's window sat empty with work
                        # pending (ended by step_begin's dispatch)
                        self.h_gap.observe(
                            eng.dispatch_gap_s - gap0,
                            trace_id=sp.trace_id or None)
                self._tick_started = None
                self.m_ticks.inc()
                self.m_tokens.inc(emitted)
                self._tokens_cum += emitted
                self._note_rates()
                self._mirror_engine_gauges()
                self._sample_device_stats()
                self._sweep_deadlines()
                # reap results whose client already gave up, so
                # _done can't grow from timed-out requests. Inside
                # the try: a failure here (engine died mid-reap)
                # must flip /healthz and wake waiters like any
                # other tick failure, not kill the ticker silently
                for rid in list(self._abandoned):
                    # no identity fallback: once _account popped the
                    # map, the bare rid may alias a DIFFERENT
                    # post-restart request with the same engine rid
                    erid = self._rid_map.get(rid)
                    if erid is None:
                        self._abandoned.discard(rid)
                        continue
                    ledger = self._pop_ledger(erid)
                    if self.engine.pop_result(erid) is not None:
                        self._abandoned.discard(rid)
                        # completed work, even if nobody is waiting
                        self._account(rid, "abandoned", ledger)
                    elif self.engine.progress(erid) is None:
                        # the engine no longer knows the request at
                        # all (its cancel dropped it outright): no
                        # result will ever be poppable — resolve it
                        # NOW, or it never earns its exactly-one
                        # terminal outcome
                        self._abandoned.discard(rid)
                        self._account(rid, "cancelled", ledger)
            except BaseException as e:
                sp.end()
                self._tick_started = None
                failure = e
            else:
                sp.end()
                t4 = time.monotonic()
                self.h_tick.observe(t4 - t0,
                                    trace_id=sp.trace_id or None)
                chip_note = getattr(eng, "chip_note_quantum", None)
                if chip_note is not None:
                    # the attribution ledger charges the quantum with
                    # the SAME two reads the tick profiler pays —
                    # no-op unless SLO accounting is configured
                    chip_note(t0, t4)
                self._note_tick_phases(t0, t1, t2, t3, t4,
                                       eng if split else None,
                                       tid=sp.trace_id or None)
                self._work.notify_all()  # wake waiters to check results
        if failure is not None:
            self._recover(failure, "step_error", gen)
            return False
        return True

    # -- supervised recovery (the tentpole) -----------------------------
    def _recover(self, exc: BaseException, cause: str, gen: int,
                 stuck_since: Optional[float] = None) -> None:
        """Safety shell around the recovery state machine: anything —
        BaseException included — escaping it must flip /healthz, never
        strand the loop with ``_recovering`` stuck True behind a green
        liveness probe (the self-healing path's own worst failure
        mode). The tick seams deliberately catch BaseException for
        device-runtime weirdness; the rebuild path deserves the same
        skepticism."""
        try:
            self._do_recover(exc, cause, gen, stuck_since)
        except BaseException as e:  # noqa: BLE001 — see docstring
            with self._work:
                self._recovering = False
                if self._failed is None:
                    self._fail(e)
            raise

    def _do_recover(self, exc: BaseException, cause: str, gen: int,
                    stuck_since: Optional[float] = None) -> None:
        """Engine failure → supervised restart, or terminal _fail when
        out of budget / no supervisor / shutting down. Runs on the
        failing ticker thread (step_error) or the watchdog monitor
        (cause=watchdog, with the stuck ticker still blocked); either
        way it ends by spawning a FRESH ticker thread on success, and
        the calling thread exits. The lock is dropped around backoff +
        rebuild (seconds of XLA compile): handlers keep answering —
        503 + Retry-After for submits, degraded /readyz — while
        /healthz stays green."""
        with self._work:
            if self._gen != gen or self._failed is not None:
                return                  # superseded / already terminal
            if stuck_since is not None \
                    and self._tick_started != stuck_since:
                return  # the "stuck" tick landed between detection
                #         and here: nothing to recover
            if cause == "watchdog":
                # counted only HERE, after the gen/stuck validation: a
                # trip aborted by the race window must not read as a
                # phantom stuck tick in the metric
                self.m_watchdog.inc()
            if self._sup is None or self._stop \
                    or not self._sup.can_restart():
                self._fail(exc)
                return
            t_fail = time.monotonic()
            self._gen += 1
            gen = self._gen
            self._recovering = True
            self._tick_started = None
            self.m_restarts.labels(cause).inc()
            attempt = self._sup.note_attempt()
            logger.warning(
                "engine failure (%s: %s); supervised restart, attempt "
                "%d/%d", cause, exc, attempt + 1,
                self._sup.restart_budget)
            # engine-rid -> loop-rid, snapshotted NOW while every live
            # captured request still has its map entry: entries popped
            # during the unlocked capture/rebuild window (deadline
            # expiry, a finishing stream) would otherwise make the
            # restore pass fall back to the ENGINE rid — the wrong
            # namespace after the first restart, aliasing other
            # requests
            cur_to_orig = {v: k for k, v in self._rid_map.items()}
            eng = self.engine
            self._work.notify_all()
        # -- no lock: capture. The engine is quiescent (ticker
        # superseded by the gen bump, submits rejected, cancels
        # skipped while recovering) and capture is read-only over
        # list()-snapshots, so handlers observe _recovering and answer
        # their fast 503 instead of stalling behind this. A
        # watchdog-declared-wedged device is not read AT ALL (host
        # state only; every slot resumes by recompute); for step_error
        # the swap snapshot is worth attempting, but its device->host
        # copies can HANG on a genuinely lost device (guards catch
        # exceptions, not hangs) — so it runs on a helper thread
        # bounded by CAPTURE_TIMEOUT_S, falling back to a host-only
        # capture on expiry. The abandoned hung thread races nothing.
        if cause == "watchdog":
            captured = self._sup.capture(eng, device_ok=False)
        else:
            box: dict = {}

            def _cap():
                box["states"] = self._sup.capture(eng, device_ok=True)

            ct = threading.Thread(target=_cap, daemon=True)
            ct.start()
            ct.join(timeout=CAPTURE_TIMEOUT_S)
            captured = box.get("states")
            if captured is None:
                logger.warning(
                    "swap capture hung > %.0fs (device lost?); "
                    "falling back to host-only capture — every "
                    "slot resumes by recompute", CAPTURE_TIMEOUT_S)
                captured = self._sup.capture(eng, device_ok=False)
        # -- no lock: backoff, then rebuild (compiles) ------------------
        new_engine = None
        while True:
            self._stop_event.wait(self._sup.backoff_delay(attempt))
            if self._stop:
                break
            try:
                new_engine = self._sup.build()
                break
            except Exception:
                logger.exception("engine rebuild failed")
                if not self._sup.can_restart():
                    break
                attempt = self._sup.note_attempt()
        with self._work:
            if new_engine is None or self._stop:
                # budget exhausted — or shutdown() cancelled the
                # recovery: drain every captured request as ``failed``
                # exactly once (nothing will ever decode them), then
                # die terminally. _failed_drained dedupes against the
                # stream-teardown _forget path.
                for st in captured:
                    orig = cur_to_orig.get(st["rid"], st["rid"])
                    if st.get("done") or orig not in self._live \
                            or orig in self._failed_drained \
                            or orig in self._deadline_hit:
                        continue
                    self._failed_drained.add(orig)
                    self._abandoned.discard(orig)
                    self._account(orig, "failed", None)
                self._recovering = False
                self._fail(exc)
                return
            self.engine = new_engine
            self._preempt_seen = {"swap": 0, "recompute": 0}
            self._spec_seen = {"drafted": 0, "accepted": 0}
            self._tenant_preempt_seen = {}
            self._psched_seen = {"spent": 0, "clamped": 0,
                                 "overrides": 0}
            # the rebuilt engine's eviction/fabric counters start at 0
            # (and its host tier starts empty): reset the mirrors or
            # the deltas would go negative and freeze the counters
            self._prefix_evict_seen = {"drop": 0, "demote": 0}
            self._fabric_seen = {"demote": 0, "promote": 0}
            # the rebuilt engine's attribution ledger restarts at zero:
            # reset the chip mirrors (the cumulative totals keep the
            # old engine's charges — conservation holds across swaps)
            self._chip_seen_ns = {}
            self._chip_seen_kvbs = {}
            self._chip_seen_wall_ns = 0
            resumed = {"swap": 0, "recompute": 0}
            lost = 0
            seen = set()
            now = time.monotonic()
            for st in captured:
                orig = cur_to_orig.get(st["rid"], st["rid"])
                seen.add(orig)
                self._rid_map.pop(orig, None)
                if orig not in self._live \
                        or orig in self._deadline_hit \
                        or orig in self._failed_drained:
                    # already terminally accounted — a deadline that
                    # expired mid-recovery, a drained failure, or a
                    # done-state whose stream popped its result during
                    # the rebuild window: nothing left to restore (and
                    # re-parking it would leak an unreachable result
                    # plus a stale rid mapping into the fresh engine)
                    continue
                if orig in self._abandoned and not st.get("done"):
                    # the client walked away mid-recovery: don't burn
                    # the rebuilt engine on it
                    self._abandoned.discard(orig)
                    self._account(orig, "cancelled", None)
                    continue
                dl = self._deadlines.get(orig)
                if dl is not None and now > dl and not st.get("done"):
                    # its deadline expired during the outage: shed now
                    self._deadline_hit.add(orig)
                    self._deadline_expired += 1
                    self._account(orig, "deadline", None)
                    continue
                try:
                    nrid, mode = self._sup.restore(new_engine, st)
                except Exception as e:
                    logger.warning("request %s lost in engine restart: "
                                   "%s", orig, e)
                    self._lost_rids.add(orig)
                    self.m_lost.inc()
                    lost += 1
                    self._abandoned.discard(orig)
                    self._account(orig, "failed", None)
                    continue
                self._rid_map[orig] = nrid
                if st.get("done"):
                    continue            # a parked result, not a resume
                resumed[mode] += 1
                self.m_resumed.labels(mode).inc()
                sp = self._spans.get(orig)
                if sp is not None and sp.recording:
                    # the restart episode, parented into the resumed
                    # request's own trace — and pinned, so an operator
                    # can open every request a restart touched
                    rsp = tracing.start_span(
                        "serve.recover", component="server", parent=sp,
                        attrs={"cause": cause, "mode": mode,
                               "restart": self._sup.restarts + 1})
                    rsp.end()
                    tracing.recorder().pin(sp.trace_id, "recover")
            for orig in sorted(self._live - seen):
                # live at failure time but absent from the capture (an
                # engine without capture support, or one whose capture
                # itself failed): nothing will ever decode it — lost,
                # drained as ``failed``, exactly once
                self._lost_rids.add(orig)
                self.m_lost.inc()
                lost += 1
                self._abandoned.discard(orig)
                self._account(orig, "failed", None)
            self._recovering = False
            self._sup.note_recovered(cause, t_fail, resumed, lost)
            self._mirror_engine_gauges()
            logger.info(
                "engine restarted (%s): %d resumed (%d swap / %d "
                "recompute), %d lost, mttr %.3fs", cause,
                sum(resumed.values()), resumed["swap"],
                resumed["recompute"], lost,
                self._sup.episodes[-1]["mttr_s"])
            self._work.notify_all()
            t = threading.Thread(target=self._run, daemon=True)
            self._thread = t
            t.start()

    def _monitor(self) -> None:
        """Stuck-tick watchdog: a decode tick in flight longer than
        ``watchdog_s`` with no arrival consumed counts as an engine
        failure — same supervised-restart path, run on THIS thread
        (the stuck ticker can't free itself; it exits via the
        generation check whenever it unblocks). Only effective on
        split-protocol engines: a bare step() hang holds the loop
        lock, which no watchdog can recover."""
        period = max(0.02, self._watchdog_s / 4.0)
        while not self._stop_event.wait(period):
            with self._work:
                if self._failed is not None:
                    return
                if self._recovering or self._tick_started is None:
                    continue
                started = self._tick_started
                dt = time.monotonic() - started
                if dt <= self._watchdog_s:
                    continue
                gen = self._gen
                exc: BaseException = TimeoutError(
                    f"watchdog: decode tick in flight {dt:.2f}s "
                    f"(> --watchdog-s {self._watchdog_s:.2f}s) with no "
                    f"arrival consumed")
            self._recover(exc, "watchdog", gen, stuck_since=started)

    # -- request deadlines ----------------------------------------------
    def _estimate_completion_s(self, max_new_tokens: int) -> tuple:
        """Rolling estimate of submit -> finished for a fresh request,
        as (seconds, expected tokens): EWMA TTFT (queue + prefill)
        plus EWMA TPOT per expected token. (None, tokens) until the
        first completion has seeded the estimates — with nothing to
        judge against, admission stays optimistic. The token count is
        returned too so the shed message's arithmetic multiplies out
        to the reported estimate.

        Expected length is min(ceiling, 2 x EWMA actual output):
        max_new_tokens is routinely a generous ceiling under
        stop_tokens, and multiplying TPOT by the ceiling would shed
        early-stopping traffic that finishes comfortably in time. The
        2x headroom keeps the estimate conservative for
        longer-than-typical requests; one that still overruns its
        deadline is caught by the mid-decode sweep (504) — a softer
        failure than wrongly refusing work the server could do."""
        tokens = float(max_new_tokens)
        if self._est_out_tokens is not None:
            tokens = min(tokens, 2.0 * self._est_out_tokens)
        if self._est_ttft_s is None:
            return None, tokens
        return (self._est_ttft_s
                + (self._est_tpot_s or 0.0) * max(0.0, tokens - 1),
                tokens)

    def _sweep_deadlines(self) -> None:
        """Cancel every live request whose deadline has passed (caller
        holds the lock; runs each tick quantum — the 'next tick
        barrier' of the deadline contract — and from stream waiters)."""
        if not self._deadlines:
            return
        now = time.monotonic()
        for rid, dl in list(self._deadlines.items()):
            if now > dl and rid not in self._deadline_hit:
                self._expire_deadline(rid)

    def _expire_deadline(self, rid: int) -> None:
        """Terminal ``deadline`` outcome for one request, exactly once
        (caller holds the lock): cancel it out of the engine (pending
        or mid-decode — cancel is the tick barrier), pop what it left,
        account. A request that FINISHED before the sweep keeps its
        ``finished`` outcome — the deadline only beats completion."""
        erid = self._rid_map.get(rid)
        prog = self.engine.progress(erid) if erid is not None else None
        if prog is None or prog[1]:
            # unknown (already terminal elsewhere) or done: not ours
            self._deadlines.pop(rid, None)
            return
        # same guard as _forget: a dead or mid-recovery engine is not
        # asked to mutate its batch — DecodeServer.cancel runs a
        # pipeline-barrier flush that would block on the very device
        # op a watchdog recovery is routing around (and the captured
        # request is simply not restored: the tombstone below covers
        # it). progress/pop_result are host dict reads, safe either way.
        cancel = getattr(self.engine, "cancel", None)
        if cancel is not None and self._failed is None \
                and not self._recovering:
            cancel(erid)
        ledger = self._pop_ledger(erid)
        self.engine.pop_result(erid)
        self._deadline_hit.add(rid)
        self._deadline_expired += 1
        self._abandoned.discard(rid)
        self._account(rid, "deadline", ledger)
        self._mirror_engine_gauges()
        self._work.notify_all()     # the stream raises DeadlineExceeded

    # -- prefill/decode disaggregation ----------------------------------
    def _fetch_pool_stats(self, target: str) -> dict:
        """Default /stats scraper for the pusher's decode-pool health
        view; ``pool_stats_fetch`` overrides it (tests, benches)."""
        import urllib.request

        with urllib.request.urlopen(
                target.rstrip("/") + "/stats", timeout=2) as resp:
            return json.loads(resp.read())

    def _refresh_pool_health(self, targets) -> None:
        """Refresh the pusher's health view of the decode pool from
        each target's /stats, at most every
        --handoff-health-interval-s. A target whose scrape fails goes
        UNKNOWN (dropped from the view), not unhealthy — the push
        attempt itself owns failure cooldowns."""
        now = time.monotonic()
        if self._pool_health_at is not None and \
                now - self._pool_health_at \
                < self._handoff_health_interval_s:
            return
        self._pool_health_at = now
        fetch = self.pool_stats_fetch or self._fetch_pool_stats
        health = {}
        for t in targets:
            try:
                st = fetch(t)
            except Exception:   # noqa: BLE001 — scrape is best-effort
                continue
            pending = st.get("pending")
            depth = pending.get("depth", 0) \
                if isinstance(pending, dict) else 0
            health[t] = {
                "queue": int(depth or 0),
                "draining": bool(st.get("draining")),
                "recovering": bool(st.get("recovering")),
            }
        self._pool_health = health

    def _order_pool(self, pool: list) -> list:
        """Order push candidates by the health view: draining or
        recovering targets are dropped (skipped BEFORE a failed
        attempt — counted in nos_tpu_serve_handoff_skipped_total),
        healthy ones sort by scraped queue depth ascending with the
        round-robin cursor breaking ties, unknown targets (scrape
        failed) sort after every known-healthy one. An empty result
        (whole pool draining) falls back to the unordered pool —
        the health view degrades to blind round-robin, never to
        dropping the handoff."""
        if not self._pool_health:
            return pool
        keep, skipped = [], 0
        for t in pool:
            h = self._pool_health.get(t)
            if h is not None and (h["draining"] or h["recovering"]):
                skipped += 1
                continue
            keep.append(t)
        if skipped:
            self.m_handoff_skipped.inc(skipped)
        if not keep:
            return pool
        rank = {t: i for i, t in enumerate(keep)}
        rr = self._handoff_rr

        def key(t):
            h = self._pool_health.get(t)
            return ((0, h["queue"]) if h is not None else (1, 0)) \
                + ((rank[t] - rr) % len(keep),)

        return sorted(keep, key=key)

    def _push_handoffs(self) -> None:
        """Pusher thread (prefill role): drain the engine's parked
        handoff states and ship each to a decode-pool target —
        round-robin, next target on failure, two laps before the
        handoff (and its request) fails. Encoding and the network send
        run OUTSIDE the loop lock; only the bookkeeping (result map,
        terminal accounting, metrics) re-enters it."""
        from nos_tpu.models.handoff import encode_handoff, handoff_nbytes
        while True:
            with self._work:
                while not self._stop and self._failed is None \
                        and not getattr(self.engine, "_handoffs", None):
                    self._work.wait(timeout=0.25)
                if self._stop or self._failed is not None:
                    return
                states = self.engine.pop_handoffs()
                # reverse map BEFORE releasing the lock: a recovery
                # could remap rids while we ship
                rev = {erid: lrid
                       for lrid, erid in self._rid_map.items()}
            for st in states:
                with self._work:
                    lrid0 = rev.get(st["rid"])
                    if lrid0 is not None \
                            and lrid0 in self._handoff_gone:
                        # the client departed while the payload was
                        # parked: don't ship KV nobody will read —
                        # resolve the request as cancelled here
                        self._handoff_gone.discard(lrid0)
                        self._account(lrid0, "cancelled",
                                      self._pop_ledger(st["rid"]))
                        self._work.notify_all()
                        continue
                    dl = (self._prefill_deadlines.get(lrid0)
                          if lrid0 is not None else None)
                    tp = (self._prefill_traceparents.get(lrid0)
                          if lrid0 is not None else None)
                if tp is not None:
                    # the journey context rides the same JSON meta
                    # plane as deadline_s: the adopting decode
                    # replica's serve.request parents into it
                    st["traceparent"] = tp
                if dl is not None:
                    # carry the REMAINING seconds, computed at ship
                    # time: wall budgets survive the hop without any
                    # cross-host clock sync. An already-negative carry
                    # still ships — adopt() arms it in the past and
                    # the decode side's next sweep sheds the expired
                    # work instead of decoding an answer nobody waits
                    # for. The descriptor key rides the handoff's JSON
                    # meta plane (models/handoff.py round-trips
                    # non-array keys verbatim).
                    st["deadline_s"] = dl - time.monotonic()
                t0 = time.monotonic()
                data = encode_handoff(st)
                sent, last_err = None, None
                targets = self._handoff_targets
                now = time.monotonic()
                pool = [t for t in targets
                        if self._handoff_unhealthy.get(t, 0.0) <= now]
                if len(pool) < len(targets):
                    self.m_handoff_skipped.inc(len(targets) - len(pool))
                ordered = None
                if not pool:
                    pool = targets      # whole pool cooling: probe all
                elif self._handoff_health_interval_s > 0:
                    # health view: skip draining/recovering targets
                    # before the first attempt, try the least-loaded
                    # healthy replica first (RR breaks ties)
                    self._refresh_pool_health(pool)
                    ordered = self._order_pool(pool)
                for i in range(max(1, 2 * len(pool))):
                    if ordered:
                        target = ordered[i % len(ordered)]
                    else:
                        target = pool[self._handoff_rr % len(pool)]
                    self._handoff_rr += 1
                    try:
                        remote_rid = self._handoff_send(target, data)
                        sent = {"target": target, "rid": int(remote_rid)}
                        self._handoff_unhealthy.pop(target, None)
                        break
                    except Exception as e:  # noqa: BLE001 — next target
                        last_err = e
                        if self._handoff_cooldown_s > 0:
                            self._handoff_unhealthy[target] = \
                                time.monotonic() + self._handoff_cooldown_s
                with self._work:
                    lrid = rev.get(st["rid"])
                    ledger = self._pop_ledger(st["rid"])
                    self.h_handoff_bytes.observe(handoff_nbytes(st))
                    self.h_handoff.observe(time.monotonic() - t0)
                    if sent is not None:
                        self.m_handoff.labels("sent").inc()
                        if lrid is not None:
                            if lrid in self._handoff_gone:
                                # departed mid-ship: the decode side
                                # owns an orphan now, but THIS loop's
                                # outcome is exactly-once cancelled
                                # and no descriptor parks unclaimed
                                self._handoff_gone.discard(lrid)
                                self._account(lrid, "cancelled",
                                              ledger)
                            else:
                                self._handoff_done[lrid] = sent
                                self._account(lrid, "finished", ledger)
                    else:
                        logger.error("handoff for rid %s failed on "
                                     "every decode target: %s",
                                     st["rid"], last_err)
                        self.m_handoff.labels("failed").inc()
                        if lrid is not None:
                            self._handoff_done[lrid] = {
                                "error": f"handoff failed: {last_err}"}
                            self._account(lrid, "failed", ledger)
                    self._work.notify_all()

    def prefill(self, prompt, max_new_tokens, timeout: float = 300.0,
                deadline_s: Optional[float] = None,
                traceparent: Optional[str] = None, **sampling):
        """Prefill-role request path: submit, wait for the handoff to
        land on a decode replica, return its descriptor
        ``{"handoff": {"target", "rid"}}`` — the gateway (or client)
        then streams/fetches from the decode replica. A request whose
        first token already completes it (max_new_tokens == 1) never
        hands off: its tokens come back directly, same wire shape as
        a colocated answer.

        ``deadline_s`` is not ENFORCED here (phase 1 is short; expiry
        is the decode side's job) but it is no longer dropped: the
        pusher ships the remaining budget inside the handoff
        descriptor and the adopting replica arms it, so expired
        phase-2 work is shed early instead of decoding unread
        tokens."""
        dl_s = deadline_s if deadline_s is not None \
            else (self._default_deadline_s or None)
        if dl_s is not None:
            dl_s = float(dl_s)
            # same finite-only discipline as stream(): NaN passes every
            # comparison as a never-expiring ghost deadline
            if not math.isfinite(dl_s) or dl_s < 0:
                raise ValueError(
                    f"deadline_s must be a finite number >= 0, "
                    f"got {dl_s}")
            if dl_s == 0:       # an EXPLICIT 0 opts out of the default
                dl_s = None
        with self._work:
            if self._failed is not None:
                raise RuntimeError(f"serving loop failed: {self._failed}")
            if self._recovering:
                self.m_requests.labels("rejected").inc()
                raise EngineRecovering(
                    "engine restarting after a fault; retry shortly")
            if self._draining:
                raise DrainingError(
                    "server is draining (terminating); retry elsewhere")
            if dl_s is not None:
                # the budgeted prefill scheduler orders waiting chunk
                # work by TTFT slack even on a prefill-role engine;
                # engines without it just see an extra kwarg
                sampling["deadline_s"] = dl_s
            try:
                erid = self.engine.submit(prompt, max_new_tokens,
                                          **sampling)
            except QueueFull:
                self.m_requests.labels("rejected").inc()
                raise
            rid = self._next_rid
            self._next_rid += 1
            self._rid_map[rid] = erid
            self._live.add(rid)
            if dl_s is not None:
                self._prefill_deadlines[rid] = time.monotonic() + dl_s
            # the prefill side of a disaggregated request records its
            # own serve.request span (role=prefill, closed by _account
            # when the handoff ships or the request completes locally)
            # and STASHES a context for the pusher: the encoded child
            # context when recording, else the raw inbound header —
            # tracing-off prefill replicas still forward the journey
            # untouched to the decode side
            sp = tracing.start_span(
                "serve.request", component="server", parent=traceparent,
                attrs={"prompt_tokens": len(prompt),
                       "max_new_tokens": max_new_tokens,
                       "role": "prefill"})
            if sp.recording:
                self._spans[rid] = sp
                self._prefill_traceparents[rid] = sp.context.encode()
            elif traceparent:
                self._prefill_traceparents[rid] = traceparent
            self._mirror_engine_gauges()
            self._work.notify_all()
            deadline = time.monotonic() + timeout
            while True:
                done = self._handoff_done.pop(rid, None)
                if done is not None:
                    if "error" in done:
                        raise RuntimeError(done["error"])
                    return {"handoff": done}
                cur = self._rid_map.get(rid)
                prog = self.engine.progress(cur) \
                    if cur is not None else None
                if prog is not None and prog[1]:
                    # completed locally (max_new_tokens == 1): the
                    # ordinary unary answer
                    ledger = self._pop_ledger(cur)
                    self.engine.pop_result(cur)
                    self._account(rid, "finished", ledger)
                    return {"tokens": list(prompt) + prog[0]}
                if self._failed is not None:
                    raise RuntimeError(
                        f"serving loop failed: {self._failed}")
                if self._stop:
                    raise RuntimeError(
                        f"request {rid} unfinished at server shutdown")
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self._forget_locked(rid)
                    raise TimeoutError(f"request {rid} timed out "
                                       f"awaiting handoff")
                self._work.wait(timeout=min(remaining, 1.0))

    def _forget_locked(self, rid: int) -> None:
        """_forget's body expects to take the lock itself; this is the
        already-locked twin for the prefill wait path."""
        self._work.release()
        try:
            self._forget(rid)
        finally:
            self._work.acquire()

    def adopt(self, data: bytes) -> int:
        """Decode-role ingest: decode one handoff payload and restore
        it into the engine — byte-exact resume of the prefilled KV plus
        the committed first token. Returns the loop rid ``result`` /
        ``watch`` serve. Geometry mismatches (block size, kv_dtype,
        model dims) raise Infeasible from the engine's restore."""
        from nos_tpu.models.handoff import decode_handoff
        state = decode_handoff(data)
        # deadline carried through the handoff (remaining seconds at
        # ship time): popped before restore — it is loop bookkeeping,
        # not engine KV state
        carried_dl = state.pop("deadline_s", None)
        # trace context carried the same way: the decode side's
        # serve.request span parents into the prefill side's, so one
        # trace_id spans the disaggregated pair
        carried_tp = state.pop("traceparent", None)
        with self._work:
            if self._failed is not None:
                raise RuntimeError(f"serving loop failed: {self._failed}")
            if self._recovering:
                raise EngineRecovering(
                    "engine restarting after a fault; retry shortly")
            if self._draining:
                raise DrainingError(
                    "server is draining (terminating); retry elsewhere")
            erid = self.engine.restore(state)
            rid = self._next_rid
            self._next_rid += 1
            self._rid_map[rid] = erid
            self._live.add(rid)
            self._adopted[rid] = list(state["prompt"])
            if self._adopt_ttl_s > 0:
                self._handoff_deadline[rid] = \
                    time.monotonic() + self._adopt_ttl_s
            if carried_dl is not None:
                # arm the carried request deadline in the SAME ledger
                # stream()'s deadlines live in: _deltas raises
                # DeadlineExceeded and _sweep_deadlines sheds it
                # mid-decode exactly like a locally-submitted request.
                # A non-positive carry (expired in transit) arms in
                # the past and the next sweep cancels it before it
                # burns a decode tick quantum.
                self._deadlines[rid] = \
                    time.monotonic() + float(carried_dl)
            sp = tracing.start_span(
                "serve.request", component="server",
                parent=carried_tp if isinstance(carried_tp, str)
                else None,
                attrs={"prompt_tokens": len(state["prompt"]),
                       "role": "decode", "adopted": True})
            if sp.recording:
                self._spans[rid] = sp
            self._mirror_engine_gauges()
            self._work.notify_all()
        return rid

    def export_chain(self, digest: str) -> Optional[bytes]:
        """KV-fabric peer-pull serve (GET /v1/kvchain/<digest>): one
        chain's codec payload from this replica's HBM prefix index or
        host tier, or None. The loop lock is held only for the chain
        lookup + async gather ENQUEUE (export_chain_begin); the
        blocking device->host copy and npz encode of a multi-megabyte
        payload run OUTSIDE it, so concurrent peer pulls never stall
        decode ticks or admission on this replica. The gather reads
        the arena version current at enqueue (chain blocks are COW,
        never written in place), so the released lock cannot skew the
        snapshot."""
        begin = getattr(self.engine, "export_chain_begin", None)
        if begin is not None:
            with self._work:
                if self._failed is not None or self._recovering:
                    return None
                handle = begin(digest)
            if handle is None:
                return None
            return self.engine.export_chain_finish(handle)
        # stub engines without the two-phase surface: whole export
        # under the lock, as before
        export = getattr(self.engine, "export_chain", None)
        if export is None:
            return None
        with self._work:
            if self._failed is not None or self._recovering:
                return None
            return export(digest)

    def _fetch_chain_bytes(self, url: str, timeout_s: float = 2.0,
                           traceparent: Optional[str] = None) -> bytes:
        import urllib.parse
        import urllib.request
        if urllib.parse.urlsplit(url).scheme not in ("http", "https"):
            # an offer names a fleet peer's HTTP surface and nothing
            # else — file:// and friends must never reach urlopen
            raise ValueError(f"kvchain fetch: non-http url {url!r}")
        req = urllib.request.Request(url)
        if self.fabric_token:
            # peer /v1/kvchain exports are token-gated (fleet-internal)
            req.add_header(FABRIC_TOKEN_HEADER, self.fabric_token)
        if traceparent:
            # the holder's kvfabric.serve span parents into the
            # puller's kvfabric.pull — the peer hop stays in-trace
            req.add_header("traceparent", traceparent)
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            if resp.status != 200:
                raise RuntimeError(f"kvchain fetch {url}: {resp.status}")
            return resp.read()

    def note_pull_denied(self, digest: Optional[str] = None,
                         parent: Optional[str] = None) -> None:
        """A kv_sources offer arrived without the fleet's fabric token
        (or none is configured): never honored — the offer steers this
        replica's outbound fetcher and seeds its prefix cache, so a
        client-supplied one is blind SSRF plus cache poisoning.
        Counted so operators can see misconfigured (or probing)
        callers; when the request carries a trace (``parent``), the
        denial is also filed into it as a kvfabric.pull span — a
        denied pull inside a slow request must not be invisible.
        Tokenless probes (no trace) stay counters-only so they cannot
        spam the flight recorder with fresh roots."""
        self._count_pull("pull_denied")
        if parent:
            dsp = tracing.start_span(
                "kvfabric.pull", component="kvfabric", parent=parent,
                attrs={"outcome": "pull_denied",
                       "digest": digest or ""})
            dsp.end()

    def _count_pull(self, ev: str) -> None:
        self._pull_counts[ev] += 1
        if hasattr(self, "m_kvfabric"):
            self.m_kvfabric.labels(ev).inc()

    def prefetch_chain(self, sources, tenant: Optional[str] = None,
                       deadline_s: Optional[float] = None,
                       parent: Optional[str] = None) -> bool:
        """Best-effort adoption of gateway-offered peer chains BEFORE
        a request submits: fetch the codec payload from the named peer
        (outside the loop lock — a slow peer must not stall the
        serving loop), then ingest it under the lock so the request's
        own prefix match hits warm. Offers without a digest are
        ignored (the digest binds the pull to one (scope, tokens)
        identity — ingest re-checks it against the decoded payload).
        Every failure path returns False (counted pull_miss) and the
        request simply prefills — the fabric is an accelerator, never
        a dependency."""
        ok = False
        for src in sources if isinstance(sources, list) else ():
            if not isinstance(src, dict):
                continue
            url, digest = src.get("url"), src.get("digest")
            if not isinstance(url, str) or not url \
                    or not isinstance(digest, str) or not digest:
                continue
            # the pull is a child of the request's journey (parent =
            # the inbound traceparent): a slow or missed peer pull
            # inside a slow request shows up IN that request's trace
            psp = tracing.start_span(
                "kvfabric.pull", component="kvfabric", parent=parent,
                attrs={"digest": digest, "url": url})
            adopted = self._pull_single_flight(
                url, digest, tenant, deadline_s,
                traceparent=(psp.context.encode() if psp.recording
                             else None))
            outcome = "pull_hit" if adopted else "pull_miss"
            psp.set_attr("outcome", outcome)
            psp.end()
            self._count_pull(outcome)
            ok = ok or adopted
        return ok

    def _pull_single_flight(self, url: str, digest: str,
                            tenant: Optional[str],
                            deadline_s: Optional[float],
                            traceparent: Optional[str] = None) -> bool:
        """One fetch+ingest per digest at a time: concurrent requests
        sharing the same cold prefix ride the leader's pull — when it
        lands, the chain is in the local index and every rider's own
        prefix match hits warm (re-fetching the identical payload
        would only hammer the peer's export path)."""
        with self._pull_lock:
            flight = self._pull_inflight.get(digest)
            leader = flight is None
            if leader:
                flight = {"done": threading.Event(), "adopted": False}
                self._pull_inflight[digest] = flight
        if not leader:
            flight["done"].wait(
                timeout=self.chain_fetch_timeout_s + 5.0)
            return flight["adopted"]
        try:
            flight["adopted"] = self._pull_once(url, digest, tenant,
                                                deadline_s, traceparent)
        finally:
            with self._pull_lock:
                self._pull_inflight.pop(digest, None)
            flight["done"].set()
        return flight["adopted"]

    def _pull_once(self, url: str, digest: str,
                   tenant: Optional[str],
                   deadline_s: Optional[float],
                   traceparent: Optional[str] = None) -> bool:
        timeout = self.chain_fetch_timeout_s
        if deadline_s is not None:
            # never spend more of the request's own completion budget
            # waiting on a peer than the budget itself allows
            timeout = max(0.1, min(timeout, float(deadline_s)))
        try:
            if self.chain_fetch is not None:
                data = self.chain_fetch(url)
            else:
                data = self._fetch_chain_bytes(url, timeout_s=timeout,
                                               traceparent=traceparent)
            with self._work:
                if self._failed is not None or self._recovering:
                    raise RuntimeError("loop not serving")
                return bool(self.engine.ingest_chain(
                    data, tenant, expect_digest=digest))
        except Exception as exc:
            logger.debug("kvfabric pull failed: %s", exc)
            return False

    def watch(self, rid: int, timeout: float = 300.0):
        """Attach to an adopted request's token stream (the decode-side
        SSE surface after a handoff): yields newly-decoded token lists
        exactly like ``stream``, for a request that entered via
        ``adopt`` instead of ``submit``."""
        with self._work:
            if self._rid_map.get(rid) is None:
                raise ValueError(f"unknown request {rid}")
            # a consumer owns the lifecycle now (its disconnect runs
            # _forget): the unclaimed-orphan TTL stands down
            self._handoff_deadline.pop(rid, None)
        return _Stream(self, rid, self._deltas(rid, timeout))

    def result(self, rid: int, timeout: float = 300.0):
        """Block for an adopted request's full sequence (prompt +
        generated) — the decode-side unary surface after a handoff.
        Idempotent once finished (until the re-fetch TTL expires): a
        gateway retrying after a socket timeout gets the same tokens
        its abandoned first attempt drained."""
        with self._work:
            final = self._adopted_final.get(rid)
            if final is not None:
                return list(final)
            prompt = self._adopted.get(rid)
            if prompt is None:
                raise ValueError(f"unknown request {rid}")
            self._handoff_deadline.pop(rid, None)   # consumer attached
        out = list(prompt)
        try:
            for delta in self._deltas(rid, timeout):
                out.extend(delta)
        except RuntimeError:
            # "request N vanished": a concurrent result() handler for
            # the same rid (an abandoned attempt the client timed out
            # on) won the engine pop — its parked final answers us.
            # Brief recheck window: the winner's pop (inside _deltas)
            # and its park below are two lock acquisitions apart.
            end = time.monotonic() + 2.0
            with self._work:
                while True:
                    final = self._adopted_final.get(rid)
                    if final is not None:
                        return list(final)
                    if time.monotonic() >= end:
                        break
                    self._work.wait(timeout=0.05)
            raise
        with self._work:
            self._adopted_final[rid] = list(out)
            if self._adopt_ttl_s > 0:
                # re-fetch grace window; _reap_orphans drops it after
                self._handoff_deadline[rid] = \
                    time.monotonic() + self._adopt_ttl_s
            self._adopted.pop(rid, None)
            self._work.notify_all()
        return out

    def generate(self, prompt, max_new_tokens, timeout: float = 300.0,
                 deadline_s: Optional[float] = None,
                 traceparent: Optional[str] = None, **sampling):
        """Unary request: expressed over ``stream`` so there is exactly
        one waiting/abandon/metrics implementation."""
        out = list(prompt)
        for delta in self.stream(prompt, max_new_tokens, timeout,
                                 deadline_s=deadline_s,
                                 traceparent=traceparent, **sampling):
            out.extend(delta)
        return out

    def _forget(self, rid: int) -> None:
        """Idempotently drop a request in whatever state it is: pop it if
        resolvable (accounting the terminal outcome), mark it abandoned
        if still decoding (the ticker reaps it), no-op if already handed
        out. Runs from stream teardown — including client disconnects
        that land exactly at completion, when the ticker may never tick
        again on an idle server. Outcomes: ``cancelled`` for a client
        that walked away (disconnect/timeout), ``failed`` when the pop
        happens during an engine-failure or shutdown drain — the request
        didn't fail its client, the server failed the request."""
        with self._work:
            # None (no map entry) means the request was already
            # terminally accounted and unmapped — the bare rid must NOT
            # be used against the engine, where it may alias a
            # different post-restart request with the same number
            erid = self._rid_map.get(rid)
            if rid in self._deadline_hit or rid in self._lost_rids:
                # already terminally accounted (deadline expiry / lost
                # in a restart): clear leftovers, never account twice.
                # The tombstone itself survives an in-flight recovery —
                # _recover's restore pass consults it to skip this
                # request's captured state (dropping it here would
                # resurrect an already-accounted request); the rare
                # stream that tears down mid-recovery leaks one set
                # entry, which is bounded and harmless. No engine
                # cleanup here: every tombstone is set alongside its
                # _account, which already popped the ledger/result and
                # the rid mapping (erid is None by construction).
                if not self._recovering:
                    self._deadline_hit.discard(rid)
                    self._lost_rids.discard(rid)
                self._abandoned.discard(rid)
                return
            if erid is None or self.engine.progress(erid) is None:
                # prefill role: the request may be parked as — or
                # already shipped as — a handoff. Drop any unclaimed
                # descriptor, and tombstone a still-live rid so the
                # pusher resolves a departed client's handoff as
                # cancelled instead of parking a descriptor nobody
                # will ever pop.
                self._handoff_done.pop(rid, None)
                if self.role == "prefill" and rid in self._live:
                    self._handoff_gone.add(rid)
                self._abandoned.discard(rid)    # already popped
                return
            draining_out = self._failed is not None or self._stop
            # stop burning ticks on output nobody will read: cancel frees
            # the slot immediately (engines without cancel — test stubs —
            # fall back to reap-after-completion). A dead engine is not
            # asked to mutate its batch; mid-recovery the request will
            # simply not be restored (_recover sees it in _abandoned).
            cancel = getattr(self.engine, "cancel", None)
            if cancel is not None and self._failed is None \
                    and not self._recovering:
                cancel(erid)
            ledger = self._pop_ledger(erid)
            if self.engine.pop_result(erid) is not None:
                self._account(rid, "failed" if draining_out
                              else "cancelled", ledger)
                self._abandoned.discard(rid)
            elif draining_out:
                # engine-failure/shutdown drain: no tick will ever
                # finish this request and no reap will ever pop it —
                # account it NOW, exactly once
                if rid not in self._failed_drained:
                    self._failed_drained.add(rid)
                    self._account(rid, "failed", ledger)
                self._abandoned.discard(rid)
            elif self.engine.progress(erid) is None:
                # cancel dropped the request outright (nothing poppable,
                # engine no longer knows it) and the engine may be idle:
                # no tick's reap will ever resolve it — terminal NOW, or
                # it never earns its exactly-one outcome
                self._account(rid, "cancelled", ledger)
                self._abandoned.discard(rid)
            else:
                self._abandoned.add(rid)
            # cancel mutated occupancy and the ticker may never run again
            # on an idle server — re-mirror here or the gauges stay stale
            self._mirror_engine_gauges()

    def _mirror_engine_gauges(self) -> None:
        """Engine-held stats (prefix cache, occupancy) -> gauges.
        Called from every path that mutates them — submit, decode tick,
        and disconnect-cancel (_forget) — plus once at startup: a
        prefill-only request completes without the ticker ever running,
        a cancel on an idle server never ticks again, and a fresh pod
        must export 0s, not absent series."""
        hits = getattr(self.engine, "prefix_hits", None)
        if hits is not None:
            self.m_prefix_hits.set(hits)
            self.m_prefix_saved.set(self.engine.prefix_tokens_saved)
        occupancy = getattr(self.engine, "occupancy", None)
        if occupancy is not None:
            active, pending = occupancy()
            self.g_active.set(active)
            self.g_pending.set(pending)
        drafted = getattr(self.engine, "spec_drafted", None)
        if drafted is not None and hasattr(self, "m_spec_draft"):
            d_delta = drafted - self._spec_seen["drafted"]
            if d_delta > 0:
                self.m_spec_draft.inc(d_delta)
                self._spec_seen["drafted"] = drafted
            accepted = self.engine.spec_accepted
            a_delta = accepted - self._spec_seen["accepted"]
            if a_delta > 0:
                self.m_spec_accepted.inc(a_delta)
                self._spec_seen["accepted"] = accepted
            events = self.engine.spec_window_events
            if events:
                self.engine.spec_window_events = []
                for a in events:
                    self.h_spec_window.observe(float(a))
        tenant_snap = getattr(self.engine, "tenant_snapshot", None)
        if self._tenant_cfg is not None and tenant_snap is not None:
            snap = tenant_snap()
            for t, row in (snap or {}).items():
                self.g_tenant_borrowed.labels(t).set(
                    row.get("borrowed_tokens_per_s", 0.0))
                for mode, n in (row.get("preempts") or {}).items():
                    seen = self._tenant_preempt_seen.get((t, mode), 0)
                    if n > seen:
                        self.m_tenant_preempt.labels(t, mode).inc(
                            n - seen)
                        self._tenant_preempt_seen[(t, mode)] = n
        pindex = getattr(self.engine, "_pindex", None)
        if pindex is not None and hasattr(self, "m_prefix_evict"):
            for tier, n in pindex.evicted.items():
                delta = n - self._prefix_evict_seen.get(tier, 0)
                if delta > 0:
                    self.m_prefix_evict.labels(tier).inc(delta)
                    self._prefix_evict_seen[tier] = n
            for ev, n in getattr(self.engine, "_fabric", {}).items():
                if ev not in self._fabric_seen:
                    continue    # ingest* counts ride pull_hit/pull_miss
                delta = n - self._fabric_seen[ev]
                if delta > 0:
                    self.m_kvfabric.labels(ev).inc(delta)
                    self._fabric_seen[ev] = n
        if hasattr(self, "m_psched_spent"):
            for attr, key, m in (
                    ("prefill_budget_spent", "spent",
                     self.m_psched_spent),
                    ("prefill_budget_clamped", "clamped",
                     self.m_psched_clamp),
                    ("prefill_budget_overrides", "overrides",
                     self.m_psched_override)):
                n = getattr(self.engine, attr, 0)
                delta = n - self._psched_seen[key]
                if delta > 0:
                    m.inc(delta)
                    self._psched_seen[key] = n
        self._mirror_chip_ledger()
        kv_stats = getattr(self.engine, "kv_stats", None)
        kv = kv_stats() if kv_stats is not None else None
        if kv:
            self.g_kv_free.set(kv["blocks_free"])
            self.g_kv_used.set(kv["blocks_used"])
            self.g_kv_shared.set(kv["cow_shared"])
            for mode, n in kv["preempts"].items():
                delta = n - self._preempt_seen.get(mode, 0)
                if delta > 0:
                    self.m_preempt.labels(mode).inc(delta)
                    self._preempt_seen[mode] = n
            # the engine's admission-time HBM snapshot feeds the same
            # gauges the interval sampler owns, so /metrics moves when
            # an admission decision observed fresh pressure between
            # --device-stats-interval ticks
            hbm = kv.get("hbm")
            if hbm and hbm.get("in_use") is not None:
                reg = default_registry()
                reg.gauge(
                    "nos_tpu_device_hbm_bytes_in_use",
                    "Device memory (HBM) bytes currently allocated, per "
                    "local device (absent on backends without "
                    "memory_stats, e.g. CPU)",
                    ("device",)).labels(hbm["device"]).set(hbm["in_use"])
                if hbm.get("limit"):
                    reg.gauge(
                        "nos_tpu_device_hbm_bytes_limit",
                        "Device memory (HBM) byte capacity, per local "
                        "device",
                        ("device",)).labels(hbm["device"]).set(
                            hbm["limit"])
        self._drain_compile_events()

    def _mirror_chip_ledger(self) -> None:
        """Delta-mirror the engine's attribution ledger into the
        chip-ms / kv-byte-seconds counters AND the loop's cumulative
        totals (which survive supervised engine swaps — the PR 13
        tenant-counter pattern: ``_do_recover`` resets the seen dicts
        when a rebuilt engine restarts its ledger from zero, so the
        cumulative view stays monotone and stays conserved)."""
        chip = getattr(self.engine, "chip", None)
        if chip is None or self.slo_engine is None:
            return
        for key, ns in chip.totals_ns().items():
            delta = ns - self._chip_seen_ns.get(key, 0)
            if delta > 0:
                self._chip_seen_ns[key] = ns
                self._chip_cum_ns[key] = \
                    self._chip_cum_ns.get(key, 0) + delta
                self.m_chip_ms.labels(*key).inc(delta / 1e6)
        delta = chip.wall_ns - self._chip_seen_wall_ns
        if delta > 0:
            self._chip_seen_wall_ns = chip.wall_ns
            self._chip_cum_wall_ns += delta
        for t, bs in chip.kv_byte_seconds().items():
            d = bs - self._chip_seen_kvbs.get(t, 0.0)
            if d > 0:
                self._chip_seen_kvbs[t] = bs
                self._chip_cum_kvbs[t] = \
                    self._chip_cum_kvbs.get(t, 0.0) + d
                self.m_kv_byte_s.labels(t).inc(d)

    def _chip_ledger_block(self) -> Optional[dict]:
        """/stats ``chip_ledger``: the loop's cumulative attribution
        totals (None = SLO accounting off). Conservation is judged on
        the cumulative integers, so it holds across engine swaps."""
        if self.slo_engine is None:
            return None
        self._mirror_chip_ledger()
        per: dict = {}
        for (t, ph), ns in sorted(self._chip_cum_ns.items()):
            per.setdefault(t, {})[ph] = round(ns / 1e6, 3)
        return {
            "wall_ms": round(self._chip_cum_wall_ns / 1e6, 3),
            "chip_ms": per,
            "kv_byte_seconds": {
                t: round(v, 3)
                for t, v in sorted(self._chip_cum_kvbs.items())},
            "conserved": (sum(self._chip_cum_ns.values())
                          == self._chip_cum_wall_ns),
        }

    def stream(self, prompt, max_new_tokens, timeout: float = 300.0,
               deadline_s: Optional[float] = None,
               tenant: Optional[str] = None,
               traceparent: Optional[str] = None, **sampling):
        """Streaming primitive: submits EAGERLY (validation errors raise
        here, before the caller commits response headers) and returns an
        iterator yielding lists of newly-decoded tokens as ticks land.
        ``close()`` at ANY point — even before the first ``next()``,
        which a raw generator's finally cannot cover — drops the request
        via ``_forget``. Token identity with the unary path is the
        engine's batch-composition-invariance contract.

        ``deadline_s`` (default: the loop's ``default_deadline_s``; 0 /
        None = none) is the request's completion budget: shed at
        admission when the rolling TTFT/TPOT estimates say it cannot be
        met (DeadlineUnmeetable — a QueueFull, so HTTP answers 429 +
        Retry-After), cancelled at the next tick barrier once expired
        (the iterator raises DeadlineExceeded). Either way the
        request's one terminal outcome is ``deadline``.

        ``tenant`` is the request-level elastic-quota identity
        (``X-Tenant`` / JSON ``tenant`` on the wire): it rides to the
        engine's weighted admission and keys the per-tenant
        goodput/shed/preempt accounting here."""
        tlabel = (self._tenant_cfg.resolve(tenant)
                  if self._tenant_cfg is not None else None)
        with self._work:
            if self._failed is not None:
                raise RuntimeError(f"serving loop failed: {self._failed}")
            if self._recovering:
                # shed at the door, same accounting as QueueFull: the
                # request never entered the loop, its one outcome is
                # ``rejected`` (conservation: every submission attempt
                # earns exactly one outcome)
                self.m_requests.labels("rejected").inc()
                raise EngineRecovering(
                    "engine restarting after a fault; retry shortly")
            if self._draining:
                raise DrainingError(
                    "server is draining (terminating); retry elsewhere")
            dl_s = deadline_s if deadline_s is not None \
                else (self._default_deadline_s or None)
            if dl_s is not None:
                dl_s = float(dl_s)
                # finite-only: json.loads accepts the NaN literal, and
                # NaN would pass every comparison below as a silent
                # never-expiring ghost deadline instead of a clean 400
                if not math.isfinite(dl_s) or dl_s < 0:
                    raise ValueError(
                        f"deadline_s must be a finite number >= 0, "
                        f"got {dl_s}")
                if dl_s == 0:
                    # an EXPLICIT 0 opts out of the fleet default
                    # (--default-deadline-s): without this, no wire
                    # value could request unbounded completion on a
                    # defaulted fleet
                    dl_s = None
            if dl_s is not None:
                est, est_tokens = self._estimate_completion_s(
                    max_new_tokens)
                # under a per-tick prefill budget the chunk queue
                # ahead of this request delays its TTFT: account the
                # estimated backlog seconds at admission — the
                # earliest layer that can know the deadline is dead
                backlog_fn = getattr(self.engine, "prefill_backlog_s",
                                     None)
                backlog_s = float(backlog_fn() or 0.0) \
                    if backlog_fn is not None else 0.0
                if est is not None:
                    est += backlog_s
                if est is not None and est > dl_s \
                        and (self._shed_streak + 1) \
                        % DEADLINE_PROBE_EVERY != 0:
                    # shed EARLY: don't burn a slot on an answer the
                    # client will throw away. Same exactly-once outcome
                    # discipline as every other terminal path. Every
                    # DEADLINE_PROBE_EVERY'th consecutive shed falls
                    # through and is admitted as a probe — its
                    # completion refreshes the EWMA estimates, so a
                    # stale post-spike estimate cannot lock the server
                    # into shedding deadline traffic forever.
                    self.m_requests.labels("deadline").inc()
                    self._deadline_shed += 1
                    self._shed_streak += 1
                    if tlabel is not None:
                        self.m_tenant_shed.labels(
                            tlabel, "deadline_unmeetable").inc()
                    raise DeadlineUnmeetable(
                        f"deadline {dl_s:.3f}s cannot be met: rolling "
                        f"estimates put completion at {est:.3f}s "
                        f"(ttft ~{self._est_ttft_s:.3f}s, ~"
                        f"{max(0.0, est_tokens - 1):.0f} expected "
                        f"tokens at "
                        f"~{(self._est_tpot_s or 0.0) * 1e3:.1f}ms "
                        f"each"
                        + (f", plus ~{backlog_s:.3f}s of chunked "
                           f"prefill queued ahead" if backlog_s else "")
                        + "); retry with a longer deadline or when "
                        "load drops")
            if tenant is not None:
                # down to the engine's weighted admission; engines
                # without tenancy (test stubs) just see an extra kwarg
                sampling["tenant"] = tenant
            if dl_s is not None:
                # thread the remaining budget to the engine: its
                # budgeted prefill scheduler orders chunk work by the
                # slack left against it (enforcement stays HERE —
                # _sweep_deadlines owns expiry); engines without the
                # scheduler (test stubs) just see an extra kwarg
                sampling["deadline_s"] = dl_s
            try:
                erid = self.engine.submit(prompt, max_new_tokens,
                                          **sampling)
            except QueueFull as e:
                self.m_requests.labels("rejected").inc()
                if tlabel is not None:
                    # tenant_quota (the tenant's own ceiling) and the
                    # shared capacity reasons alike, attributed to the
                    # tenant that hit them
                    self.m_tenant_shed.labels(
                        tlabel, getattr(e, "reason", "queue_full")).inc()
                raise
            rid = self._next_rid
            self._next_rid += 1
            self._rid_map[rid] = erid
            self._live.add(rid)
            if tlabel is not None:
                self._tenant_of[rid] = tlabel
            self._shed_streak = 0       # an admission ends the streak
            if dl_s is not None:
                self._deadlines[rid] = time.monotonic() + dl_s
            # one span per REQUEST (not per token): the request's
            # journey through the serving loop, closed by _account with
            # its outcome and latency attrs — SLO breaches pin it. An
            # inbound ``traceparent`` (the gateway attempt's context)
            # is ADOPTED instead of minting a fresh trace_id, so the
            # fleet sees one trace per request; malformed headers fall
            # back to a fresh root (tracing.py's decode contract).
            sp = tracing.start_span(
                "serve.request", component="server", parent=traceparent,
                attrs={"prompt_tokens": len(prompt),
                       "max_new_tokens": max_new_tokens})
            if sp.recording:
                self._spans[rid] = sp
            self._mirror_engine_gauges()
            self._work.notify_all()

        return _Stream(self, rid, self._deltas(rid, timeout))

    def _deltas(self, rid: int, timeout: float):
        """The one token-delta generator behind ``stream`` (submitted
        requests) and ``watch``/``result`` (adopted handoffs): yields
        newly-decoded token lists until the request finishes, with the
        deadline/recovery/abandon discipline shared verbatim."""
        sent = 0
        finished = False
        deadline = time.monotonic() + timeout
        with self._work:
            self._watchers[rid] = self._watchers.get(rid, 0) + 1
        try:
            while True:
                with self._work:
                    # own-deadline check first: expiry beats both
                    # further waiting and the vanished error (the
                    # expire path popped the engine's record)
                    dl = self._deadlines.get(rid)
                    if dl is not None and time.monotonic() > dl \
                            and rid not in self._deadline_hit:
                        self._expire_deadline(rid)
                    if rid in self._deadline_hit:
                        raise DeadlineExceeded(
                            f"request {rid} exceeded its deadline")
                    if rid in self._lost_rids:
                        raise RuntimeError(
                            f"request {rid} lost in engine restart")
                    erid = self._rid_map.get(rid)
                    prog = self.engine.progress(erid) \
                        if erid is not None else None
                    if prog is None:
                        if self._recovering:
                            # mid-restore: the request is captured,
                            # not gone — wait for the rebuilt engine
                            self._work.wait(timeout=0.05)
                            continue
                        if self._failed is not None:
                            # drained as failed by a terminal
                            # engine death (possibly a cancelled
                            # recovery) — name the real cause
                            raise RuntimeError(
                                f"serving loop failed: {self._failed}")
                        # reaped out from under us (shutdown race)
                        raise RuntimeError(f"request {rid} vanished")
                    toks, done = prog
                    delta = toks[sent:]
                    if done:
                        ledger = self._pop_ledger(erid)
                        self.engine.pop_result(erid)
                        self._account(rid, "finished", ledger)
                        finished = True
                    elif not delta:
                        if self._failed is not None:
                            raise RuntimeError(
                                f"serving loop failed: {self._failed}")
                        if self._stop:
                            # loop.shutdown() ran (drain timeout /
                            # interpreter exit): no tick will ever
                            # finish this request — fail it NOW so
                            # the non-daemon handler thread exits
                            # instead of waiting out its timeout
                            raise RuntimeError(
                                f"request {rid} unfinished at server "
                                "shutdown")
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            raise TimeoutError(
                                f"request {rid} timed out")
                        self._work.wait(timeout=min(remaining, 1.0))
                        continue
                if delta:
                    sent += len(delta)
                    yield delta
                if finished:
                    return
        finally:
            with self._work:
                left = self._watchers.get(rid, 0) - 1
                if left > 0:
                    self._watchers[rid] = left
                else:
                    self._watchers.pop(rid, None)
            if not finished and left <= 0:
                # timeout / failure / client gone — and no OTHER
                # consumer (a retried resume) still attached
                self._forget(rid)

    def _forget_if_unwatched(self, rid: int) -> None:
        """_Stream.close()'s forget: skipped while another consumer
        (a retried handoff resume) is still attached to the rid."""
        with self._work:
            if self._watchers.get(rid, 0) > 0:
                return
        self._forget(rid)

    def _reap_orphans(self) -> None:
        """Decode-role reaper thread: an adopted handoff whose consumer
        never attached (the gateway crashed mid-resume, or phase 2
        exhausted its retries — the pusher's 'decode side owns an
        orphan now' case) would otherwise decode to completion and park
        its result, ledger and rid maps forever. Whatever is still
        armed in _handoff_deadline past its TTL is dropped: unclaimed
        live requests are cancelled out of the engine (terminal
        ``cancelled``, exactly once), consumed finals just leave the
        re-fetch cache."""
        period = min(5.0, max(0.1, self._adopt_ttl_s / 4.0))
        while not self._stop_event.wait(period):
            expired: list = []
            with self._work:
                if not self._handoff_deadline:
                    continue
                now = time.monotonic()
                for rid, dl in list(self._handoff_deadline.items()):
                    if now <= dl:
                        continue
                    self._handoff_deadline.pop(rid, None)
                    if self._adopted_final.pop(rid, None) is not None:
                        self._adopted.pop(rid, None)
                    else:
                        expired.append(rid)
            for rid in expired:
                self._forget(rid)

    def shutdown(self) -> None:
        """Stop the loop deterministically, INCLUDING during an
        in-progress recovery: ``_stop`` + the event interrupt the
        backoff/rebuild wait, and the recovery thread — seeing _stop —
        drains its captured requests as ``failed`` (exactly once) and
        marks the loop terminally failed instead of restoring into an
        engine nobody will tick (the drain-during-shutdown race)."""
        with self._work:
            self._stop = True
            self._work.notify_all()
        self._stop_event.set()
        self._thread.join(timeout=5)
        if self._monitor_thread is not None:
            self._monitor_thread.join(timeout=5)
        if self._handoff_thread is not None:
            self._handoff_thread.join(timeout=5)
        if self._orphan_thread is not None:
            self._orphan_thread.join(timeout=5)


class _Stream:
    """Iterator over a streamed request whose ``close()`` is safe in
    every state: a started generator runs its finally; a NEVER-started
    one (e.g. response headers failed before the first frame) gets the
    explicit idempotent ``_forget`` so the submitted request cannot leak
    into the engine's done-table."""

    def __init__(self, loop: "ServingLoop", rid: int, gen):
        self._loop = loop
        self.rid = rid
        self._gen = gen

    def __iter__(self):
        return self

    def __next__(self):
        return next(self._gen)

    def close(self) -> None:
        self._gen.close()
        self._loop._forget_if_unwatched(self.rid)


def build_engine(cfg: ServerConfig):
    """Load params (checkpoint / int8, shared with cmd/generate.py) and
    build the continuous-batching engine."""
    from nos_tpu.cmd.generate import GenerateConfig, load_params
    from nos_tpu.models.serving import DecodeServer

    # config errors must fire BEFORE the (multi-GB) checkpoint load
    if cfg.prefill_chunk and (cfg.prefill_chunk < 8 or
                              cfg.prefill_chunk & (cfg.prefill_chunk - 1)):
        raise ValueError(
            f"prefill_chunk must be 0 or a power of two >= 8, got "
            f"{cfg.prefill_chunk}")
    if cfg.prefill_budget < 0:
        raise ValueError(
            f"prefill_budget must be >= 0, got {cfg.prefill_budget}")
    if cfg.prefill_budget and not cfg.prefill_chunk:
        raise ValueError(
            "prefill_budget requires chunked prefill (set "
            "prefill_chunk): the budget schedules chunk forwards, and "
            "without chunking there is nothing to budget")
    if cfg.pipeline_depth < 1:
        raise ValueError(
            f"pipeline_depth must be >= 1, got {cfg.pipeline_depth}")
    if cfg.decode_steps < 1:
        raise ValueError(
            f"decode_steps must be >= 1, got {cfg.decode_steps}")
    if cfg.kv_dtype not in ("bf16", "int8"):
        raise ValueError(
            f"kv_dtype must be bf16|int8, got {cfg.kv_dtype!r}")
    if cfg.kv_dtype == "int8" and not cfg.kv_blocks:
        raise ValueError(
            "kv_dtype=int8 requires the paged KV cache: set "
            "kv_blocks/kv_block_size (the slot-static engine has no "
            "per-block scale storage, so int8 KV is not supported "
            "there) — or run kv_dtype=bf16")
    if cfg.paged_kernel not in ("on", "off"):
        raise ValueError(
            f"paged_kernel must be on|off, got {cfg.paged_kernel!r}")
    # plumbed by env so every trace site (base + speculative engines,
    # and the supervisor's rebuild factory, which re-enters here) sees
    # one authoritative answer; set BEFORE the engine compiles. The
    # kernel walks per-slot block tables, so on a slot-static engine
    # (kv_blocks=0) the fleet-default "on" is INERT rather than an
    # error — the default flip must not break every non-paged config.
    os.environ["NOS_TPU_PAGED_KERNEL"] = \
        "1" if (cfg.paged_kernel == "on" and cfg.kv_blocks) else "0"
    if cfg.draft_checkpoint_dir and cfg.draft_n_tokens < 1:
        raise ValueError(
            f"draft_n_tokens must be >= 1, got {cfg.draft_n_tokens}")
    if cfg.kv_blocks:
        bs = cfg.kv_block_size
        if bs < 8 or bs & (bs - 1):
            raise ValueError(
                f"kv_block_size must be a power of two >= 8 when "
                f"kv_blocks is set, got {bs}")
        if cfg.max_seq % bs:
            raise ValueError(
                f"max_seq {cfg.max_seq} must be a multiple of "
                f"kv_block_size {bs}")
        if cfg.kv_blocks < 2:
            raise ValueError(
                f"kv_blocks must be >= 2 (one reserved null block plus "
                f"at least one usable), got {cfg.kv_blocks}")
    if cfg.role not in ("colocated", "prefill", "decode"):
        raise ValueError(
            f"role must be colocated|prefill|decode, got {cfg.role!r}")
    if cfg.role != "colocated" and not cfg.kv_blocks:
        raise ValueError(
            f"role={cfg.role} requires the paged KV cache (set "
            f"kv_blocks/kv_block_size): the prefill->decode handoff "
            f"payload is the paged swap format — quantized blocks + "
            f"per-block scales — which the slot-static engine cannot "
            f"produce or adopt")
    if cfg.role == "prefill" and not cfg.decode_pool.strip():
        raise ValueError(
            "role=prefill requires --decode-pool (comma-separated "
            "decode-replica base URLs): a prefill server with nowhere "
            "to ship its handoffs would strand every request after "
            "its first token")
    if cfg.role == "prefill" and cfg.draft_checkpoint_dir:
        raise ValueError(
            "role=prefill with speculative decoding is pointless: a "
            "prefill replica never decodes, so the draft would only "
            "burn HBM — run the draft on the decode side "
            "(role=decode re-prefills it from each adopted handoff) "
            "or colocated")
    if cfg.kv_host_tier_bytes < 0:
        raise ValueError(
            f"kv_host_tier_bytes must be >= 0, got "
            f"{cfg.kv_host_tier_bytes}")
    if cfg.kv_host_tier_bytes and not (cfg.kv_blocks
                                       and cfg.prefix_cache_size):
        raise ValueError(
            "kv_host_tier_bytes requires the paged KV cache with a "
            "prefix cache (set kv_blocks/kv_block_size AND "
            "prefix_cache_size): the host tier stores demoted prefix "
            "chains, which only the paged prefix index produces — "
            "without one there is nothing to demote")
    mesh = None
    if cfg.tp and cfg.tp > 1:
        import jax
        from jax.sharding import Mesh

        from nos_tpu.models.transformer import param_shardings
        from nos_tpu.parallel.mesh import arrange_devices

        devs = jax.devices()
        if len(devs) < cfg.tp:
            raise ValueError(
                f"tp={cfg.tp} but only {len(devs)} devices visible")
        kv = cfg.n_kv_heads or cfg.n_heads
        if kv % cfg.tp:
            raise ValueError(
                f"kv_heads {kv} not divisible by tp={cfg.tp}; the "
                f"cache head axis cannot shard evenly")
        if cfg.draft_checkpoint_dir:
            dkv = cfg.draft_n_kv_heads or cfg.draft_n_heads
            if dkv % cfg.tp:
                raise ValueError(
                    f"draft kv_heads {dkv} not divisible by tp={cfg.tp}; "
                    f"the draft cache head axis cannot shard evenly")
        # snake-walked placement: tp neighbours one ICI hop apart, same
        # contract the trainer's mesh gets (parallel/mesh.py)
        mesh = Mesh(arrange_devices(devs[:cfg.tp], (cfg.tp,)), ("tp",))

    # request-level elastic quota: parsed HERE so the supervisor's
    # rebuild factory re-creates a tenant-aware engine from the same
    # config (a restart must not silently drop tenancy)
    tenant_quota = TenantQuotaConfig.load(cfg.tenant_config)
    # host-RAM KV tier (built per engine: a supervised rebuild starts
    # with an EMPTY tier — its content was host process state tied to
    # the failed engine's arena geometry, and demotions refill it)
    host_tier = None
    if cfg.kv_host_tier_bytes:
        from nos_tpu.kvfabric import HostTierStore

        host_tier = HostTierStore(cfg.kv_host_tier_bytes)
    gcfg = GenerateConfig(
        vocab=cfg.vocab, d_model=cfg.d_model, n_layers=cfg.n_layers,
        n_heads=cfg.n_heads, n_kv_heads=cfg.n_kv_heads, d_ff=cfg.d_ff,
        max_seq=cfg.max_seq, n_experts=cfg.n_experts, bf16=cfg.bf16,
        checkpoint_dir=cfg.checkpoint_dir, int8=cfg.int8, seed=cfg.seed)
    model_cfg, params = load_params(gcfg)
    if mesh is not None:
        if cfg.int8:
            from nos_tpu.models.quant import quant_param_shardings

            shardings = quant_param_shardings(mesh, model_cfg)
        else:
            shardings = param_shardings(mesh, model_cfg)
        params = jax.device_put(params, shardings)
    if cfg.draft_checkpoint_dir:
        from nos_tpu.models.spec_serving import SpeculativeDecodeServer

        dcfg_in = GenerateConfig(
            vocab=cfg.vocab, d_model=cfg.draft_d_model,
            n_layers=cfg.draft_n_layers, n_heads=cfg.draft_n_heads,
            n_kv_heads=cfg.draft_n_kv_heads, d_ff=cfg.draft_d_ff,
            max_seq=cfg.max_seq, bf16=cfg.bf16,
            checkpoint_dir=cfg.draft_checkpoint_dir, seed=cfg.seed)
        draft_cfg, draft_params = load_params(dcfg_in)
        if mesh is not None:
            draft_params = jax.device_put(
                draft_params, param_shardings(mesh, draft_cfg))
        return SpeculativeDecodeServer(
            params, model_cfg, draft_params, draft_cfg,
            n_draft=cfg.draft_n_tokens, max_batch=cfg.max_batch,
            prefix_cache_size=cfg.prefix_cache_size, mesh=mesh,
            prefill_chunk=cfg.prefill_chunk, max_pending=cfg.max_pending,
            # the speculative engine rides the full dispatch template:
            # pipelined windows, fused rounds, paged + int8 KV all apply
            pipeline_depth=cfg.pipeline_depth,
            decode_steps=cfg.decode_steps,
            kv_block_size=cfg.kv_block_size, kv_blocks=cfg.kv_blocks,
            kv_swap=cfg.kv_swap, hbm_admit_frac=cfg.kv_hbm_admit_frac,
            kv_dtype=cfg.kv_dtype, tenant_quota=tenant_quota,
            role=cfg.role, host_tier=host_tier,
            prefill_budget=cfg.prefill_budget)
    return DecodeServer(params, model_cfg, max_batch=cfg.max_batch,
                        prefix_cache_size=cfg.prefix_cache_size, mesh=mesh,
                        prefill_chunk=cfg.prefill_chunk,
                        max_pending=cfg.max_pending,
                        pipeline_depth=cfg.pipeline_depth,
                        decode_steps=cfg.decode_steps,
                        kv_block_size=cfg.kv_block_size,
                        kv_blocks=cfg.kv_blocks, kv_swap=cfg.kv_swap,
                        hbm_admit_frac=cfg.kv_hbm_admit_frac,
                        kv_dtype=cfg.kv_dtype,
                        tenant_quota=tenant_quota, role=cfg.role,
                        host_tier=host_tier,
                        prefill_budget=cfg.prefill_budget)


def make_http_server(cfg: ServerConfig, loop: ServingLoop
                     ) -> ThreadingHTTPServer:
    class Handler(BaseHTTPRequestHandler):
        # http.server applies this to the connection socket in setup();
        # a stalled read/write raises TimeoutError instead of pinning a
        # non-daemon handler thread past the drain window (see
        # ServerConfig.socket_timeout_s)
        timeout = cfg.socket_timeout_s or None

        def log_message(self, fmt, *args):      # route through logging
            logger.debug("http: " + fmt, *args)

        def _reply(self, code: int, body: dict, headers=()) -> None:
            data = json.dumps(body).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            for name, value in headers:
                self.send_header(name, value)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            if self.path == "/healthz":
                ok = loop.healthy
                self._reply(200 if ok else 500,
                            {"status": "ok" if ok else "unhealthy"})
            elif self.path == "/readyz":
                # draining flips readiness first: the Service stops
                # routing new traffic here while in-flight requests
                # finish. A supervised recovery reports ``degraded`` —
                # also 503, so the Service pulls the endpoint for the
                # restart window — while /healthz stays green (only a
                # TERMINAL, budget-exhausted failure flips it).
                if loop.draining:
                    self._reply(503, {"status": "draining"})
                elif loop.recovering:
                    self._reply(503, {"status": "degraded"},
                                headers=[("Retry-After", "1")])
                else:
                    self._reply(200, {"status": "ok"})
            elif self.path == "/metrics":
                # content-negotiated like every daemon (cmd/serve.py):
                # an openmetrics Accept gets exemplar-bearing buckets,
                # so TTFT/TPOT drill down to concrete request traces
                text, ctype = metrics_payload(
                    self.headers.get("Accept", ""))
                body = text.encode()
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif self.path == "/stats":
                # live engine introspection: active slots, pending
                # queue, pipeline window, prefix cache, SLO/goodput,
                # rolling rates — the operator's first stop before
                # metrics history or traces
                self._reply(200, loop.stats())
            elif self.path.startswith("/v1/result/"):
                # decode-role unary attach: the full sequence of an
                # adopted handoff once it finishes (gateway phase 2)
                try:
                    rid = int(self.path.rsplit("/", 1)[1].split("?")[0])
                    tokens = loop.result(rid, timeout=cfg.drain_timeout_s
                                         + 270.0)
                except ValueError as e:
                    self._reply(404, {"error": str(e),
                                      "reason": "unknown_rid"})
                    return
                except DeadlineExceeded as e:
                    self._reply(504, {"error": str(e),
                                      "deadline_exceeded": True})
                    return
                except TimeoutError as e:
                    self._reply(503, {"error": str(e),
                                      "reason": "timeout"})
                    return
                except Exception as e:  # noqa: BLE001 — JSON 500
                    self._reply(500, {"error": f"{type(e).__name__}: {e}"})
                    return
                self._reply(200, {"tokens": tokens})
            elif self.path.startswith("/v1/stream/"):
                # decode-role streaming attach: SSE over an adopted
                # handoff's remaining tokens
                try:
                    rid = int(self.path.rsplit("/", 1)[1].split("?")[0])
                    gen = loop.watch(rid)
                except ValueError as e:
                    self._reply(404, {"error": str(e),
                                      "reason": "unknown_rid"})
                    return
                self._stream_sse(gen)
            elif self.path.startswith("/v1/kvchain/"):
                # KV-fabric peer pull: the codec payload of one prefix
                # chain by fleet digest, served raw (octet-stream, not
                # JSON — it IS the handoff wire format) from this
                # replica's HBM index or host tier. 404 means the
                # chain aged out since the gateway's last /stats
                # scrape; the puller just prefills. Fleet-internal:
                # only peer replicas ever call this, and chain digests
                # are public arithmetic over scope + tokens, so an
                # ungated export would hand any client another
                # tenant's KV bytes plus a 200-vs-404 cache-residency
                # oracle (the ISSUE 13 side channel) — hence the
                # shared-token gate, closed when no token is set.
                digest = self.path.rsplit("/", 1)[1].split("?")[0]
                # the holder's side of a peer pull, parented into the
                # puller's kvfabric.pull. Recorded only when the pull
                # carries a trace — tokenless probes must not be able
                # to mint fresh roots into the flight recorder.
                inbound_tp = self.headers.get("traceparent")
                ssp = tracing.start_span(
                    "kvfabric.serve", component="kvfabric",
                    parent=inbound_tp,
                    attrs={"digest": digest}) if inbound_tp \
                    else tracing.NOOP_SPAN
                if not cfg.kv_fabric_token or self.headers.get(
                        FABRIC_TOKEN_HEADER) != cfg.kv_fabric_token:
                    ssp.set_attr("outcome", "denied")
                    ssp.end()
                    self._reply(403, {"error": "kv fabric token "
                                      "required",
                                      "reason": "fabric_token"})
                    return
                try:
                    data = loop.export_chain(digest)
                except Exception as e:  # noqa: BLE001 — JSON 500
                    ssp.set_attr("outcome", "error")
                    ssp.set_error(str(e))
                    ssp.end()
                    self._reply(500, {"error": f"{type(e).__name__}: {e}"})
                    return
                if data is None:
                    ssp.set_attr("outcome", "miss")
                    ssp.end()
                    self._reply(404, {"error": "unknown chain",
                                      "digest": digest})
                    return
                ssp.set_attr("outcome", "served")
                ssp.set_attr("nbytes", len(data))
                ssp.end()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "application/octet-stream")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
            elif self.path == "/debug/traces":
                self._reply(200, tracing.recorder().to_json())
            elif self.path.startswith("/debug/traces/"):
                tid = self.path.rsplit("/", 1)[1]
                spans = tracing.recorder().trace(tid)
                if not spans:
                    self._reply(404, {"error": "unknown trace",
                                      "trace_id": tid})
                else:
                    self._reply(200, {
                        "trace_id": tid,
                        "spans": [sp.to_dict() for sp in spans],
                    })
            elif self.path.startswith("/debug/profile"):
                # Perfetto/chrome trace of the last N decode ticks
                # decomposed into phases — save the body to a file and
                # open it at ui.perfetto.dev. ?ticks=N bounds the
                # window (default 64, capped at the phase ring).
                n = 64
                if "?" in self.path:
                    try:
                        from urllib.parse import parse_qs, urlsplit
                        q = parse_qs(urlsplit(self.path).query)
                        n = int(q.get("ticks", ["64"])[0])
                    except (ValueError, IndexError):
                        self._reply(400, {"error": "ticks must be an "
                                          "integer"})
                        return
                self._reply(200, loop.profile_trace(last_n=n))
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})

        def _stream_sse(self, gen) -> None:
            """Server-sent events: one ``data: {"tokens": [...]}`` frame
            per decode batch, ``data: [DONE]`` terminator (the OpenAI
            streaming convention, token-ids instead of text). Mid-stream
            failures become an SSE error frame — the 200 is already on
            the wire, so a clean in-band error beats a dropped
            connection. Fully self-contained: every exit path closes the
            stream (dropping the server-side request — ``_Stream.close``
            is safe even before the first frame, covering a disconnect
            during header send) and nothing escapes to do_POST, whose
            JSON error arms must never write a second status line onto a
            committed SSE response."""
            try:
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.send_header("Connection", "close")
                self.end_headers()
                for delta in gen:
                    self.wfile.write(
                        b"data: " + json.dumps({"tokens": delta}).encode()
                        + b"\n\n")
                    self.wfile.flush()
                self.wfile.write(b"data: [DONE]\n\n")
            except OSError:             # client went away (BrokenPipe, reset)
                pass
            except (TimeoutError, RuntimeError) as e:
                # in-band error frame, then the normal terminator: clients
                # must always be able to read to [DONE] and distinguish a
                # server-reported failure from a dropped connection
                try:
                    self.wfile.write(
                        b"data: " + json.dumps(
                            {"error": f"{type(e).__name__}: {e}"}).encode()
                        + b"\n\ndata: [DONE]\n\n")
                except OSError:
                    pass
            finally:
                gen.close()

        def do_POST(self):
            if self.path == "/admin/drain":
                # the fleet controller's graceful scale-down hook:
                # stop admitting (readyz flips to draining, the
                # Service pulls the endpoint), let in-flight requests
                # finish; the pod is deleted once /stats reports no
                # work (or the controller's drain budget expires and
                # deletion's SIGTERM path owns the tail). Shares the
                # serving port's trust domain (no auth, like the rest
                # of this surface) — hence reversible via
                # /admin/undrain rather than a one-way latch.
                loop.begin_drain()
                self._reply(200, {"status": "draining"})
                return
            if self.path == "/admin/undrain":
                loop.cancel_drain()
                self._reply(200, {"status": "ok"})
                return
            if self.path == "/v1/handoff":
                # decode-role ingest: one encoded handoff payload ->
                # adopted rid (restored byte-exact into the engine)
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    rid = loop.adopt(self.rfile.read(length))
                except Infeasible as e:
                    self._reply(400, {"error": f"{type(e).__name__}: {e}",
                                      "infeasible": True,
                                      "reason": e.reason})
                    return
                except EngineRecovering as e:
                    self._reply(503, {"error": str(e),
                                      "reason": "recovering"},
                                headers=[("Retry-After", "1")])
                    return
                except DrainingError as e:
                    self._reply(503, {"error": str(e),
                                      "reason": "draining"})
                    return
                except Exception as e:  # noqa: BLE001 — JSON 500
                    self._reply(500, {"error": f"{type(e).__name__}: {e}"})
                    return
                self._reply(200, {"rid": rid})
                return
            if self.path != "/v1/generate":
                self._reply(404, {"error": f"unknown path {self.path}"})
                return
            try:
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                prompt = [int(t) for t in body["prompt"]]
                n = int(body.get("max_new_tokens",
                                 cfg.default_max_new_tokens))
                sampling = {}
                if "temperature" in body:
                    sampling["temperature"] = float(body["temperature"])
                if "top_k" in body:
                    sampling["top_k"] = int(body["top_k"])
                if "top_p" in body:
                    sampling["top_p"] = float(body["top_p"])
                if "seed" in body:
                    sampling["seed"] = int(body["seed"])
                if "stop_tokens" in body:
                    sampling["stop_tokens"] = [
                        int(t) for t in body["stop_tokens"]]
                if "priority" in body:
                    # paged-KV preemption order: under block pressure
                    # the LOWEST priority slot yields first
                    sampling["priority"] = int(body["priority"])
                if "cache_prefix" in body:
                    # mark this prompt's KV as a reusable prefix (system
                    # prompts); reuse is automatic on every request.
                    # Strict type check: bool("false") is True, and a
                    # mistyped string would silently pin device memory
                    if not isinstance(body["cache_prefix"], bool):
                        raise ValueError(
                            "cache_prefix must be a JSON boolean")
                    sampling["cache_prefix"] = body["cache_prefix"]
                # request-level elastic-quota identity: body field
                # wins, X-Tenant header second; absent = the default
                # tenant. Validated (it becomes a metric label and a
                # prefix-cache scope); a tenant at/over its max
                # token-rate under contention sheds 429
                # reason=tenant_quota.
                tenant = body.get("tenant",
                                  self.headers.get("X-Tenant"))
                if tenant is not None:
                    sampling["tenant"] = validate_tenant_name(
                        str(tenant))
                # per-request completion deadline: body field wins,
                # header second, server default (--default-deadline-s)
                # last. Unmeetable -> 429 + Retry-After (shed early),
                # expired mid-flight -> 504 outcome=deadline.
                deadline = body.get(
                    "deadline_s", self.headers.get("X-Request-Deadline-S"))
                if deadline is not None:
                    sampling["deadline_s"] = float(deadline)
                # inbound W3C trace context: the request's
                # serve.request span ADOPTS the caller's trace (the
                # gateway attempt's) instead of minting a fresh one —
                # malformed values degrade to a fresh root inside
                # tracing, never to an error
                inbound_tp = self.headers.get("traceparent")
                if inbound_tp:
                    sampling["traceparent"] = inbound_tp
                if body.get("kv_sources"):
                    # gateway-attached KV-fabric peer offers: pull the
                    # named chain(s) from peer replicas BEFORE submit,
                    # so this request's prefix match hits warm.
                    # Best-effort by design — any failure just means a
                    # normal prefill (prefetch_chain never raises).
                    # Honored ONLY with the fleet's shared fabric
                    # token: an offer steers this replica's outbound
                    # fetcher (SSRF) and seeds its prefix cache
                    # (poisoning), so client-supplied ones are counted
                    # and dropped — the gateway strips the field from
                    # client bodies and stamps the token on its own.
                    if cfg.kv_fabric_token and self.headers.get(
                            FABRIC_TOKEN_HEADER) == cfg.kv_fabric_token:
                        loop.prefetch_chain(
                            body["kv_sources"], sampling.get("tenant"),
                            deadline_s=sampling.get("deadline_s"),
                            parent=inbound_tp)
                    else:
                        srcs = body["kv_sources"]
                        loop.note_pull_denied(
                            digest=(srcs[0].get("digest")
                                    if isinstance(srcs, list) and srcs
                                    and isinstance(srcs[0], dict)
                                    else None),
                            parent=inbound_tp)
                if cfg.role == "prefill":
                    # prefill role: the answer is a handoff descriptor
                    # ({"handoff": {"target", "rid"}}) the gateway
                    # follows to the decode replica's /v1/result or
                    # /v1/stream — or plain tokens when the first
                    # token already completed the request. The
                    # ``stream`` flag is irrelevant here: streaming
                    # happens at the decode replica.
                    self._reply(200, loop.prefill(prompt, n, **sampling))
                    return
                if body.get("stream"):
                    # stream() submits eagerly, so validation errors land
                    # in the except arms below as a clean JSON 4xx —
                    # headers are only committed once the request is in
                    gen = loop.stream(prompt, n, **sampling)
                    self._stream_sse(gen)
                    return
                tokens = loop.generate(prompt, n, **sampling)
            except Infeasible as e:
                # permanent: the request can NEVER run here (prompt +
                # budget exceeds the cache, or needs more KV blocks
                # than the whole pool) — 400 with no Retry-After, so
                # clients fix the request instead of hammering it
                self._reply(400, {"error": f"{type(e).__name__}: {e}",
                                  "infeasible": True,
                                  "reason": e.reason})
                return
            except (KeyError, ValueError, TypeError) as e:
                self._reply(400, {"error": f"{type(e).__name__}: {e}",
                                  "reason": "bad_request"})
                return
            except QueueFull as e:
                # transient: out of capacity RIGHT NOW — 429 +
                # Retry-After says come back. ``reason`` splits the
                # shed causes machine-readably (queue_full = slots or
                # the waiting line; hbm_admission = free slots but the
                # KV pool / HBM headroom blocks admission;
                # deadline_unmeetable = the rolling latency estimates
                # say the client's deadline cannot be met): the fleet
                # controller scales on capacity pressure, not on
                # deadline pressure, and must tell them apart
                self._reply(429, {"error": str(e), "reason": e.reason},
                            headers=[("Retry-After", "1")])
                return
            except DeadlineExceeded as e:
                # the request was admitted but its deadline expired
                # mid-flight: cancelled at the tick barrier, terminal
                # outcome ``deadline``
                self._reply(504, {"error": str(e),
                                  "deadline_exceeded": True})
                return
            except EngineRecovering as e:
                # supervised restart in flight: same wire shape as
                # QueueFull (Retry-After) but 503 — the SERVER is
                # briefly degraded, not the client over capacity.
                # ``reason`` makes the 503 family machine-readable for
                # the gateway's retry policy (a recovering replica is
                # worth a short backoff; a draining one never is)
                self._reply(503, {"error": str(e),
                                  "reason": "recovering"},
                            headers=[("Retry-After", "1")])
                return
            except DrainingError as e:
                self._reply(503, {"error": str(e),
                                  "reason": "draining"})
                return
            except TimeoutError as e:
                self._reply(503, {"error": str(e),
                                  "reason": "timeout"})
                return
            except Exception as e:  # decode-loop death → JSON 500, not a dropped conn
                self._reply(500, {"error": f"{type(e).__name__}: {e}"})
                return
            self._reply(200, {"tokens": tokens})

    class Server(ThreadingHTTPServer):
        # handler threads outlive shutdown(): after a drain declares the
        # ENGINE idle, the thread delivering the final response may still
        # be between its last wakeup and the socket write — non-daemon
        # threads make interpreter exit wait for that write instead of
        # killing it (the connection-reset the drain exists to prevent).
        # Bounded: loop.shutdown() fails any still-waiting request, and
        # Handler.timeout bounds threads blocked on the socket itself
        # (e.g. reading a stalled client's request body), so every
        # handler thread exits within ~socket_timeout_s of the main
        # loop's finally.
        daemon_threads = False

    return Server(("0.0.0.0", cfg.port), Handler)


def main(argv: Optional[Sequence[str]] = None) -> None:
    parser = argparse.ArgumentParser(prog="nos-tpu-server",
                                     description=__doc__)
    parser.add_argument("--config", default="", help="server config YAML")
    parser.add_argument("--checkpoint-dir", default="")
    parser.add_argument("--port", type=int, default=None)
    parser.add_argument(
        "--pipeline-depth", type=int, default=None,
        help="decode ticks in flight before the host blocks on a token "
             "fetch (1 = host-serial; overrides config)")
    parser.add_argument(
        "--decode-steps", type=int, default=None,
        help="decode steps fused into one compiled dispatch "
             "(1 = off; overrides config)")
    parser.add_argument(
        "--prefill-chunk", type=int, default=None,
        help="chunked prefill chunk size in prompt tokens (0 = off "
             "[default]; power of two >= 8; a long prompt's prefill "
             "runs chunk-at-a-time interleaved with decode ticks "
             "instead of one monolithic forward; overrides config)")
    parser.add_argument(
        "--prefill-budget", type=int, default=None,
        help="per-tick chunked-prefill budget in prompt tokens (0 = "
             "the unconditional one-chunk-per-tick rule [default]; "
             "requires --prefill-chunk; overrides config): each "
             "decode tick spends at most this many prompt tokens on "
             "chunk forwards, chosen by deadline slack (EDF on "
             "estimated TTFT; clamps to zero while a decode slot's "
             "TPOT slack is negative) so colocated decode TPOT holds "
             "flat under long-prompt admission storms. Outputs stay "
             "token-identical to the unbudgeted run; echoed in "
             "/stats config for fleet drift detection")
    parser.add_argument(
        "--kv-block-size", type=int, default=None,
        help="paged-KV block size in tokens (power of two >= 8 "
             "dividing max_seq; only meaningful with --kv-blocks; "
             "overrides config)")
    parser.add_argument(
        "--kv-blocks", type=int, default=None,
        help="paged-KV pool size in blocks (0 = slot-static KV; the "
             "resident KV budget is kv_blocks * kv_block_size tokens; "
             "overrides config)")
    parser.add_argument(
        "--kv-swap", choices=("on", "off"), default=None,
        help="block-pressure preemption mode: on = swap the victim's "
             "KV to host and restore byte-exact, off = recompute it "
             "from the tokens on resume (overrides config)")
    parser.add_argument(
        "--kv-dtype", choices=("bf16", "int8"), default=None,
        help="paged-KV storage dtype (overrides config): int8 "
             "quantizes KV on write with per-block scales — ~2x the "
             "blocks per HBM byte, ~2x sustained paged concurrency — "
             "and requires --kv-blocks (the slot-static engine has no "
             "scale storage; rejected with a clear error)")
    parser.add_argument(
        "--kv-host-tier-bytes", type=int, default=None,
        help="host-RAM KV tier capacity in bytes (0 = off [default]; "
             "overrides config; requires --kv-blocks and a prefix "
             "cache). Prefix chains evicted from the HBM arena under "
             "block pressure DEMOTE here instead of dropping, and a "
             "later prefix miss that matches a stored chain PROMOTES "
             "it back via the batched restore scatter, bit-exact. "
             "Also backs GET /v1/kvchain/<digest> so gateway peer "
             "pulls can warm other replicas from this tier")
    parser.add_argument(
        "--kv-fabric-token", default=None,
        help="shared fleet secret gating the KV fabric's HTTP "
             "surfaces (empty = disabled [default]; overrides "
             "config): kv_sources peer-pull offers are only honored "
             "and GET /v1/kvchain/<digest> only served when the "
             "request's X-NOS-KV-Fabric-Token header matches. Set "
             "the SAME value on every replica and on the gateway's "
             "--kv-fabric-token")
    parser.add_argument(
        "--paged-kernel", choices=("on", "off"), default=None,
        help="paged attention formulation (overrides config): on "
             "[default] = the fused Pallas kernel for every query "
             "shape — decode steps, speculative verify bursts, "
             "prefix-hit suffix prefill (in-kernel block-table walk, "
             "int8 dequant fused into the attention inner loop — no "
             "materialized gather; inert without --kv-blocks), off = "
             "the XLA gather formulation (the escape hatch and the "
             "parity oracle). Plumbed as NOS_TPU_PAGED_KERNEL; "
             "echoed in /stats config for fleet drift detection")
    parser.add_argument(
        "--role", choices=("colocated", "prefill", "decode"),
        default=None,
        help="prefill/decode disaggregation role (overrides config): "
             "colocated = one engine prefills and decodes (default); "
             "prefill = requests leave after their first token as a "
             "KV handoff shipped round-robin to --decode-pool "
             "(requires --kv-blocks; int8 KV halves handoff bytes); "
             "decode = adopts handoffs via POST /v1/handoff and "
             "serves /v1/result//v1/stream (requires --kv-blocks and "
             "the same kv geometry as the prefill peers). Echoed in "
             "/stats config for fleet drift detection")
    parser.add_argument(
        "--decode-pool", default=None,
        help="comma-separated decode-replica base URLs a prefill-role "
             "server ships handoffs to (required with --role=prefill; "
             "overrides config)")
    parser.add_argument(
        "--handoff-cooldown-s", type=float, default=None,
        help="seconds a decode replica is skipped by the handoff "
             "pusher after a failed push before the round-robin "
             "retries it (0 = re-probe every time; skips counted in "
             "nos_tpu_serve_handoff_skipped_total; overrides config)")
    parser.add_argument(
        "--handoff-health-interval-s", type=float, default=None,
        help="decode-pool health-view refresh cadence in seconds for "
             "a --role=prefill server's handoff pusher (0 = off "
             "[default]; overrides config): the pusher scrapes each "
             "decode target's /stats at most this often and prefers "
             "healthy, least-loaded replicas — a draining replica is "
             "skipped before the first failed attempt (counted in "
             "nos_tpu_serve_handoff_skipped_total) instead of being "
             "discovered by one")
    parser.add_argument(
        "--draft-checkpoint-dir", default=None,
        help="enable speculative decoding: checkpoint of the draft "
             "model that proposes --draft-n-tokens per verify window "
             "(draft dims come from the config file; overrides config)")
    parser.add_argument(
        "--draft-n-tokens", type=int, default=None,
        help="speculative proposals per verify window (>= 1; only "
             "meaningful with --draft-checkpoint-dir; overrides config)")
    parser.add_argument(
        "--slo-ttft-ms", type=float, default=None,
        help="time-to-first-token SLO target in ms (0 = unset; feeds "
             "nos_tpu_serve_slo_total and the goodput gauge; overrides "
             "config)")
    parser.add_argument(
        "--slo-tpot-ms", type=float, default=None,
        help="mean time-per-output-token SLO target in ms (0 = unset; "
             "overrides config)")
    parser.add_argument(
        "--slo-fast-window-s", type=float, default=None,
        help="fast burn-rate window in seconds for per-tenant SLO "
             "error budgets (active only when the tenant config "
             "carries slo objectives; overrides config)")
    parser.add_argument(
        "--slo-slow-window-s", type=float, default=None,
        help="slow burn-rate window in seconds (budget-remaining "
             "horizon; overrides config)")
    parser.add_argument(
        "--slo-burn-threshold", type=float, default=None,
        help="fast-window burn rate at/over which a breach trip fires "
             "(emits an slo.breach span and pins the breaching "
             "request's trace; overrides config)")
    parser.add_argument(
        "--slo-capture-interval-s", type=float, default=None,
        help="minimum seconds between breach-capture trips per "
             "(tenant, objective) — the flight-recorder rate limit "
             "(overrides config)")
    parser.add_argument(
        "--device-stats-interval", type=float, default=None,
        help="seconds between device.memory_stats() samples into the "
             "HBM gauges (0 disables; overrides config)")
    parser.add_argument(
        "--restart-budget", type=int, default=None,
        help="supervised engine restarts allowed over the process "
             "lifetime (0 = engine failure is terminal; overrides "
             "config). On failure, live requests are captured and "
             "resumed bit-exactly into a rebuilt engine")
    parser.add_argument(
        "--watchdog-s", type=float, default=None,
        help="stuck-tick watchdog threshold in seconds (0 = off; "
             "overrides config): a decode tick blocked in its device "
             "wait longer than this counts as an engine failure and "
             "triggers a supervised restart (dispatch-time compiles "
             "don't count — size it above the slowest device wait)")
    parser.add_argument(
        "--tenant-config", default=None,
        help="request-level elastic quota: per-tenant token-rate "
             "min/max with borrowing, as a file path or inline JSON "
             "(empty = tenancy off; overrides config). Requests carry "
             "a tenant via the JSON field / X-Tenant header; admission "
             "becomes the weighted tenant pick, guaranteed tenants "
             "reclaim slots by bit-exact preemption, over-max tenants "
             "shed 429 reason=tenant_quota under contention")
    parser.add_argument(
        "--default-deadline-s", type=float, default=None,
        help="default per-request completion deadline in seconds "
             "(0 = none; overrides config; per-request override via "
             "the deadline_s field / X-Request-Deadline-S header). "
             "Unmeetable deadlines shed at admission (429), expired "
             "ones cancel at the next tick barrier (504)")
    # the fleet-shared observability flags (--log-format plus the
    # --trace-* sampler / flight-recorder knobs), same as every
    # control-plane binary — Helm feeds all daemons from one helper
    from nos_tpu.cmd.serve import observability_flags
    observability_flags(parser)
    args = parser.parse_args(argv)

    cfg = ServerConfig.from_yaml_file(args.config) if args.config \
        else ServerConfig()
    if args.checkpoint_dir:
        cfg.checkpoint_dir = args.checkpoint_dir
    if args.port is not None:
        cfg.port = args.port
    if args.pipeline_depth is not None:
        cfg.pipeline_depth = args.pipeline_depth
    if args.decode_steps is not None:
        cfg.decode_steps = args.decode_steps
    if args.prefill_chunk is not None:
        cfg.prefill_chunk = args.prefill_chunk
    if args.prefill_budget is not None:
        cfg.prefill_budget = args.prefill_budget
    if args.kv_block_size is not None:
        cfg.kv_block_size = args.kv_block_size
    if args.kv_blocks is not None:
        cfg.kv_blocks = args.kv_blocks
    if args.kv_swap is not None:
        cfg.kv_swap = args.kv_swap == "on"
    if args.kv_dtype is not None:
        cfg.kv_dtype = args.kv_dtype
    if args.kv_host_tier_bytes is not None:
        cfg.kv_host_tier_bytes = args.kv_host_tier_bytes
    if args.kv_fabric_token is not None:
        cfg.kv_fabric_token = args.kv_fabric_token
    if args.paged_kernel is not None:
        cfg.paged_kernel = args.paged_kernel
    if args.role is not None:
        cfg.role = args.role
    if args.decode_pool is not None:
        cfg.decode_pool = args.decode_pool
    if args.handoff_cooldown_s is not None:
        cfg.handoff_cooldown_s = args.handoff_cooldown_s
    if args.handoff_health_interval_s is not None:
        cfg.handoff_health_interval_s = args.handoff_health_interval_s
    if args.draft_checkpoint_dir is not None:
        cfg.draft_checkpoint_dir = args.draft_checkpoint_dir
    if args.draft_n_tokens is not None:
        cfg.draft_n_tokens = args.draft_n_tokens
    if args.slo_ttft_ms is not None:
        cfg.slo_ttft_ms = args.slo_ttft_ms
    if args.slo_tpot_ms is not None:
        cfg.slo_tpot_ms = args.slo_tpot_ms
    if args.slo_fast_window_s is not None:
        cfg.slo_fast_window_s = args.slo_fast_window_s
    if args.slo_slow_window_s is not None:
        cfg.slo_slow_window_s = args.slo_slow_window_s
    if args.slo_burn_threshold is not None:
        cfg.slo_burn_threshold = args.slo_burn_threshold
    if args.slo_capture_interval_s is not None:
        cfg.slo_capture_interval_s = args.slo_capture_interval_s
    if args.device_stats_interval is not None:
        cfg.device_stats_interval_s = args.device_stats_interval
    if args.restart_budget is not None:
        cfg.restart_budget = args.restart_budget
    if args.watchdog_s is not None:
        cfg.watchdog_s = args.watchdog_s
    if args.default_deadline_s is not None:
        cfg.default_deadline_s = args.default_deadline_s
    if args.tenant_config is not None:
        cfg.tenant_config = args.tenant_config
    if cfg.restart_budget < 0:
        raise ValueError(
            f"restart_budget must be >= 0, got {cfg.restart_budget}")
    if cfg.watchdog_s < 0 or cfg.default_deadline_s < 0:
        raise ValueError(
            "watchdog_s and default_deadline_s must be >= 0")
    from nos_tpu.cmd import setup_logging as _shared_setup_logging
    _shared_setup_logging(
        0, args.log_format,
        numeric_level=getattr(logging, cfg.log_level.upper(), 20))
    tracing.configure(
        sampling=args.trace_sampling,
        recorder_size=args.trace_recorder_size,
        slow_threshold_s=args.trace_slow_threshold)

    # the supervisor's rebuild path: a fresh engine (fresh compile)
    # from the same config. None when restarts are disabled — engine
    # failure is then terminal exactly as before supervision existed.
    factory = (lambda: build_engine(cfg)) if cfg.restart_budget > 0 \
        else None
    # parsed once more for the LOOP's accounting half (the engine half
    # parses inside build_engine so the supervisor factory carries it);
    # a malformed config fails HERE, before the checkpoint load
    tenant_quota = TenantQuotaConfig.load(cfg.tenant_config)

    def _http_handoff_send(target: str, data: bytes) -> int:
        """Ship one encoded handoff to a decode replica; returns the
        decode-side rid. Errors propagate — the pusher tries the next
        pool target."""
        import urllib.request

        req = urllib.request.Request(
            target.rstrip("/") + "/v1/handoff", data=data,
            headers={"Content-Type": "application/octet-stream"},
            method="POST")
        with urllib.request.urlopen(req, timeout=30) as resp:
            return int(json.loads(resp.read())["rid"])

    decode_pool = [u.strip() for u in cfg.decode_pool.split(",")
                   if u.strip()]
    loop = ServingLoop(
        build_engine(cfg), slo_ttft_ms=cfg.slo_ttft_ms,
        role=cfg.role, handoff_targets=decode_pool,
        handoff_send=(_http_handoff_send if cfg.role == "prefill"
                      else None),
        handoff_cooldown_s=cfg.handoff_cooldown_s,
        handoff_health_interval_s=cfg.handoff_health_interval_s,
        slo_tpot_ms=cfg.slo_tpot_ms,
        device_stats_interval_s=cfg.device_stats_interval_s,
        engine_factory=factory, restart_budget=cfg.restart_budget,
        restart_backoff_s=cfg.restart_backoff_s,
        restart_backoff_max_s=cfg.restart_backoff_max_s,
        watchdog_s=cfg.watchdog_s,
        default_deadline_s=cfg.default_deadline_s, seed=cfg.seed,
        tenant_quota=tenant_quota,
        fabric_token=cfg.kv_fabric_token,
        slo_fast_window_s=cfg.slo_fast_window_s,
        slo_slow_window_s=cfg.slo_slow_window_s,
        slo_burn_threshold=cfg.slo_burn_threshold,
        slo_capture_interval_s=cfg.slo_capture_interval_s,
        # /stats config echo: what the fleet controller compares across
        # replicas to catch config drift between scrapes
        config_echo={
            "max_batch": cfg.max_batch,
            "pipeline_depth": cfg.pipeline_depth,
            "decode_steps": cfg.decode_steps,
            # chunking + the per-tick prefill budget drifting between
            # replicas makes colocated TPOT replica-dependent under
            # the same traffic — surface both in the drift detector
            "prefill_chunk": cfg.prefill_chunk,
            "prefill_budget": cfg.prefill_budget,
            "kv_block_size": cfg.kv_block_size,
            "kv_blocks": cfg.kv_blocks,
            "kv_swap": cfg.kv_swap,
            "kv_dtype": cfg.kv_dtype,
            # host-tier capacity drifting between replicas would skew
            # the gateway's peer-pull economics — same drift detector
            "kv_host_tier_bytes": cfg.kv_host_tier_bytes,
            # whether the fabric HTTP surfaces are token-gated open —
            # a BOOLEAN, never the secret itself: one tokenless
            # replica silently dropping every peer pull is exactly
            # the config drift the echo exists to catch
            "kv_fabric_auth": bool(cfg.kv_fabric_token),
            # kernel drift between replicas would make decode numerics
            # replica-dependent (online-softmax vs gather formulation)
            # — surface it in the same drift detector as every knob
            "paged_kernel": cfg.paged_kernel,
            "speculative": bool(cfg.draft_checkpoint_dir),
            "draft_n_tokens": (cfg.draft_n_tokens
                               if cfg.draft_checkpoint_dir else 0),
            "max_seq": cfg.max_seq,
            # disaggregation role + mesh shape: the gateway routes NEW
            # requests only to prefill/colocated replicas off this
            # echo, and a replica decoding on a drifted mesh (or the
            # wrong role) is exactly the split-brain the fleet drift
            # detector exists to catch
            "role": cfg.role,
            "mesh": {"tp": cfg.tp if cfg.tp and cfg.tp > 1 else 0},
            # tenant quota drifting between replicas would make the
            # fleet's notion of "fair" replica-dependent — surface it
            # in the same drift detector as every other knob
            "tenant_quota": (tenant_quota.echo()
                             if tenant_quota is not None else None),
            # SLO accounting mode proof (ISSUE 20 acceptance): which
            # mode this replica runs — enabled only when the tenant
            # config carries objectives — plus the window/threshold
            # knobs whose drift would make fleet burn rates
            # replica-dependent
            "slo_accounting": {
                "enabled": bool(tenant_quota is not None
                                and tenant_quota.slo_enabled()),
                "fast_window_s": cfg.slo_fast_window_s,
                "slow_window_s": cfg.slo_slow_window_s,
                "burn_threshold": cfg.slo_burn_threshold,
                "capture_interval_s": cfg.slo_capture_interval_s,
            },
        })
    httpd = make_http_server(cfg, loop)

    def _finish_drain():
        drained = loop.wait_idle(cfg.drain_timeout_s)
        logger.info("drain %s; shutting down",
                    "complete" if drained else
                    f"timed out after {cfg.drain_timeout_s:.0f}s")
        httpd.shutdown()        # must come from another thread

    def _on_sigterm(*_):
        logger.info("SIGTERM: draining (budget %.0fs)", cfg.drain_timeout_s)
        loop.begin_drain()
        threading.Thread(target=_finish_drain, daemon=True).start()

    if threading.current_thread() is threading.main_thread():
        import signal

        signal.signal(signal.SIGTERM, _on_sigterm)
    logger.info("serving on :%d (max_batch=%d)", cfg.port, cfg.max_batch)
    try:
        httpd.serve_forever()
    finally:
        # order matters: shutting the loop first fails any still-waiting
        # handler (bounded exit), then server_close joins handler threads
        # (stdlib block_on_close) and releases the listening socket
        loop.shutdown()
        httpd.server_close()


if __name__ == "__main__":
    main()
