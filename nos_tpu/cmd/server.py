"""nos-tpu-server — the serving binary a gang-scheduled inference pod
runs: the continuous-batching engine (models/serving.py) behind a
minimal HTTP API.

    POST /v1/generate   {"prompt": [ids], "max_new_tokens": N,
                         "temperature": T?, "top_k": K?, "top_p": P?,
                         "seed": S?}
                        -> {"tokens": [full sequence]}
    GET  /healthz       -> ok

Requests batch continuously: concurrent POSTs share the engine's decode
ticks (one compiled program per tick serves every active slot), each
blocking only until its own slot completes. Params load exactly like
``nos-tpu-generate`` (checkpoint restore, optional int8).
"""
from __future__ import annotations

import argparse
import json
import logging
import threading
import time
from dataclasses import dataclass, fields
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Sequence

from nos_tpu.models.errors import QueueFull  # jax-free module: keeps this
                                             # file importable without jax
from nos_tpu.utils.metrics import default_registry

logger = logging.getLogger("nos_tpu.server")


@dataclass
class ServerConfig:
    # model (must match the checkpoint's training config)
    vocab: int = 32000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: int = 0
    d_ff: int = 1408
    max_seq: int = 512
    n_experts: int = 0
    bf16: bool = True
    checkpoint_dir: str = ""
    int8: bool = False
    # serving
    max_batch: int = 8
    # admission bound (0 = unbounded): beyond max_batch active slots, at
    # most this many requests wait; past it, POST /v1/generate answers
    # 429 so clients shed load instead of queueing into timeouts
    max_pending: int = 0
    # tensor-parallel serving: shard params (transformer.param_shardings,
    # or quant.quant_param_shardings when int8) and the KV cache
    # (generate.cache_shardings — KV heads over tp) across the first
    # ``tp`` local devices. 0/1 = single device. Tokens are invariant to
    # tp, bf16 and int8 alike (tested); requires kv_heads % tp == 0.
    tp: int = 0
    # prefix-cache entries (0 = off): each holds one prompt's KV on
    # device — budget by model size (flagship: ~64 MB per 1k tokens)
    prefix_cache_size: int = 0
    # chunked prefill (0 = off): power-of-two chunk size; a long
    # prompt's prefill interleaves with decode ticks one chunk per tick,
    # bounding the latency hit admission inflicts on active requests
    # (under speculative decoding the draft cache chunks alongside the
    # target: one target chunk + one cheap draft chunk per tick).
    prefill_chunk: int = 0
    # pipelined decode dispatch: up to this many decode ticks in flight
    # before the host blocks on a token fetch (1 = host-serial). Greedy
    # outputs stay bit-identical to generate() at any depth; streaming
    # granularity coarsens to ~depth*decode_steps tokens per SSE frame.
    # The speculative engine pins this to 1 (its verify burst already
    # amortizes dispatch overhead).
    pipeline_depth: int = 1
    # fused multi-step decode: this many decode steps compiled into ONE
    # dispatch (lax.scan), [batch, decode_steps] tokens per device sync.
    # Pays in decode-bound phases; 1 = off. Pinned to 1 under
    # speculative decoding.
    decode_steps: int = 1
    # speculative decoding (draft_checkpoint_dir set = on): a smaller
    # draft model proposes draft_n_tokens per tick, the target verifies
    # them in one wide forward. Greedy requests stay bit-identical to
    # plain decoding; sampled requests keep the exact target
    # distribution (accept-reject). Draft dims below must match the
    # draft checkpoint's training config.
    draft_checkpoint_dir: str = ""
    draft_d_model: int = 256
    draft_n_layers: int = 2
    draft_n_heads: int = 4
    draft_n_kv_heads: int = 0
    draft_d_ff: int = 704
    draft_n_tokens: int = 4
    default_max_new_tokens: int = 64
    port: int = 8000
    seed: int = 0
    log_level: str = "info"
    # SIGTERM → stop admitting (503 + readyz flips so the Service pulls
    # this endpoint), let in-flight requests finish up to this budget,
    # then exit — the Kubernetes termination contract. Keep it under
    # the pod's terminationGracePeriodSeconds.
    drain_timeout_s: float = 30.0
    # per-socket read/write timeout. daemon_threads=False means process
    # exit JOINS handler threads; without a socket timeout a thread
    # blocked reading a stalled client's request body would outlive the
    # drain budget indefinitely (only SIGKILL would end it). Any blocking
    # socket op now fails within this bound, so exit is bounded by
    # drain_timeout_s + socket_timeout_s.
    socket_timeout_s: float = 30.0

    @classmethod
    def from_yaml_file(cls, path: str) -> "ServerConfig":
        import yaml

        with open(path) as f:
            data = yaml.safe_load(f) or {}
        known = {f.name for f in fields(cls)}
        unknown = set(data) - known
        if unknown:
            raise ValueError(
                f"{path}: unknown server config keys {sorted(unknown)}")
        return cls(**data)


class DrainingError(RuntimeError):
    """Submission refused because the server is draining for termination
    (its own error type so the HTTP layer can answer 503, not 500)."""


class ServingLoop:
    """Thread-safe wrapper around DecodeServer: handlers submit and wait;
    one background thread ticks the engine whenever there is work. A tick
    failure (XLA OOM, device loss) marks the loop unhealthy — /healthz
    flips to 500 so orchestration restarts the pod instead of every
    request silently burning its timeout."""

    def __init__(self, engine):
        reg = default_registry()
        # register() is idempotent per (name, type, labels) and raises on
        # a mismatched re-registration — exactly what we want at startup
        self.m_requests = reg.counter(
            "nos_tpu_serve_requests_total",
            "Requests completed by the serving loop")
        self.m_tokens = reg.counter(
            "nos_tpu_serve_tokens_total", "Tokens emitted by decode ticks")
        self.m_ticks = reg.counter(
            "nos_tpu_serve_ticks_total", "Decode ticks executed")
        self.m_abandoned = reg.counter(
            "nos_tpu_serve_abandoned_total",
            "Requests that finished after their client timed out")
        self.m_rejected = reg.counter(
            "nos_tpu_serve_rejected_total",
            "Requests shed at admission (QueueFull -> 429)")
        self.g_active = reg.gauge(
            "nos_tpu_serve_active_slots", "Slots decoding right now")
        self.g_pending = reg.gauge(
            "nos_tpu_serve_pending_requests",
            "Requests waiting for a slot")
        self.m_prefix_hits = reg.gauge(
            "nos_tpu_serve_prefix_hits",
            "Prefill requests served from the prefix cache")
        self.m_prefix_saved = reg.gauge(
            "nos_tpu_serve_prefix_tokens_saved",
            "Prompt tokens whose prefill was skipped via the prefix cache")
        # per-tick economics (buckets carry trace exemplars when a
        # serve.tick span is sampled): service time is the whole
        # quantum (dispatch + wait + bookkeeping); the dispatch gap
        # mirrors the engine's structural dispatch_gap_s — time with NO
        # decode tick in flight while decodable slots existed, i.e. the
        # accelerator host-blocked. pipeline_depth >= 2 drives the gap
        # to ~0 (the window never empties outside barriers); the two
        # histograms together make the win measurable.
        self.h_tick = reg.histogram(
            "nos_tpu_serve_tick_seconds",
            "Serving-loop tick service time (dispatch + wait + host "
            "bookkeeping)")
        self.h_gap = reg.histogram(
            "nos_tpu_serve_dispatch_gap_seconds",
            "Per-tick dispatch gap: time the engine had no decode tick "
            "in flight while decodable slots existed (the accelerator "
            "host-blocked behind bookkeeping)")
        self.engine = engine
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._stop = False
        self._draining = False
        self._failed: Optional[BaseException] = None
        self._abandoned: set = set()        # rids whose client timed out
        self.m_rejected.inc(0)          # export 0, not an absent series
        self._mirror_engine_gauges()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    @property
    def healthy(self) -> bool:
        return self._failed is None

    @property
    def draining(self) -> bool:
        return self._draining

    def begin_drain(self) -> None:
        """Stop admitting; in-flight requests keep decoding. The k8s
        termination sequence: SIGTERM → readiness flips (Service stops
        routing here) → new submits 503 → wait_idle → exit."""
        with self._work:
            self._draining = True
            self._work.notify_all()

    def wait_idle(self, timeout: float) -> bool:
        """Block until the engine has no queued or decoding work (or
        ``timeout``/loop death). Returns True when fully drained."""
        deadline = time.monotonic() + timeout
        with self._work:
            while self.engine.has_work():
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._failed is not None \
                        or self._stop:
                    return not self.engine.has_work()
                self._work.wait(timeout=min(remaining, 1.0))
            return True

    def _fail(self, e: BaseException) -> None:
        """Mark the loop dead (caller holds the lock): /healthz flips
        BEFORE the single notify_all, so every wait_idle/stream waiter —
        re-checking under this same lock — observes healthy == False by
        the time it returns. Exactly one wakeup; the ticker thread exits
        right after."""
        logger.exception("decode tick failed; marking unhealthy")
        self._failed = e
        self._work.notify_all()

    def _run(self) -> None:
        # engines exposing the split-step protocol (DecodeServer) run
        # the blocking device wait OUTSIDE the condition lock, so
        # handlers submit/stream/cancel while the device computes;
        # step()-only engines (test stubs) tick under the lock as before
        split = hasattr(self.engine, "step_begin") \
            and hasattr(self.engine, "step_wait") \
            and hasattr(self.engine, "step_finish")
        from nos_tpu.obs import tracing
        while True:
            sp = None
            with self._work:
                while not self._stop and not self.engine.has_work():
                    self._work.wait()
                if self._stop:
                    return
                t0 = time.monotonic()
                sp = tracing.start_span("serve.tick", component="server")
                handle = None
                emitted = 0
                gap0 = getattr(self.engine, "dispatch_gap_s", None)
                try:
                    if split:
                        handle = self.engine.step_begin()
                    else:
                        emitted = self.engine.step()
                except BaseException as e:
                    sp.end()
                    self._fail(e)
                    return
            if split:
                # the only blocking device wait — lock released, so a
                # concurrent submit's barrier flush may consume the
                # handle under us (step_finish is idempotent on it)
                try:
                    self.engine.step_wait(handle)
                except BaseException as e:
                    with self._work:
                        sp.end()
                        self._fail(e)
                    return
            with self._work:
                try:
                    if split:
                        emitted = self.engine.step_finish(handle)
                        if gap0 is not None:
                            # the engine's structural gap counter: time
                            # this tick's window sat empty with work
                            # pending (ended by step_begin's dispatch)
                            self.h_gap.observe(
                                self.engine.dispatch_gap_s - gap0,
                                trace_id=sp.trace_id or None)
                    self.m_ticks.inc()
                    self.m_tokens.inc(emitted)
                    self._mirror_engine_gauges()
                    # reap results whose client already gave up, so
                    # _done can't grow from timed-out requests. Inside
                    # the try: a failure here (engine died mid-reap)
                    # must flip /healthz and wake waiters like any
                    # other tick failure, not kill the ticker silently
                    for rid in list(self._abandoned):
                        if self.engine.pop_result(rid) is not None:
                            self._abandoned.discard(rid)
                            # completed work, even if nobody is waiting
                            self.m_requests.inc()
                            self.m_abandoned.inc()
                except BaseException as e:
                    sp.end()
                    self._fail(e)
                    return
                sp.end()
                self.h_tick.observe(time.monotonic() - t0,
                                    trace_id=sp.trace_id or None)
                self._work.notify_all()     # wake waiters to check results

    def generate(self, prompt, max_new_tokens, timeout: float = 300.0,
                 **sampling):
        """Unary request: expressed over ``stream`` so there is exactly
        one waiting/abandon/metrics implementation."""
        out = list(prompt)
        for delta in self.stream(prompt, max_new_tokens, timeout,
                                 **sampling):
            out.extend(delta)
        return out

    def _forget(self, rid: int) -> None:
        """Idempotently drop a request in whatever state it is: pop it if
        finished (counting the completion), mark it abandoned if still
        decoding (the ticker reaps it), no-op if already handed out. Runs
        from stream teardown — including client disconnects that land
        exactly at completion, when the ticker may never tick again on an
        idle server."""
        with self._work:
            if self.engine.progress(rid) is None:
                self._abandoned.discard(rid)    # already popped
                return
            # stop burning ticks on output nobody will read: cancel frees
            # the slot immediately (engines without cancel — test stubs —
            # fall back to reap-after-completion)
            cancel = getattr(self.engine, "cancel", None)
            if cancel is not None:
                cancel(rid)
            if self.engine.pop_result(rid) is not None:
                self.m_requests.inc()
                self.m_abandoned.inc()
                self._abandoned.discard(rid)
            else:
                self._abandoned.add(rid)
            # cancel mutated occupancy and the ticker may never run again
            # on an idle server — re-mirror here or the gauges stay stale
            self._mirror_engine_gauges()

    def _mirror_engine_gauges(self) -> None:
        """Engine-held stats (prefix cache, occupancy) -> gauges.
        Called from every path that mutates them — submit, decode tick,
        and disconnect-cancel (_forget) — plus once at startup: a
        prefill-only request completes without the ticker ever running,
        a cancel on an idle server never ticks again, and a fresh pod
        must export 0s, not absent series."""
        hits = getattr(self.engine, "prefix_hits", None)
        if hits is not None:
            self.m_prefix_hits.set(hits)
            self.m_prefix_saved.set(self.engine.prefix_tokens_saved)
        occupancy = getattr(self.engine, "occupancy", None)
        if occupancy is not None:
            active, pending = occupancy()
            self.g_active.set(active)
            self.g_pending.set(pending)

    def stream(self, prompt, max_new_tokens, timeout: float = 300.0,
               **sampling):
        """Streaming primitive: submits EAGERLY (validation errors raise
        here, before the caller commits response headers) and returns an
        iterator yielding lists of newly-decoded tokens as ticks land.
        ``close()`` at ANY point — even before the first ``next()``,
        which a raw generator's finally cannot cover — drops the request
        via ``_forget``. Token identity with the unary path is the
        engine's batch-composition-invariance contract."""
        with self._work:
            if self._failed is not None:
                raise RuntimeError(f"serving loop failed: {self._failed}")
            if self._draining:
                raise DrainingError(
                    "server is draining (terminating); retry elsewhere")
            try:
                rid = self.engine.submit(prompt, max_new_tokens, **sampling)
            except QueueFull:
                self.m_rejected.inc()
                raise
            self._mirror_engine_gauges()
            self._work.notify_all()

        def deltas():
            sent = 0
            finished = False
            deadline = time.monotonic() + timeout
            try:
                while True:
                    with self._work:
                        prog = self.engine.progress(rid)
                        if prog is None:
                            # reaped out from under us (shutdown race)
                            raise RuntimeError(f"request {rid} vanished")
                        toks, done = prog
                        delta = toks[sent:]
                        if done:
                            self.engine.pop_result(rid)
                            self.m_requests.inc()
                            finished = True
                        elif not delta:
                            if self._failed is not None:
                                raise RuntimeError(
                                    f"serving loop failed: {self._failed}")
                            if self._stop:
                                # loop.shutdown() ran (drain timeout /
                                # interpreter exit): no tick will ever
                                # finish this request — fail it NOW so
                                # the non-daemon handler thread exits
                                # instead of waiting out its timeout
                                raise RuntimeError(
                                    f"request {rid} unfinished at server "
                                    "shutdown")
                            remaining = deadline - time.monotonic()
                            if remaining <= 0:
                                raise TimeoutError(
                                    f"request {rid} timed out")
                            self._work.wait(timeout=min(remaining, 1.0))
                            continue
                    if delta:
                        sent += len(delta)
                        yield delta
                    if finished:
                        return
            finally:
                if not finished:        # timeout / failure / client gone
                    self._forget(rid)

        return _Stream(self, rid, deltas())

    def shutdown(self) -> None:
        with self._work:
            self._stop = True
            self._work.notify_all()
        self._thread.join(timeout=5)


class _Stream:
    """Iterator over a streamed request whose ``close()`` is safe in
    every state: a started generator runs its finally; a NEVER-started
    one (e.g. response headers failed before the first frame) gets the
    explicit idempotent ``_forget`` so the submitted request cannot leak
    into the engine's done-table."""

    def __init__(self, loop: "ServingLoop", rid: int, gen):
        self._loop = loop
        self.rid = rid
        self._gen = gen

    def __iter__(self):
        return self

    def __next__(self):
        return next(self._gen)

    def close(self) -> None:
        self._gen.close()
        self._loop._forget(self.rid)


def build_engine(cfg: ServerConfig):
    """Load params (checkpoint / int8, shared with cmd/generate.py) and
    build the continuous-batching engine."""
    from nos_tpu.cmd.generate import GenerateConfig, load_params
    from nos_tpu.models.serving import DecodeServer

    # config errors must fire BEFORE the (multi-GB) checkpoint load
    if cfg.prefill_chunk and (cfg.prefill_chunk < 8 or
                              cfg.prefill_chunk & (cfg.prefill_chunk - 1)):
        raise ValueError(
            f"prefill_chunk must be 0 or a power of two >= 8, got "
            f"{cfg.prefill_chunk}")
    if cfg.pipeline_depth < 1:
        raise ValueError(
            f"pipeline_depth must be >= 1, got {cfg.pipeline_depth}")
    if cfg.decode_steps < 1:
        raise ValueError(
            f"decode_steps must be >= 1, got {cfg.decode_steps}")
    mesh = None
    if cfg.tp and cfg.tp > 1:
        import jax
        from jax.sharding import Mesh

        from nos_tpu.models.transformer import param_shardings
        from nos_tpu.parallel.mesh import arrange_devices

        devs = jax.devices()
        if len(devs) < cfg.tp:
            raise ValueError(
                f"tp={cfg.tp} but only {len(devs)} devices visible")
        kv = cfg.n_kv_heads or cfg.n_heads
        if kv % cfg.tp:
            raise ValueError(
                f"kv_heads {kv} not divisible by tp={cfg.tp}; the "
                f"cache head axis cannot shard evenly")
        if cfg.draft_checkpoint_dir:
            dkv = cfg.draft_n_kv_heads or cfg.draft_n_heads
            if dkv % cfg.tp:
                raise ValueError(
                    f"draft kv_heads {dkv} not divisible by tp={cfg.tp}; "
                    f"the draft cache head axis cannot shard evenly")
        # snake-walked placement: tp neighbours one ICI hop apart, same
        # contract the trainer's mesh gets (parallel/mesh.py)
        mesh = Mesh(arrange_devices(devs[:cfg.tp], (cfg.tp,)), ("tp",))

    gcfg = GenerateConfig(
        vocab=cfg.vocab, d_model=cfg.d_model, n_layers=cfg.n_layers,
        n_heads=cfg.n_heads, n_kv_heads=cfg.n_kv_heads, d_ff=cfg.d_ff,
        max_seq=cfg.max_seq, n_experts=cfg.n_experts, bf16=cfg.bf16,
        checkpoint_dir=cfg.checkpoint_dir, int8=cfg.int8, seed=cfg.seed)
    model_cfg, params = load_params(gcfg)
    if mesh is not None:
        if cfg.int8:
            from nos_tpu.models.quant import quant_param_shardings

            shardings = quant_param_shardings(mesh, model_cfg)
        else:
            shardings = param_shardings(mesh, model_cfg)
        params = jax.device_put(params, shardings)
    if cfg.draft_checkpoint_dir:
        from nos_tpu.models.spec_serving import SpeculativeDecodeServer

        dcfg_in = GenerateConfig(
            vocab=cfg.vocab, d_model=cfg.draft_d_model,
            n_layers=cfg.draft_n_layers, n_heads=cfg.draft_n_heads,
            n_kv_heads=cfg.draft_n_kv_heads, d_ff=cfg.draft_d_ff,
            max_seq=cfg.max_seq, bf16=cfg.bf16,
            checkpoint_dir=cfg.draft_checkpoint_dir, seed=cfg.seed)
        draft_cfg, draft_params = load_params(dcfg_in)
        if mesh is not None:
            draft_params = jax.device_put(
                draft_params, param_shardings(mesh, draft_cfg))
        return SpeculativeDecodeServer(
            params, model_cfg, draft_params, draft_cfg,
            n_draft=cfg.draft_n_tokens, max_batch=cfg.max_batch,
            prefix_cache_size=cfg.prefix_cache_size, mesh=mesh,
            prefill_chunk=cfg.prefill_chunk, max_pending=cfg.max_pending,
            # accepted for config uniformity; the spec engine pins both
            # to 1 (see SpeculativeDecodeServer.__init__)
            pipeline_depth=cfg.pipeline_depth,
            decode_steps=cfg.decode_steps)
    return DecodeServer(params, model_cfg, max_batch=cfg.max_batch,
                        prefix_cache_size=cfg.prefix_cache_size, mesh=mesh,
                        prefill_chunk=cfg.prefill_chunk,
                        max_pending=cfg.max_pending,
                        pipeline_depth=cfg.pipeline_depth,
                        decode_steps=cfg.decode_steps)


def make_http_server(cfg: ServerConfig, loop: ServingLoop
                     ) -> ThreadingHTTPServer:
    class Handler(BaseHTTPRequestHandler):
        # http.server applies this to the connection socket in setup();
        # a stalled read/write raises TimeoutError instead of pinning a
        # non-daemon handler thread past the drain window (see
        # ServerConfig.socket_timeout_s)
        timeout = cfg.socket_timeout_s or None

        def log_message(self, fmt, *args):      # route through logging
            logger.debug("http: " + fmt, *args)

        def _reply(self, code: int, body: dict, headers=()) -> None:
            data = json.dumps(body).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            for name, value in headers:
                self.send_header(name, value)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            if self.path == "/healthz":
                ok = loop.healthy
                self._reply(200 if ok else 500,
                            {"status": "ok" if ok else "unhealthy"})
            elif self.path == "/readyz":
                # draining flips readiness first: the Service stops
                # routing new traffic here while in-flight requests finish
                if loop.draining:
                    self._reply(503, {"status": "draining"})
                else:
                    self._reply(200, {"status": "ok"})
            elif self.path == "/metrics":
                body = default_registry().expose().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})

        def _stream_sse(self, gen) -> None:
            """Server-sent events: one ``data: {"tokens": [...]}`` frame
            per decode batch, ``data: [DONE]`` terminator (the OpenAI
            streaming convention, token-ids instead of text). Mid-stream
            failures become an SSE error frame — the 200 is already on
            the wire, so a clean in-band error beats a dropped
            connection. Fully self-contained: every exit path closes the
            stream (dropping the server-side request — ``_Stream.close``
            is safe even before the first frame, covering a disconnect
            during header send) and nothing escapes to do_POST, whose
            JSON error arms must never write a second status line onto a
            committed SSE response."""
            try:
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.send_header("Connection", "close")
                self.end_headers()
                for delta in gen:
                    self.wfile.write(
                        b"data: " + json.dumps({"tokens": delta}).encode()
                        + b"\n\n")
                    self.wfile.flush()
                self.wfile.write(b"data: [DONE]\n\n")
            except OSError:             # client went away (BrokenPipe, reset)
                pass
            except (TimeoutError, RuntimeError) as e:
                # in-band error frame, then the normal terminator: clients
                # must always be able to read to [DONE] and distinguish a
                # server-reported failure from a dropped connection
                try:
                    self.wfile.write(
                        b"data: " + json.dumps(
                            {"error": f"{type(e).__name__}: {e}"}).encode()
                        + b"\n\ndata: [DONE]\n\n")
                except OSError:
                    pass
            finally:
                gen.close()

        def do_POST(self):
            if self.path != "/v1/generate":
                self._reply(404, {"error": f"unknown path {self.path}"})
                return
            try:
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                prompt = [int(t) for t in body["prompt"]]
                n = int(body.get("max_new_tokens",
                                 cfg.default_max_new_tokens))
                sampling = {}
                if "temperature" in body:
                    sampling["temperature"] = float(body["temperature"])
                if "top_k" in body:
                    sampling["top_k"] = int(body["top_k"])
                if "top_p" in body:
                    sampling["top_p"] = float(body["top_p"])
                if "seed" in body:
                    sampling["seed"] = int(body["seed"])
                if "stop_tokens" in body:
                    sampling["stop_tokens"] = [
                        int(t) for t in body["stop_tokens"]]
                if "cache_prefix" in body:
                    # mark this prompt's KV as a reusable prefix (system
                    # prompts); reuse is automatic on every request.
                    # Strict type check: bool("false") is True, and a
                    # mistyped string would silently pin device memory
                    if not isinstance(body["cache_prefix"], bool):
                        raise ValueError(
                            "cache_prefix must be a JSON boolean")
                    sampling["cache_prefix"] = body["cache_prefix"]
                if body.get("stream"):
                    # stream() submits eagerly, so validation errors land
                    # in the except arms below as a clean JSON 4xx —
                    # headers are only committed once the request is in
                    gen = loop.stream(prompt, n, **sampling)
                    self._stream_sse(gen)
                    return
                tokens = loop.generate(prompt, n, **sampling)
            except (KeyError, ValueError, TypeError) as e:
                self._reply(400, {"error": f"{type(e).__name__}: {e}"})
                return
            except QueueFull as e:
                self._reply(429, {"error": str(e)},
                            headers=[("Retry-After", "1")])
                return
            except (TimeoutError, DrainingError) as e:
                self._reply(503, {"error": str(e)})
                return
            except Exception as e:  # decode-loop death → JSON 500, not a dropped conn
                self._reply(500, {"error": f"{type(e).__name__}: {e}"})
                return
            self._reply(200, {"tokens": tokens})

    class Server(ThreadingHTTPServer):
        # handler threads outlive shutdown(): after a drain declares the
        # ENGINE idle, the thread delivering the final response may still
        # be between its last wakeup and the socket write — non-daemon
        # threads make interpreter exit wait for that write instead of
        # killing it (the connection-reset the drain exists to prevent).
        # Bounded: loop.shutdown() fails any still-waiting request, and
        # Handler.timeout bounds threads blocked on the socket itself
        # (e.g. reading a stalled client's request body), so every
        # handler thread exits within ~socket_timeout_s of the main
        # loop's finally.
        daemon_threads = False

    return Server(("0.0.0.0", cfg.port), Handler)


def main(argv: Optional[Sequence[str]] = None) -> None:
    parser = argparse.ArgumentParser(prog="nos-tpu-server",
                                     description=__doc__)
    parser.add_argument("--config", default="", help="server config YAML")
    parser.add_argument("--checkpoint-dir", default="")
    parser.add_argument("--port", type=int, default=None)
    parser.add_argument(
        "--pipeline-depth", type=int, default=None,
        help="decode ticks in flight before the host blocks on a token "
             "fetch (1 = host-serial; overrides config)")
    parser.add_argument(
        "--decode-steps", type=int, default=None,
        help="decode steps fused into one compiled dispatch "
             "(1 = off; overrides config)")
    parser.add_argument(
        "--log-format", choices=("text", "json"), default="text",
        help="log line format; json emits one object per line with "
             "trace_id/span_id injected when a tracing span is active")
    args = parser.parse_args(argv)

    cfg = ServerConfig.from_yaml_file(args.config) if args.config \
        else ServerConfig()
    if args.checkpoint_dir:
        cfg.checkpoint_dir = args.checkpoint_dir
    if args.port is not None:
        cfg.port = args.port
    if args.pipeline_depth is not None:
        cfg.pipeline_depth = args.pipeline_depth
    if args.decode_steps is not None:
        cfg.decode_steps = args.decode_steps
    from nos_tpu.cmd import setup_logging as _shared_setup_logging
    _shared_setup_logging(
        0, args.log_format,
        numeric_level=getattr(logging, cfg.log_level.upper(), 20))

    loop = ServingLoop(build_engine(cfg))
    httpd = make_http_server(cfg, loop)

    def _finish_drain():
        drained = loop.wait_idle(cfg.drain_timeout_s)
        logger.info("drain %s; shutting down",
                    "complete" if drained else
                    f"timed out after {cfg.drain_timeout_s:.0f}s")
        httpd.shutdown()        # must come from another thread

    def _on_sigterm(*_):
        logger.info("SIGTERM: draining (budget %.0fs)", cfg.drain_timeout_s)
        loop.begin_drain()
        threading.Thread(target=_finish_drain, daemon=True).start()

    if threading.current_thread() is threading.main_thread():
        import signal

        signal.signal(signal.SIGTERM, _on_sigterm)
    logger.info("serving on :%d (max_batch=%d)", cfg.port, cfg.max_batch)
    try:
        httpd.serve_forever()
    finally:
        # order matters: shutting the loop first fails any still-waiting
        # handler (bounded exit), then server_close joins handler threads
        # (stdlib block_on_close) and releases the listening socket
        loop.shutdown()
        httpd.server_close()


if __name__ == "__main__":
    main()
