"""Dispatch: ``python -m nos_tpu.cmd <binary> [flags]``."""
from __future__ import annotations

import sys

_BINARIES = {
    "apiserver": "nos_tpu.cmd.apiserver",
    "operator": "nos_tpu.cmd.operator",
    "scheduler": "nos_tpu.cmd.scheduler",
    "partitioner": "nos_tpu.cmd.partitioner",
    "tpuagent": "nos_tpu.cmd.tpuagent",
    "deviceplugin": "nos_tpu.cmd.deviceplugin",
    "lifecycle": "nos_tpu.cmd.lifecycle",
    "fleet": "nos_tpu.cmd.fleet",
    "gateway": "nos_tpu.cmd.gateway",
    "harvest": "nos_tpu.cmd.harvest",
    "metricsexporter": "nos_tpu.cmd.metricsexporter",
    "trainer": "nos_tpu.cmd.trainer",
    "generate": "nos_tpu.cmd.generate",
    "server": "nos_tpu.cmd.server",
}


def main() -> None:
    if len(sys.argv) < 2 or sys.argv[1] not in _BINARIES:
        names = ", ".join(sorted(_BINARIES))
        print(f"usage: python -m nos_tpu.cmd <{names}> [flags]", file=sys.stderr)
        raise SystemExit(2)
    import importlib

    mod = importlib.import_module(_BINARIES[sys.argv[1]])
    mod.main(sys.argv[2:])


if __name__ == "__main__":
    main()
