"""nos-tpu-lifecycle — the node-lifecycle / slice-repair controller.

No reference analog (the nos stack assumes healthy nodes; SURVEY §2.7
flags node/slice fault handling as new TPU ground). Hosts
``lifecycle.NodeLifecycleController``: watches node heartbeat Leases and
lifecycle notice annotations, fences dead / preempted / maintenance-due /
chip-degraded nodes, and evicts displaced multi-host gangs whole so the
gang scheduler rebinds them atomically on surviving slices.
"""
from __future__ import annotations

import argparse
from typing import Optional, Sequence

from nos_tpu.cmd import serve
from nos_tpu.kube.controller import Manager
from nos_tpu.kube.leaderelection import LeaderElectionConfig
from nos_tpu.lifecycle import NodeLifecycleController


def build(
    server,
    lease_timeout_s: float = 40.0,
    check_interval_s: float = 5.0,
    maintenance_drain_lead_s: float = 120.0,
    max_unhealthy_chips: int = 0,
    leader_election: bool = True,
    identity: str = "lifecycle-0",
) -> Manager:
    election = None
    if leader_election:
        election = LeaderElectionConfig(
            lease_name="nos-tpu-lifecycle-leader", identity=identity)
    mgr = Manager(server, leader_election=election)
    # the controller keeps its wall-clock default (notice deadlines are
    # cross-host wall timestamps); the manager's monotonic clock only
    # paces requeues, and the two need not agree
    mgr.add_controller(NodeLifecycleController(
        lease_timeout_s=lease_timeout_s,
        check_interval_s=check_interval_s,
        maintenance_drain_lead_s=maintenance_drain_lead_s,
        max_unhealthy_chips=max_unhealthy_chips,
    ).controller())
    return mgr


def main(argv: Optional[Sequence[str]] = None) -> None:
    parser = argparse.ArgumentParser(
        prog="nos-tpu-lifecycle", description=__doc__)
    serve.common_flags(parser, config=False)
    parser.add_argument(
        "--lease-timeout", type=float, default=40.0,
        help="seconds a node's heartbeat Lease may sit unchanged before "
             "the node is declared NotReady (kubelet default ceiling)")
    parser.add_argument(
        "--check-interval", type=float, default=5.0,
        help="seconds between per-node staleness re-checks")
    parser.add_argument(
        "--maintenance-drain-lead", type=float, default=120.0,
        help="seconds ahead of an announced maintenance window to start "
             "draining the node")
    parser.add_argument(
        "--max-unhealthy-chips", type=int, default=0,
        help="tolerated unhealthy chips per node before slice repair "
             "treats the host as failed")
    parser.add_argument(
        "--identity", default="lifecycle-0",
        help="leader-election identity (pod name in-cluster)")
    parser.add_argument(
        "--no-leader-election", action="store_true",
        help="single-replica deployments may skip the Lease")
    args = parser.parse_args(argv)

    serve.setup_observability(args)
    mgr = build(
        serve.connect(args),
        lease_timeout_s=args.lease_timeout,
        check_interval_s=args.check_interval,
        maintenance_drain_lead_s=args.maintenance_drain_lead,
        max_unhealthy_chips=args.max_unhealthy_chips,
        leader_election=not args.no_leader_election,
        identity=args.identity,
    )
    serve.run_daemon(mgr, args.health_port, args.health_host)


if __name__ == "__main__":
    main()
