"""nos-tpu-metrics-exporter — cluster telemetry snapshot.

Analog of cmd/metricsexporter (metricsexporter.go:33-91 + metrics.go:24-42):
collects cluster facts (nodes, accelerator types, chip counts — both
allocatable and USED by bound pods — and quota objects) into one JSON
document and writes it to a file/stdout. One-shot by default;
``--interval N`` re-collects every N seconds (rewriting ``--output``
each cycle) for sidecar-style periodic export. The reference POSTs to a
vendor endpoint; here upload is gated behind --endpoint and off by
default (and a no-egress environment simply keeps the file).
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Dict, Optional, Sequence

from nos_tpu import constants
from nos_tpu.cmd import serve
from nos_tpu.kube.client import Client


def _quota_slack(client: Client) -> dict:
    """Per-namespace ElasticQuota slack in CHIPS, from the quota
    aggregates (quota/info.py) over the objects' reported status:

    - ``borrowable``: the namespace's own unused min — capacity other
      namespaces may borrow FROM it right now (Σ over its quota's
      resources of max(0, min - used), chips-converted);
    - ``guaranteed_overquota``: the namespace's fair share of the
      cluster-wide borrowable pool (``guaranteed_overquotas`` — the
      floor preemption protects when several namespaces borrow at
      once).

    Exported as nos_tpu_quota_borrowable_chips{namespace} /
    nos_tpu_quota_guaranteed_overquota_chips{namespace} and mirrored
    into the JSON snapshot — the capacity-review view of "who could
    lend, who is owed" that the fleet controller's scale decisions act
    on. One series per QUOTA: a CompositeElasticQuota spanning several
    namespaces exports a single series labeled with the sorted member
    list joined by "," — per-member rows would each carry the full
    slack and any sum() over the gauge would over-count the pool."""
    from nos_tpu.fleet.quota import build_quota_infos
    from nos_tpu.tpu.slice import resource_chips
    from nos_tpu.utils.metrics import default_registry

    infos = build_quota_infos(client, recompute_used=False)
    reg = default_registry()
    g_borrow = reg.gauge(
        "nos_tpu_quota_borrowable_chips",
        "Chips of this namespace's ElasticQuota min currently unused — "
        "the slack other namespaces may borrow from it (composite "
        "quotas export one series labeled with their joined member "
        "namespaces, so sum() reads the true pool)",
        ("namespace",))
    g_guaranteed = reg.gauge(
        "nos_tpu_quota_guaranteed_overquota_chips",
        "Chips of the cluster-wide borrowable pool guaranteed to this "
        "namespace (its proportional share of aggregated overquotas — "
        "the floor quota preemption protects)",
        ("namespace",))
    out = {}
    seen = set()
    for ns in sorted(infos):
        info = infos[ns]
        if id(info) in seen:
            continue                    # composite: export ONCE
        seen.add(id(info))
        label = ",".join(sorted(info.namespaces)) or ns
        unused = {r: max(0.0, m - info.used.get(r, 0))
                  for r, m in info.min.items()}
        borrowable = resource_chips(unused)
        guaranteed = resource_chips(infos.guaranteed_overquotas(ns))
        g_borrow.labels(label).set(borrowable)
        g_guaranteed.labels(label).set(guaranteed)
        out[label] = {"borrowable_chips": borrowable,
                      "guaranteed_overquota_chips": guaranteed}
    return out


def collect(client: Client) -> dict:
    from nos_tpu.tpu.slice import resource_chips

    pods = client.list("Pod")
    # used chips per node: requests of LIVE pods bound there — pending
    # pods hold no chips yet, terminated (Succeeded/Failed) pods hold
    # none anymore even while still bound awaiting GC. The
    # allocatable-vs-used gap is the snapshot's whole point for
    # capacity review.
    used_by_node: Dict[str, float] = {}
    for p in pods:
        node = p.spec.node_name
        if not node or p.status.phase in ("Succeeded", "Failed"):
            continue
        used_by_node[node] = \
            used_by_node.get(node, 0) + resource_chips(p.request())
    nodes = []
    for node in client.list("Node"):
        labels = node.metadata.labels
        nodes.append({
            "name": node.metadata.name,
            "accelerator": labels.get(constants.LABEL_TPU_ACCELERATOR),
            "topology": labels.get(constants.LABEL_TPU_TOPOLOGY),
            "partitioning": labels.get(constants.LABEL_PARTITIONING),
            "tpu_chips": node.status.allocatable.get(constants.RESOURCE_TPU, 0),
            "tpu_chips_used": used_by_node.get(node.metadata.name, 0),
            "tpu_slices": {
                k: v for k, v in node.status.allocatable.items()
                if k.startswith(constants.RESOURCE_TPU_SLICE_PREFIX)
            },
        })
    quotas = [
        {
            "namespace": q.metadata.namespace,
            "name": q.metadata.name,
            "min": q.spec.min,
            "max": q.spec.max,
            "used": q.status.used,
        }
        for q in client.list("ElasticQuota")
    ]
    composite = [
        {
            "name": q.metadata.name,
            "namespaces": q.spec.namespaces,
            "min": q.spec.min,
            "max": q.spec.max,
            "used": q.status.used,
        }
        for q in client.list("CompositeElasticQuota")
    ]
    return {
        "version": "v0.1",
        "nodes": nodes,
        "elastic_quotas": quotas,
        "composite_elastic_quotas": composite,
        "quota_slack": _quota_slack(client),
        "pod_count": len(pods),
        "tpu_pod_count": sum(
            1 for p in pods
            if any(
                r == constants.RESOURCE_TPU
                or r.startswith(constants.RESOURCE_TPU_SLICE_PREFIX)
                for r in p.request()
            )
        ),
    }


def main(argv: Optional[Sequence[str]] = None) -> None:
    parser = argparse.ArgumentParser(prog="nos-tpu-metrics-exporter",
                                     description=__doc__)
    serve.common_flags(parser, config=False)
    parser.add_argument("--output", default="-",
                        help="file to write the snapshot to ('-' = stdout)")
    parser.add_argument(
        "--endpoint", default=None,
        help="optional URL to POST the snapshot to (disabled by default)",
    )
    parser.add_argument(
        "--interval", type=float, default=0.0,
        help="seconds between snapshot re-collections (0 = one-shot, "
             "the default); periodic mode rewrites --output each cycle "
             "until interrupted",
    )
    args = parser.parse_args(argv)
    serve.setup_observability(args)

    client = Client(serve.connect(args))

    def snapshot_once() -> None:
        doc = json.dumps(collect(client), indent=2, sort_keys=True)
        if args.output == "-":
            print(doc)
        else:
            with open(args.output, "w") as f:
                f.write(doc + "\n")
        if args.endpoint:
            import urllib.request

            req = urllib.request.Request(
                args.endpoint, data=doc.encode(), method="POST",
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=30) as resp:
                print(f"uploaded: HTTP {resp.status}", file=sys.stderr)

    snapshot_once()     # one-shot mode: a failure exits loudly
    while args.interval > 0:
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:
            break
        try:
            snapshot_once()
        except Exception as e:      # noqa: BLE001 — sidecar keeps going
            # periodic mode is a long-lived sidecar: one transient API
            # or upload failure must not kill the export loop
            print(f"snapshot failed (will retry): {e}", file=sys.stderr)


if __name__ == "__main__":
    main()
