"""nos-tpu-metrics-exporter — one-shot cluster telemetry snapshot.

Analog of cmd/metricsexporter (metricsexporter.go:33-91 + metrics.go:24-42):
collects cluster facts (nodes, accelerator types, chip counts, quota
objects) into one JSON document and writes it to a file/stdout. The
reference POSTs to a vendor endpoint; here upload is gated behind
--endpoint and off by default (and a no-egress environment simply keeps
the file).
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Optional, Sequence

from nos_tpu import constants
from nos_tpu.cmd import serve
from nos_tpu.kube.client import Client


def collect(client: Client) -> dict:
    nodes = []
    for node in client.list("Node"):
        labels = node.metadata.labels
        nodes.append({
            "name": node.metadata.name,
            "accelerator": labels.get(constants.LABEL_TPU_ACCELERATOR),
            "topology": labels.get(constants.LABEL_TPU_TOPOLOGY),
            "partitioning": labels.get(constants.LABEL_PARTITIONING),
            "tpu_chips": node.status.allocatable.get(constants.RESOURCE_TPU, 0),
            "tpu_slices": {
                k: v for k, v in node.status.allocatable.items()
                if k.startswith(constants.RESOURCE_TPU_SLICE_PREFIX)
            },
        })
    quotas = [
        {
            "namespace": q.metadata.namespace,
            "name": q.metadata.name,
            "min": q.spec.min,
            "max": q.spec.max,
            "used": q.status.used,
        }
        for q in client.list("ElasticQuota")
    ]
    composite = [
        {
            "name": q.metadata.name,
            "namespaces": q.spec.namespaces,
            "min": q.spec.min,
            "max": q.spec.max,
            "used": q.status.used,
        }
        for q in client.list("CompositeElasticQuota")
    ]
    pods = client.list("Pod")
    return {
        "version": "v0.1",
        "nodes": nodes,
        "elastic_quotas": quotas,
        "composite_elastic_quotas": composite,
        "pod_count": len(pods),
        "tpu_pod_count": sum(
            1 for p in pods
            if any(
                r == constants.RESOURCE_TPU
                or r.startswith(constants.RESOURCE_TPU_SLICE_PREFIX)
                for r in p.request()
            )
        ),
    }


def main(argv: Optional[Sequence[str]] = None) -> None:
    parser = argparse.ArgumentParser(prog="nos-tpu-metrics-exporter",
                                     description=__doc__)
    serve.common_flags(parser, config=False)
    parser.add_argument("--output", default="-",
                        help="file to write the snapshot to ('-' = stdout)")
    parser.add_argument(
        "--endpoint", default=None,
        help="optional URL to POST the snapshot to (disabled by default)",
    )
    args = parser.parse_args(argv)
    serve.setup_observability(args)

    client = Client(serve.connect(args))
    doc = json.dumps(collect(client), indent=2, sort_keys=True)
    if args.output == "-":
        print(doc)
    else:
        with open(args.output, "w") as f:
            f.write(doc + "\n")
    if args.endpoint:
        import urllib.request

        req = urllib.request.Request(
            args.endpoint, data=doc.encode(), method="POST",
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            print(f"uploaded: HTTP {resp.status}", file=sys.stderr)


if __name__ == "__main__":
    main()
