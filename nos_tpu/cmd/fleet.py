"""nos-tpu-fleet — the serving-fleet autoscaler (ISSUE 8).

Hosts ``fleet.FleetController``: scrapes each ``nos-tpu-server``
replica's ``/stats`` (goodput, queue depth + oldest wait, TTFT p99,
uptime + config echo), runs the hysteresis-damped scaling policy, and
actuates through the operator plane — scale-up creates replica pods
whose chip requests flow through ElasticQuota (borrowing slack when it
exists, clamped when it does not), scale-down drains a replica
gracefully (POST /admin/drain flips its readiness, in-flight requests
finish, then the pod is released).

Replica pods are found by the ``nos.ai/fleet=<name>`` label in
``--namespace``; their /stats endpoints are reached through
``--replica-url-template``. The default addresses replicas by POD IP
(``{ip}`` = status.podIP): no Service required, and a draining replica
— gone from Service endpoints the moment its readiness flips — stays
reachable, so the controller can observe "in-flight work finished"
instead of waiting out the drain budget. ``{name}``/``{namespace}``
placeholders remain for DNS-fronted setups.
"""
from __future__ import annotations

import argparse
import json
import logging
import urllib.request
from typing import Optional, Sequence

from nos_tpu.cmd import serve
from nos_tpu.fleet import FleetConfig, FleetController, PolicyConfig
from nos_tpu.kube.controller import Manager
from nos_tpu.kube.leaderelection import LeaderElectionConfig

logger = logging.getLogger(__name__)


class HttpReplicaClient:
    """/stats scraper + drain trigger over the replica's own HTTP
    surface. One failure returns None (the controller reads an
    unscrapable replica as a signal, not an error).

    The default template addresses replicas by POD IP (``{ip}`` =
    ``status.podIP``): it needs no Service, resolves on any flat pod
    network, and — critically for the drain sequence — keeps working
    after ``/admin/drain`` flips readiness, when the pod drops out of
    Service endpoints/DNS but keeps its IP. ``{name}``/``{namespace}``
    remain available for DNS-fronted setups (a headless Service with
    ``publishNotReadyAddresses: true``)."""

    def __init__(self, url_template: str, timeout_s: float = 2.0):
        self.url_template = url_template
        self.timeout_s = timeout_s

    def _url(self, pod) -> Optional[str]:
        ip = pod.status.pod_ip
        if "{ip}" in self.url_template and not ip:
            return None         # not started yet: nothing to reach
        return self.url_template.format(
            name=pod.metadata.name, namespace=pod.metadata.namespace,
            ip=ip)

    def stats(self, pod) -> Optional[dict]:
        url = self._url(pod)
        if url is None:
            return None
        try:
            with urllib.request.urlopen(
                    url + "/stats", timeout=self.timeout_s) as r:
                return json.loads(r.read())
        except Exception:   # noqa: BLE001 — unreachable is a signal
            return None

    def drain(self, pod) -> None:
        url = self._url(pod)
        if url is None:
            return              # deletion's SIGTERM path still drains
        req = urllib.request.Request(
            url + "/admin/drain", data=b"{}",
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=self.timeout_s):
            pass


class HttpGatewayClient:
    """Scrapes the gateway's /stats for the door-queue activation
    signal (``--gateway-url``). Unreachable reads as None — the
    controller treats gateway silence as zero pressure, and the
    ConfigMap annotation remains the durable fallback path."""

    def __init__(self, url: str, timeout_s: float = 2.0):
        self.url = url.rstrip("/")
        self.timeout_s = timeout_s

    def stats(self) -> Optional[dict]:
        try:
            with urllib.request.urlopen(
                    self.url + "/stats", timeout=self.timeout_s) as r:
                return json.loads(r.read())
        except Exception:   # noqa: BLE001 — unreachable is a signal
            return None


def build(server, cfg: FleetConfig, stats_source=None, drain_hook=None,
          leader_election: bool = True,
          identity: str = "fleet-0", gateway_source=None) -> Manager:
    election = None
    if leader_election:
        election = LeaderElectionConfig(
            lease_name=f"nos-tpu-fleet-{cfg.name}-leader",
            identity=identity)
    mgr = Manager(server, leader_election=election)
    ctl = FleetController(cfg, stats_source=stats_source,
                          drain_hook=drain_hook,
                          gateway_source=gateway_source)
    mgr.add_controller(ctl.controller())
    mgr.stats = ctl.stats           # HealthServer /stats route
    return mgr


def main(argv: Optional[Sequence[str]] = None) -> None:
    parser = argparse.ArgumentParser(prog="nos-tpu-fleet",
                                     description=__doc__)
    serve.common_flags(parser, config=False)
    parser.add_argument("--fleet", default="default",
                        help="fleet name (the nos.ai/fleet label value)")
    parser.add_argument("--namespace", default="serving",
                        help="namespace the replica pods live in (the "
                             "namespace whose ElasticQuota governs them)")
    parser.add_argument(
        "--chips-per-replica", type=float, default=4.0,
        help="chips each replica pod requests (flows through "
             "ElasticQuota admission)")
    parser.add_argument(
        "--resource", default="google.com/tpu",
        help="resource name each replica requests (a sub-slice "
             "resource like nos.ai/tpu-slice-2x2 for partitioned hosts)")
    parser.add_argument("--min-replicas", type=int, default=1)
    parser.add_argument("--max-replicas", type=int, default=8)
    parser.add_argument(
        "--interval", type=float, default=5.0,
        help="seconds between reconcile/scrape passes")
    parser.add_argument(
        "--queue-high", type=float, default=4.0,
        help="pending requests per ready replica above which sustained "
             "pressure scales up")
    parser.add_argument(
        "--queue-low", type=float, default=0.5,
        help="pending per replica below which a healthy fleet may "
             "shrink (the gap to --queue-high is the hysteresis band)")
    parser.add_argument(
        "--goodput-floor", type=float, default=0.90,
        help="goodput below which the fleet scales up even without a "
             "queue")
    parser.add_argument(
        "--goodput-ceiling", type=float, default=0.98,
        help="goodput required before the fleet may scale down")
    parser.add_argument(
        "--ttft-p99-high-ms", type=float, default=0.0,
        help="worst-replica TTFT p99 above which the fleet scales up "
             "(0 = disabled)")
    parser.add_argument(
        "--oldest-wait-high-s", type=float, default=0.0,
        help="oldest queued-request wait above which the fleet scales "
             "up (0 = disabled)")
    parser.add_argument(
        "--up-stable", type=float, default=15.0,
        help="seconds pressure must hold before a scale-up step")
    parser.add_argument(
        "--down-stable", type=float, default=60.0,
        help="seconds of idleness before a scale-down step")
    parser.add_argument(
        "--up-cooldown", type=float, default=30.0,
        help="minimum seconds between scale-up steps")
    parser.add_argument(
        "--down-cooldown", type=float, default=120.0,
        help="minimum seconds between scale-down steps")
    parser.add_argument("--max-step-up", type=int, default=2)
    parser.add_argument("--max-step-down", type=int, default=1)
    parser.add_argument(
        "--drain-timeout", type=float, default=60.0,
        help="seconds a draining replica may finish in-flight work "
             "before the pod is released anyway")
    parser.add_argument(
        "--replica-priority", type=int, default=0,
        help="pod priority for replica pods (preemption victim order)")
    parser.add_argument(
        "--replica-url-template",
        default="http://{ip}:8000",
        help="how to reach a replica pod's HTTP surface; {ip} "
             "(status.podIP — works without a Service and survives the "
             "drain readiness flip), {name} and {namespace} are "
             "substituted")
    parser.add_argument(
        "--scrape-timeout", type=float, default=2.0,
        help="per-replica /stats scrape timeout in seconds")
    parser.add_argument(
        "--gateway-url", default="",
        help="base URL of the nos-tpu-gateway front door; its /stats "
             "door_queue becomes the scale-from-zero activation "
             "signal (empty = read the nos.ai/gateway-queued ConfigMap "
             "annotation the gateway stamps instead)")
    parser.add_argument(
        "--identity", default="fleet-0",
        help="leader-election identity (pod name in-cluster)")
    parser.add_argument(
        "--no-leader-election", action="store_true",
        help="single-replica deployments may skip the Lease")
    args = parser.parse_args(argv)

    serve.setup_observability(args)
    cfg = FleetConfig(
        name=args.fleet, namespace=args.namespace,
        resource=args.resource,
        chips_per_replica=args.chips_per_replica,
        policy=PolicyConfig(
            min_replicas=args.min_replicas,
            max_replicas=args.max_replicas,
            queue_high=args.queue_high, queue_low=args.queue_low,
            goodput_floor=args.goodput_floor,
            goodput_ceiling=args.goodput_ceiling,
            ttft_p99_high_s=args.ttft_p99_high_ms / 1e3,
            oldest_wait_high_s=args.oldest_wait_high_s,
            up_stable_s=args.up_stable, down_stable_s=args.down_stable,
            up_cooldown_s=args.up_cooldown,
            down_cooldown_s=args.down_cooldown,
            max_step_up=args.max_step_up,
            max_step_down=args.max_step_down,
        ),
        reconcile_interval_s=args.interval,
        drain_timeout_s=args.drain_timeout,
        priority=args.replica_priority,
    )
    replica = HttpReplicaClient(args.replica_url_template,
                                timeout_s=args.scrape_timeout)
    gateway = (HttpGatewayClient(args.gateway_url,
                                 timeout_s=args.scrape_timeout)
               if args.gateway_url else None)
    mgr = build(
        serve.connect(args), cfg,
        stats_source=replica.stats, drain_hook=replica.drain,
        leader_election=not args.no_leader_election,
        identity=args.identity,
        gateway_source=gateway.stats if gateway else None,
    )
    serve.run_daemon(mgr, args.health_port, args.health_host)


if __name__ == "__main__":
    main()
