"""nos-tpu-gateway — the serving fleet's front door (ISSUE 11).

    POST /v1/generate   same wire shape as nos-tpu-server, proxied to a
                        replica picked by prefix-affinity (the prompt's
                        leading block-chain hashed onto a consistent
                        ring), least-loaded fallback under a bounded
                        imbalance; unary and SSE streaming alike
    GET  /healthz       gateway process liveness
    GET  /readyz        always ok while running — a gateway with ZERO
                        replicas still accepts traffic (it queues at
                        the door and activates the fleet)
    GET  /stats         router snapshot: replicas, door queue, routes,
                        sheds, retries (the fleet controller's
                        --gateway-url scrape target)
    GET  /v1/slo        fleet SLO roll-up (ISSUE 20): per-tenant burn
                        rates and budget remaining recomputed from
                        summed per-replica window counts, chip-second
                        attribution totals, and useful work per chip
                        hour (optionally folding in --harvest-url's
                        harvested chip-seconds)
    GET  /metrics       nos_tpu_gateway_* (+ /debug/traces)

Discovery mirrors the fleet controller: ``nos.ai/fleet=<name>`` pods in
``--namespace``, scraped by POD IP through ``--replica-url-template``
(a draining replica leaves Service endpoints but keeps its IP — the
gateway must keep seeing it to stop routing there gracefully).

Retry semantics are the productionized ``test_fleet_chaos`` router:
per-replica 429/503 sheds back off reason-aware and retry the next
candidate; a replica dying mid-request requeues the attempt; the
request completes EXACTLY once fleet-wide (each replica's serving loop
accounts its own interrupted attempts). Deadlines propagate with the
budget REMAINING after door queueing and retries, via the existing
``X-Request-Deadline-S`` header.

Scale-from-zero: with no admitting replica, requests park at the door
and the gateway publishes its queue depth as the activation signal —
the ``nos_tpu_gateway_door_queue`` gauge, ``/stats`` ``door_queue``,
and the ``nos.ai/gateway-queued`` annotation stamped onto the
``nos-tpu-gateway-<fleet>`` ConfigMap — which the fleet controller
consumes as pressure even at ready==0. The queue flushes on the first
replica turning ready.
"""
from __future__ import annotations

import argparse
import json
import logging
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Iterable, Optional, Sequence

from nos_tpu import constants
from nos_tpu.cmd import serve
from nos_tpu.cmd.fleet import HttpReplicaClient
from nos_tpu.cmd.serve import metrics_payload
from nos_tpu.gateway import (
    GatewayRouter, PodDiscovery, Replica, ReplicaUnreachable,
    RouterConfig,
)
from nos_tpu.kvfabric import FABRIC_TOKEN_HEADER
from nos_tpu.kube.apiserver import NotFound
from nos_tpu.kube.client import Client
from nos_tpu.kube.objects import ConfigMap, ObjectMeta
from nos_tpu.models.errors import (
    DeadlineExceeded, EngineRecovering, Infeasible, QueueFull,
)
from nos_tpu.models.tenantquota import (
    TenantQuotaConfig, validate_tenant_name,
)
from nos_tpu.obs import tracing

logger = logging.getLogger(__name__)


class HttpReplicaTransport:
    """One dispatch attempt over a replica's own HTTP surface, raising
    the serving-plane error taxonomy the router retries through. The
    remaining deadline budget travels as ``X-Request-Deadline-S``."""

    def __init__(self, timeout_s: float = 300.0,
                 fabric_token: str = ""):
        self.timeout_s = timeout_s
        self.fabric_token = fabric_token or ""

    def _request(self, replica: Replica, req: dict, stream: bool):
        if not replica.handle:
            # a Running pod without an IP yet: nothing to dial
            raise ReplicaUnreachable(
                f"replica {replica.name} has no address yet")
        body = dict(req["sampling"])
        body["prompt"] = req["prompt"]
        body["max_new_tokens"] = req["max_new_tokens"]
        if stream:
            body["stream"] = True
        headers = {"Content-Type": "application/json"}
        if req.get("kv_sources"):
            # KV-fabric peer-pull offer: the replica fetches the named
            # peer chain before admitting the request (best-effort).
            # Stamped with the fleet's shared fabric token — replicas
            # drop tokenless offers, because an offer steers their
            # outbound fetcher and seeds their prefix cache (only the
            # gateway may attach one; client-supplied kv_sources are
            # stripped at the door)
            body["kv_sources"] = req["kv_sources"]
            if self.fabric_token:
                headers[FABRIC_TOKEN_HEADER] = self.fabric_token
        if req.get("deadline_s") is not None:
            headers["X-Request-Deadline-S"] = f"{req['deadline_s']:.3f}"
        if req.get("traceparent"):
            # W3C trace context: the replica's serve.request span
            # parents into the gateway attempt instead of minting a
            # fresh trace_id — one trace per request, fleet-wide
            headers["traceparent"] = req["traceparent"]
        timeout = self.timeout_s
        if req.get("deadline_s") is not None:
            timeout = min(timeout, req["deadline_s"] + 5.0)
        return urllib.request.Request(
            f"{replica.handle}/v1/generate",
            data=json.dumps(body).encode(), headers=headers,
            method="POST"), timeout

    def _raise_for(self, e: urllib.error.HTTPError):
        try:
            payload = json.loads(e.read() or b"{}")
        except Exception:   # noqa: BLE001 — body is advisory
            payload = {}
        msg = payload.get("error") or f"replica answered {e.code}"
        reason = payload.get("reason")
        if e.code == 429:
            raise QueueFull(msg, reason=reason or "queue_full")
        if e.code == 400:
            if payload.get("infeasible"):
                raise Infeasible(msg)
            raise ValueError(msg)
        if e.code == 504:
            raise DeadlineExceeded(msg)
        if e.code == 503:
            if reason == "recovering":
                raise EngineRecovering(msg)
            # draining / timeout / unknown 503: retryable elsewhere
            raise RuntimeError(msg)
        raise RuntimeError(msg)

    def send(self, replica: Replica, req: dict) -> list:
        request, timeout = self._request(replica, req, stream=False)
        try:
            with urllib.request.urlopen(request, timeout=timeout) as r:
                payload = json.loads(r.read())
                if "handoff" in payload:
                    # a prefill-role replica answered with the handoff
                    # descriptor: hand it to the router's phase 2
                    return payload
                return payload["tokens"]
        except urllib.error.HTTPError as e:
            self._raise_for(e)
        except (urllib.error.URLError, OSError) as e:
            raise ReplicaUnreachable(
                f"replica {replica.name} unreachable: {e}") from e

    def _resume_target(self, replica: Replica, desc: dict,
                       deadline_s=None):
        """The one phase-2 preamble resume and resume_stream share:
        resolve the decode replica's base address (its handle, else the
        descriptor's target) and clamp the socket timeout to the
        request deadline."""
        base = replica.handle or desc.get("target")
        if not base:
            raise ReplicaUnreachable(
                f"decode replica {replica.name} has no address")
        timeout = self.timeout_s
        if deadline_s is not None:
            timeout = min(timeout, deadline_s + 5.0)
        return base, timeout

    def resume(self, replica: Replica, desc: dict,
               deadline_s=None) -> list:
        """Phase 2 unary: fetch a handed-off request's full sequence
        from the decode replica (``GET /v1/result/<rid>``)."""
        base, timeout = self._resume_target(replica, desc, deadline_s)
        try:
            with urllib.request.urlopen(
                    f"{base}/v1/result/{desc['rid']}",
                    timeout=timeout) as r:
                return json.loads(r.read())["tokens"]
        except urllib.error.HTTPError as e:
            self._raise_for(e)
        except (urllib.error.URLError, OSError) as e:
            raise ReplicaUnreachable(
                f"decode replica {replica.name} unreachable: {e}") from e

    def resume_stream(self, replica: Replica, desc: dict,
                      deadline_s=None) -> Iterable[list]:
        """Phase 2 streaming: SSE attach to the decode replica's
        ``/v1/stream/<rid>`` — same frame protocol as send_stream."""
        base, timeout = self._resume_target(replica, desc, deadline_s)
        try:
            resp = urllib.request.urlopen(
                f"{base}/v1/stream/{desc['rid']}", timeout=timeout)
        except urllib.error.HTTPError as e:
            self._raise_for(e)
            return
        except (urllib.error.URLError, OSError) as e:
            raise ReplicaUnreachable(
                f"decode replica {replica.name} unreachable: {e}") from e
        yield from self._iter_sse(resp, replica.name)

    def send_stream(self, replica: Replica, req: dict
                    ) -> Iterable[list]:
        """SSE passthrough: yields token-list deltas; an in-band error
        frame BEFORE any data raises (retryable at the router), after
        data it raises too — the router propagates it (no replay)."""
        request, timeout = self._request(replica, req, stream=True)
        try:
            resp = urllib.request.urlopen(request, timeout=timeout)
        except urllib.error.HTTPError as e:
            self._raise_for(e)
            return
        except (urllib.error.URLError, OSError) as e:
            raise ReplicaUnreachable(
                f"replica {replica.name} unreachable: {e}") from e
        yield from self._iter_sse(resp, replica.name)

    @staticmethod
    def _iter_sse(resp, name: str) -> Iterable[list]:
        """The one SSE frame loop send_stream and resume_stream share:
        yields token-list deltas until [DONE]; in-band error frames and
        early closes raise."""
        try:
            for raw in resp:
                line = raw.strip()
                if not line or not line.startswith(b"data: "):
                    continue
                data = line[len(b"data: "):]
                if data == b"[DONE]":
                    return
                frame = json.loads(data)
                if "error" in frame:
                    raise RuntimeError(frame["error"])
                yield frame.get("tokens") or []
            # stream ended without [DONE]: the replica died mid-answer
            raise ReplicaUnreachable(
                f"replica {name} closed the stream early")
        finally:
            resp.close()


class AnnotationStamper:
    """Publishes the door-queue depth as the ``nos.ai/gateway-queued``
    annotation on the ``nos-tpu-gateway-<fleet>`` ConfigMap — the
    durable half of the activation signal (the gauge being the live
    half). Runs on its own thread: the router calls ``note`` under its
    lock, so the network write must happen elsewhere. Level-triggered
    and idempotent: only depth CHANGES are stamped, including back to
    zero (a stale nonzero annotation would hold a scaled-to-zero fleet
    awake forever)."""

    def __init__(self, client: Client, fleet: str, namespace: str):
        self.client = client
        self.fleet = fleet
        self.namespace = namespace
        self.name = f"nos-tpu-gateway-{fleet}"
        self._event = threading.Event()
        self._stop = False
        self._depth = 0
        self._stamped: Optional[int] = None
        self._thread = threading.Thread(
            target=self._run, name="gateway-activation", daemon=True)

    def start(self) -> "AnnotationStamper":
        self._ensure()
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop = True
        self._event.set()
        self._thread.join(timeout=5)

    def note(self, depth: int) -> None:
        self._depth = depth
        self._event.set()

    def _ensure(self) -> None:
        try:
            self.client.create(ConfigMap(
                metadata=ObjectMeta(name=self.name,
                                    namespace=self.namespace),
                data={"fleet": self.fleet}))
        except Exception:   # noqa: BLE001 — AlreadyExists or transient;
            pass            # the patch below is the real write

    def _run(self) -> None:
        while not self._stop:
            self._event.wait()
            self._event.clear()
            if self._stop:
                return
            depth = self._depth
            if depth == self._stamped:
                continue
            try:
                self.client.patch(
                    "ConfigMap", self.name, self.namespace,
                    lambda cm: cm.metadata.annotations.update(
                        {constants.ANNOTATION_GATEWAY_QUEUED: str(depth)}))
                self._stamped = depth
            except NotFound:
                self._ensure()
                self._event.set()       # retry the stamp
            except Exception as e:  # noqa: BLE001 — advisory signal
                logger.debug("activation stamp failed: %s", e)


class DiscoveryLoop:
    """Polls PodDiscovery every ``interval_s`` into the router."""

    def __init__(self, discovery: PodDiscovery, router: GatewayRouter,
                 interval_s: float):
        self.discovery = discovery
        self.router = router
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="gateway-discovery", daemon=True)

    def start(self) -> "DiscoveryLoop":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.router.update(self.discovery.poll())
            except Exception as e:  # noqa: BLE001 — a failed poll keeps
                logger.warning("discovery pass failed: %s", e)  # last view
            self._stop.wait(self.interval_s)


def make_http_server(router: GatewayRouter, port: int,
                     fleet: str) -> ThreadingHTTPServer:
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            logger.debug("http: " + fmt, *args)

        def _reply(self, code: int, body: dict, headers=()) -> None:
            data = json.dumps(body).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            for name, value in headers:
                self.send_header(name, value)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            if self.path == "/healthz":
                self._reply(200, {"status": "ok"})
            elif self.path == "/readyz":
                # a replica-less gateway is still READY: it queues at
                # the door and wakes the fleet — flipping readiness
                # here would hide the front door exactly when the
                # scale-from-zero path needs it reachable
                self._reply(200, {"status": "ok"})
            elif self.path == "/metrics":
                text, ctype = metrics_payload(
                    self.headers.get("Accept", ""))
                body = text.encode()
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif self.path == "/stats":
                snap = router.stats()
                snap["fleet"] = fleet
                self._reply(200, snap)
            elif self.path == "/v1/slo":
                snap = router.slo()
                snap["fleet"] = fleet
                self._reply(200, snap)
            elif self.path == "/debug/traces":
                self._reply(200, tracing.recorder().to_json())
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})

        def _stream_sse(self, gen, first=None) -> None:
            """Mirror of the serving binary's SSE framing: deltas as
            ``data:`` frames, errors in-band, always a ``[DONE]``.
            ``first`` is the pre-pulled delta do_POST primed with —
            by the time headers commit here, sheds have already taken
            the JSON 4xx path."""
            try:
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.send_header("Connection", "close")
                self.end_headers()
                if first is not None:
                    self.wfile.write(
                        b"data: " + json.dumps({"tokens": first}).encode()
                        + b"\n\n")
                    self.wfile.flush()
                for delta in gen:
                    self.wfile.write(
                        b"data: " + json.dumps({"tokens": delta}).encode()
                        + b"\n\n")
                    self.wfile.flush()
                self.wfile.write(b"data: [DONE]\n\n")
            except OSError:
                pass
            except Exception as e:  # noqa: BLE001 — in-band error frame
                try:
                    self.wfile.write(
                        b"data: " + json.dumps(
                            {"error": f"{type(e).__name__}: {e}"}).encode()
                        + b"\n\ndata: [DONE]\n\n")
                except OSError:
                    pass
            finally:
                gen.close()

        def do_POST(self):
            if self.path != "/v1/generate":
                self._reply(404, {"error": f"unknown path {self.path}"})
                return
            try:
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                prompt = [int(t) for t in body.pop("prompt")]
                n = int(body.pop("max_new_tokens", 64))
                stream = bool(body.pop("stream", False))
                deadline = body.pop(
                    "deadline_s",
                    self.headers.get("X-Request-Deadline-S"))
                deadline_s = float(deadline) if deadline is not None \
                    else None
                # request-level elastic-quota identity (body field
                # wins, X-Tenant header second): rides the door's
                # fleet-wide max admission, scopes the affinity key,
                # and forwards to the replica's weighted admission
                tenant = body.pop("tenant",
                                  self.headers.get("X-Tenant"))
                if tenant is not None:
                    tenant = validate_tenant_name(str(tenant))
                # kv_sources is fleet-internal (the router attaches
                # its own offers, token-stamped): a client-supplied
                # one would steer a replica's outbound fetcher (blind
                # SSRF) and seed its prefix cache (poisoning) —
                # stripped, never forwarded
                body.pop("kv_sources", None)
                # every remaining body key forwards verbatim — the
                # replica owns validation of its own wire surface
                if stream:
                    gen = router.stream(prompt, n, deadline_s=deadline_s,
                                        tenant=tenant, **body)
                    # prime the FIRST delta before committing the
                    # status line: router.stream is lazy, and a door
                    # shed / spent deadline / exhausted retry budget
                    # must answer the same JSON 429/504 the replica
                    # surface answers — not a 200 whose body carries
                    # an error frame no Retry-After logic can see
                    # (the serving binary submits eagerly for exactly
                    # this reason)
                    try:
                        first = next(gen)
                    except StopIteration:
                        first = None
                    self._stream_sse(gen, first=first)
                    return
                tokens, replica, attempts = router.dispatch(
                    prompt, n, deadline_s=deadline_s, tenant=tenant,
                    **body)
            except Infeasible as e:
                self._reply(400, {"error": f"{type(e).__name__}: {e}",
                                  "infeasible": True,
                                  "reason": e.reason})
                return
            except (KeyError, ValueError, TypeError) as e:
                self._reply(400, {"error": f"{type(e).__name__}: {e}",
                                  "reason": "bad_request"})
                return
            except QueueFull as e:
                # the gateway's own door sheds (fleet_queue_full /
                # fleet_hbm_admission / door_queue_full /
                # no_ready_replicas) and replica sheds that survived
                # the retry budget — same 429 + Retry-After shape
                self._reply(429, {"error": str(e), "reason": e.reason},
                            headers=[("Retry-After", "1")])
                return
            except DeadlineExceeded as e:
                self._reply(504, {"error": str(e),
                                  "deadline_exceeded": True})
                return
            except EngineRecovering as e:
                self._reply(503, {"error": str(e),
                                  "reason": "recovering"},
                            headers=[("Retry-After", "1")])
                return
            except Exception as e:  # noqa: BLE001 — retries exhausted
                self._reply(502, {"error": f"{type(e).__name__}: {e}"})
                return
            self._reply(200, {"tokens": tokens, "replica": replica,
                              "attempts": attempts})

    class Server(ThreadingHTTPServer):
        daemon_threads = True

    return Server(("0.0.0.0", port), Handler)


def main(argv: Optional[Sequence[str]] = None) -> None:
    parser = argparse.ArgumentParser(prog="nos-tpu-gateway",
                                     description=__doc__)
    serve.common_flags(parser, config=False)
    parser.add_argument("--fleet", default="default",
                        help="fleet name (the nos.ai/fleet label value)")
    parser.add_argument("--namespace", default="serving",
                        help="namespace the replica pods live in")
    parser.add_argument("--port", type=int, default=8080,
                        help="front-door HTTP port")
    parser.add_argument(
        "--replica-url-template", default="http://{ip}:8000",
        help="how to reach a replica pod's HTTP surface ({ip} = "
             "status.podIP; {name}/{namespace} substituted)")
    parser.add_argument(
        "--scrape-timeout", type=float, default=2.0,
        help="per-replica /stats scrape timeout in seconds")
    parser.add_argument(
        "--discovery-interval", type=float, default=2.0,
        help="seconds between replica discovery/scrape passes")
    parser.add_argument(
        "--block-size", type=int, default=16,
        help="affinity-hash block size in tokens — match the replicas' "
             "--kv-block-size so the routed block-chain is the one "
             "their PrefixBlockIndex actually shares")
    parser.add_argument(
        "--affinity-blocks", type=int, default=4,
        help="leading FULL blocks hashed into the affinity key; set at "
             "or below your shortest shared system-prompt length in "
             "blocks (hashing past the shared prefix scatters it)")
    parser.add_argument(
        "--max-imbalance", type=float, default=4.0,
        help="requests a ring candidate may carry beyond the "
             "least-loaded replica before affinity yields to balance")
    parser.add_argument(
        "--admit-pending-per-replica", type=float, default=0.0,
        help="fleet-wide pending per admitting replica above which the "
             "door sheds 429 reason=fleet_queue_full (0 = off)")
    parser.add_argument(
        "--admit-hbm-frac", type=float, default=0.0,
        help="shed 429 reason=fleet_hbm_admission while EVERY "
             "admitting replica reports HBM use at/above this fraction "
             "(0 = off)")
    parser.add_argument(
        "--max-door-queue", type=int, default=256,
        help="requests that may park at the door while no replica "
             "admits (scale-from-zero); past it the door sheds 429")
    parser.add_argument(
        "--door-wait", type=float, default=30.0,
        help="seconds a parked request waits for a replica before "
             "shedding 429 reason=no_ready_replicas")
    parser.add_argument(
        "--tenant-config", default="",
        help="request-level elastic quota at the door: FLEET-WIDE "
             "per-tenant token-rate min/max as a file path or inline "
             "JSON (empty = off). A tenant at/over its max — summed "
             "from the scraped per-replica /stats tenants sections — "
             "sheds 429 reason=tenant_quota before reaching any "
             "replica; the affinity key is tenant-scoped (matching "
             "the replicas' tenant-scoped prefix chains) unless "
             "share_prefix opts out")
    parser.add_argument(
        "--tenant-quota-attempts", type=int, default=2,
        help="total dispatch attempts answered 429 tenant_quota "
             "before the request fails as 429 (1 = fail on the first "
             "quota shed; the Nth shed is the failing one) — a burst "
             "tenant backs off on its quota instead of walking the "
             "fleet's full retry ladder")
    parser.add_argument(
        "--kv-fabric", choices=("on", "off"), default="off",
        help="fleet-wide KV fabric (off [default]): on = keep a union "
             "index over the replicas' /stats prefix_index sections "
             "and attach a peer-pull offer (kv_sources naming the "
             "warmest peer's /v1/kvchain/<digest>) to dispatches whose "
             "routed replica is colder on the prompt's prefix chain — "
             "the replica pulls the chain instead of re-prefilling. "
             "Requires replicas running a prefix cache; pair with "
             "--kv-host-tier-bytes on the replicas so evicted chains "
             "stay pullable from host RAM")
    parser.add_argument(
        "--kv-fabric-max-blocks", type=int, default=32,
        help="deepest block-aligned prompt prefix the fabric "
             "enumerates chain digests for per dispatch (cost is one "
             "digest per block, longest-first)")
    parser.add_argument(
        "--kv-fabric-token", default="",
        help="shared fleet secret stamped (as X-NOS-KV-Fabric-Token) "
             "on dispatches carrying kv_sources offers — replicas "
             "drop tokenless offers and refuse tokenless "
             "/v1/kvchain exports, so --kv-fabric=on requires it; "
             "set the SAME value on every replica's "
             "--kv-fabric-token")
    parser.add_argument(
        "--slo-burn-threshold", type=float, default=14.4,
        help="fleet fast-window burn rate at/above which an "
             "aggregated (tenant, objective) row reports breaching "
             "in GET /v1/slo (burn recomputed from summed "
             "per-replica window counts)")
    parser.add_argument(
        "--harvest-url", default="",
        help="harvest controller /stats URL; when set, its "
             "harvested_chip_seconds counter feeds the "
             "useful-work-per-chip-hour figure in GET /v1/slo "
             "(empty = serving chip-seconds only)")
    parser.add_argument(
        "--retry-attempts", type=int, default=12,
        help="dispatch attempts per request before failing it")
    parser.add_argument(
        "--retry-backoff", type=float, default=0.05,
        help="reason-aware retry backoff base in seconds")
    parser.add_argument(
        "--request-timeout", type=float, default=300.0,
        help="per-attempt replica HTTP timeout in seconds")
    args = parser.parse_args(argv)
    if args.kv_fabric == "on" and not args.kv_fabric_token:
        # a tokenless fabric is a silent no-op: every replica drops
        # tokenless kv_sources offers — fail loud at startup instead
        parser.error("--kv-fabric=on requires --kv-fabric-token "
                     "(replicas ignore tokenless peer-pull offers)")

    serve.setup_observability(args)
    client = Client(serve.connect(args))
    transport = HttpReplicaTransport(timeout_s=args.request_timeout,
                                     fabric_token=args.kv_fabric_token)
    stamper = AnnotationStamper(client, args.fleet,
                                args.namespace).start()
    router = GatewayRouter(
        RouterConfig(
            block_size=args.block_size,
            affinity_blocks=args.affinity_blocks,
            max_imbalance=args.max_imbalance,
            admit_pending_per_replica=args.admit_pending_per_replica,
            admit_hbm_frac=args.admit_hbm_frac,
            max_door_queue=args.max_door_queue,
            door_wait_s=args.door_wait,
            max_attempts=args.retry_attempts,
            backoff_s=args.retry_backoff,
            tenant_config=TenantQuotaConfig.load(args.tenant_config),
            tenant_quota_attempts=args.tenant_quota_attempts,
            fabric=args.kv_fabric == "on",
            fabric_max_blocks=args.kv_fabric_max_blocks,
            slo_burn_threshold=args.slo_burn_threshold,
        ),
        transport=transport.send,
        stream_transport=transport.send_stream,
        resume_transport=transport.resume,
        resume_stream_transport=transport.resume_stream,
        on_activation=stamper.note,
    )
    if args.harvest_url:
        def _harvest_stats(url=args.harvest_url,
                           timeout=args.scrape_timeout):
            try:
                with urllib.request.urlopen(url, timeout=timeout) as rsp:
                    return json.loads(rsp.read().decode())
            except (urllib.error.URLError, OSError, ValueError):
                return None     # feed absent this cycle; roll-up uses 0
        router.harvest_source = _harvest_stats
    scraper = HttpReplicaClient(args.replica_url_template,
                                timeout_s=args.scrape_timeout)

    def handle_for(pod):
        return scraper._url(pod)

    discovery = DiscoveryLoop(
        PodDiscovery(client, args.fleet, args.namespace,
                     stats_source=scraper.stats, handle_for=handle_for),
        router, args.discovery_interval).start()
    httpd = make_http_server(router, args.port, args.fleet)
    logger.info("gateway for fleet %s on :%d", args.fleet, args.port)
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        discovery.stop()
        stamper.stop()
        httpd.server_close()


if __name__ == "__main__":
    main()
