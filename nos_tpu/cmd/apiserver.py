"""nos-tpu-apiserver — the coordination backbone.

The reference's binaries all point at the cluster's kube-apiserver; this
binary is that backbone for a self-contained nos-tpu deployment (and the
envtest double for integration tests): it hosts the typed object store,
admission webhooks (analog of the operator's validating webhooks,
pkg/api/nos.nebuly.com/v1alpha1/*_webhook.go), the standard field indexes
(cmd/gpupartitioner/gpupartitioner.go:270-292), and serves the JSON/HTTP
API every other binary's RemoteApiServer speaks.
"""
from __future__ import annotations

import argparse
from typing import Optional, Sequence

from nos_tpu.api.webhooks import register_quota_webhooks
from nos_tpu.cmd import serve
from nos_tpu.kube.apiserver import ApiServer
from nos_tpu.kube.httpapi import ApiHttpServer


def register_standard_indexes(server: ApiServer) -> None:
    """Field indexes the controllers list by (reference
    cmd/gpupartitioner/gpupartitioner.go:270-292: pod phase + node name)."""
    server.register_index("Pod", "status.phase", lambda p: p.status.phase)
    server.register_index("Pod", "spec.nodeName", lambda p: p.spec.node_name or None)


def build(host: str = "127.0.0.1", port: int = 8001,
          quota_webhooks: bool = True) -> ApiHttpServer:
    server = ApiServer()
    register_standard_indexes(server)
    if quota_webhooks:
        register_quota_webhooks(server)
    return ApiHttpServer(server, host=host, port=port)


def main(argv: Optional[Sequence[str]] = None) -> None:
    parser = argparse.ArgumentParser(prog="nos-tpu-apiserver",
                                     description=__doc__)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8001)
    parser.add_argument(
        "--no-quota-webhooks", action="store_true",
        help="disable ElasticQuota/CompositeElasticQuota admission validation",
    )
    parser.add_argument("--log-level", type=int, default=0)
    serve.observability_flags(parser)
    args = parser.parse_args(argv)
    serve.setup_observability(args)

    http = build(args.host, args.port, quota_webhooks=not args.no_quota_webhooks)
    print(f"nos-tpu-apiserver listening at {http.address}")
    try:
        http.serve_forever()
    except KeyboardInterrupt:
        http.stop()


if __name__ == "__main__":
    main()
