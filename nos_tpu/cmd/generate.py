"""nos-tpu-generate — decode from a trained checkpoint.

The inference counterpart of the trainer binary: loads the params saved
by ``nos-tpu-trainer`` (orbax, params-only restore), optionally
quantizes the matmul weights to int8 (models/quant.py — decode is
HBM-bandwidth-bound on weight reads), and runs KV-cache generation
(models/generate.py). Prompts are token-id lists (tokenization is the
serving stack's concern, not the framework's); output is one JSON line
per prompt batch.

Usage:
    nos-tpu-generate --config model.yaml --checkpoint-dir /ckpt \\
        --prompt 1,5,20 --max-new-tokens 64 --temperature 0.8 --int8
"""
from __future__ import annotations

import argparse
import json
import logging
from dataclasses import dataclass, fields
from typing import Optional, Sequence

logger = logging.getLogger("nos_tpu.generate")


@dataclass
class GenerateConfig:
    # model (must match the checkpoint's training config)
    vocab: int = 32000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: int = 0
    d_ff: int = 1408
    max_seq: int = 512
    n_experts: int = 0
    bf16: bool = True
    # decode
    checkpoint_dir: str = ""
    max_new_tokens: int = 32
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 0.0
    int8: bool = False
    seed: int = 0
    log_level: str = "info"

    @classmethod
    def from_yaml_file(cls, path: str) -> "GenerateConfig":
        import yaml

        with open(path) as f:
            data = yaml.safe_load(f) or {}
        known = {f.name for f in fields(cls)}
        unknown = set(data) - known
        if unknown:
            raise ValueError(
                f"{path}: unknown generate config keys {sorted(unknown)}")
        return cls(**data)


def load_params(cfg: GenerateConfig):
    """Init-or-restore: the checkpoint overrides fresh init when present."""
    import jax
    import jax.numpy as jnp

    from nos_tpu.models import transformer as tfm

    model_cfg = tfm.TransformerConfig(
        vocab=cfg.vocab, d_model=cfg.d_model, n_layers=cfg.n_layers,
        n_heads=cfg.n_heads, n_kv_heads=cfg.n_kv_heads, d_ff=cfg.d_ff,
        max_seq=cfg.max_seq, n_experts=cfg.n_experts,
        dtype=jnp.bfloat16 if cfg.bf16 else jnp.float32,
    )
    if cfg.checkpoint_dir:
        from nos_tpu.train import CheckpointManager

        # shape-only template: never materialize (or pay init compute
        # for) weights the restore immediately replaces
        template = jax.eval_shape(
            lambda: tfm.init_params(jax.random.PRNGKey(0), model_cfg))
        ckpt = CheckpointManager(cfg.checkpoint_dir)
        from nos_tpu.train.checkpoint import model_arch_dict

        # mismatched dims fail HERE by field name, not as an orbax
        # shape error mid-restore
        ckpt.validate_model_config(model_arch_dict(cfg))
        step = ckpt.latest()
        params = ckpt.restore_params(step, params_template=template)
        ckpt.close()
        logger.info("restored params from step %s", step)
    else:
        params = tfm.init_params(jax.random.PRNGKey(cfg.seed), model_cfg)
    if cfg.int8:
        from nos_tpu.models.quant import quantize_params

        params = quantize_params(params)
        logger.info("quantized matmul weights to int8")
    return model_cfg, params


def run(cfg: GenerateConfig, prompts: Sequence[Sequence[int]]):
    """Generate continuations for prompt token lists (equal lengths make
    one batch; ragged prompts run one batch each). Returns the full
    token sequences as lists."""
    import jax
    import jax.numpy as jnp

    from nos_tpu.models.generate import generate

    if any(len(p) == 0 for p in prompts):
        raise ValueError("empty prompt: every prompt needs >= 1 token id")
    model_cfg, params = load_params(cfg)
    rng = (jax.random.PRNGKey(cfg.seed + 1)
           if cfg.temperature > 0 else None)

    by_len: dict = {}
    for i, p in enumerate(prompts):
        by_len.setdefault(len(p), []).append((i, list(p)))

    results: list = [None] * len(prompts)
    for gi, (_, group) in enumerate(sorted(by_len.items())):
        idxs = [i for i, _ in group]
        batch = jnp.asarray([p for _, p in group], jnp.int32)
        # independent sampling noise per length-group
        grng = jax.random.fold_in(rng, gi) if rng is not None else None
        out = generate(params, model_cfg, batch, cfg.max_new_tokens,
                       temperature=cfg.temperature, top_k=cfg.top_k,
                       top_p=cfg.top_p, rng=grng)
        for row, i in enumerate(idxs):
            results[i] = [int(t) for t in out[row]]
    return results


def main(argv: Optional[Sequence[str]] = None) -> None:
    parser = argparse.ArgumentParser(prog="nos-tpu-generate",
                                     description=__doc__)
    parser.add_argument("--config", default="", help="model config YAML")
    parser.add_argument("--checkpoint-dir", default="")
    parser.add_argument("--prompt", action="append", default=[],
                        help="comma-separated token ids (repeatable)")
    parser.add_argument("--max-new-tokens", type=int, default=None)
    parser.add_argument("--temperature", type=float, default=None)
    parser.add_argument("--top-k", type=int, default=None)
    parser.add_argument("--top-p", type=float, default=None)
    parser.add_argument("--int8", action="store_true")
    parser.add_argument(
        "--log-format", choices=("text", "json"), default="text",
        help="log line format; json emits one object per line with "
             "trace_id/span_id injected when a tracing span is active")
    args = parser.parse_args(argv)

    cfg = GenerateConfig.from_yaml_file(args.config) if args.config \
        else GenerateConfig()
    if args.checkpoint_dir:
        cfg.checkpoint_dir = args.checkpoint_dir
    if args.max_new_tokens is not None:
        cfg.max_new_tokens = args.max_new_tokens
    if args.temperature is not None:
        cfg.temperature = args.temperature
    if args.top_k is not None:
        cfg.top_k = args.top_k
    if args.top_p is not None:
        cfg.top_p = args.top_p
    if args.int8:
        cfg.int8 = True
    from nos_tpu.cmd import setup_logging as _shared_setup_logging
    _shared_setup_logging(
        0, args.log_format,
        numeric_level=getattr(logging, cfg.log_level.upper(), 20))

    prompts = []
    for raw in args.prompt or ["0"]:
        try:
            toks = [int(t) for t in raw.split(",") if t.strip()]
        except ValueError:
            parser.error(f"--prompt {raw!r} contains a non-integer token; "
                         f"pass comma-separated token ids, e.g. '1,2,3'")
        if not toks:
            parser.error(f"--prompt {raw!r} parsed to zero tokens; pass a "
                         f"comma-separated list of token ids, e.g. '1,2,3'")
        prompts.append(toks)
    for seq in run(cfg, prompts):
        print(json.dumps({"tokens": seq}))


if __name__ == "__main__":
    main()
