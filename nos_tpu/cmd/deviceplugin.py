"""nos-tpu-device-plugin — per-node DaemonSet advertising sub-slice
resources to the kubelet.

The consumer end of the partitioner's hand-off (analog of the NVIDIA
device plugin the reference's MPS partitioner restarts,
internal/partitioning/mps/partitioner.go:61-123): reads the
``nos.ai/device-plugin.config`` node label + the
``nos-device-plugin-config`` ConfigMap entry it names, and serves the
kubelet Device Plugin API v1beta1 (registration, ListAndWatch,
Allocate) from ``agents/deviceplugin.py``. Plan changes land as new
ListAndWatch frames on the live stream — no restart, no re-register.
"""
from __future__ import annotations

import argparse
import logging
import os
import threading
import time
from typing import Optional, Sequence

from nos_tpu.agents.deviceplugin import (
    KUBELET_SOCKET,
    TpuDevicePlugin,
    config_source_from_client,
)
from nos_tpu.cmd import serve

logger = logging.getLogger("nos_tpu.deviceplugin")


def main(argv: Optional[Sequence[str]] = None) -> None:
    parser = argparse.ArgumentParser(prog="nos-tpu-device-plugin",
                                     description=__doc__)
    parser.add_argument("--node", default=os.environ.get("NODE_NAME", ""),
                        help="this node's name (downward-API NODE_NAME)")
    parser.add_argument("--socket-dir",
                        default="/var/lib/kubelet/device-plugins",
                        help="where plugin sockets live (kubelet dir)")
    parser.add_argument("--kubelet-socket", default=KUBELET_SOCKET)
    parser.add_argument("--poll-seconds", type=float, default=5.0,
                        help="hand-off re-read cadence")
    parser.add_argument("--once", action="store_true",
                        help="one refresh then exit (smoke/debug)")
    serve.common_flags(parser, config=False)
    args = parser.parse_args(argv)
    serve.setup_observability(args)
    if not args.node:
        parser.error("--node (or NODE_NAME) is required")

    client = serve.connect(args)
    plugin = TpuDevicePlugin(
        config_source_from_client(client, args.node),
        args.socket_dir, kubelet_socket=args.kubelet_socket)
    health = serve.HealthServer(host=args.health_host,
                                port=args.health_port).start() \
        if args.health_port else None

    # pod stop sends SIGTERM, not SIGINT: without a handler the process
    # dies skipping the finally, leaving stale sockets on the hostPath
    # for the replacement pod to trip over
    import signal

    def _sigterm(*_):
        raise SystemExit(0)

    if threading.current_thread() is threading.main_thread():
        signal.signal(signal.SIGTERM, _sigterm)

    def safe_refresh() -> None:
        # transient failures (apiserver blip, partitioner mid-write,
        # malformed entry) must NOT crash the pod: a dying plugin tears
        # down its sockets and the kubelet zeroes every sub-slice
        # resource until the crashloop restart re-registers — retry
        # next poll instead. The same applies at STARTUP: a bad entry
        # must leave the pod alive and polling, not crashlooping.
        try:
            plugin.refresh()
        except Exception:                          # noqa: BLE001
            logger.exception("refresh failed; retrying next poll")

    try:
        if args.once:
            plugin.refresh()       # strict: smoke runs must surface errors
            return
        safe_refresh()
        while True:
            time.sleep(args.poll_seconds)
            safe_refresh()
    except KeyboardInterrupt:
        pass
    finally:
        if health is not None:
            health.stop()
        plugin.stop()


if __name__ == "__main__":
    main()
