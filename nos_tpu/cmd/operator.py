"""nos-tpu-operator — quota reconcilers.

Analog of cmd/operator/operator.go:50-126: a manager running the
ElasticQuota + CompositeElasticQuota reconcilers (the validating webhooks
live with the apiserver binary, which is the admission path here) with
healthz/readyz probes and metrics.
"""
from __future__ import annotations

import argparse
from typing import Optional, Sequence

from nos_tpu.api.configs import OperatorConfig
from nos_tpu.cmd import serve
from nos_tpu.kube.controller import Manager
from nos_tpu.quota.controller import (
    CompositeElasticQuotaReconciler,
    ElasticQuotaReconciler,
)
from nos_tpu.tpu.resource_calc import ResourceCalculator


def build(server, config: Optional[OperatorConfig] = None) -> Manager:
    cfg = config or OperatorConfig()
    calc = ResourceCalculator(
        tpu_memory_gb=cfg.tpu_resource_memory_gb,
        nvidia_gpu_memory_gb=cfg.nvidia_gpu_resource_memory_gb,
    )
    mgr = Manager(server, leader_election=cfg.leader_election_config("operator"))
    mgr.add_controller(ElasticQuotaReconciler(calc).controller())
    mgr.add_controller(CompositeElasticQuotaReconciler(calc).controller())
    return mgr


def main(argv: Optional[Sequence[str]] = None) -> None:
    parser = argparse.ArgumentParser(prog="nos-tpu-operator", description=__doc__)
    serve.common_flags(parser)
    args = parser.parse_args(argv)

    cfg = OperatorConfig.from_yaml_file(args.config) if args.config \
        else OperatorConfig()
    serve.setup_logging(cfg.log_level)
    mgr = build(serve.connect(args), cfg)
    serve.run_daemon(mgr, args.health_port, args.health_host)


if __name__ == "__main__":
    main()
