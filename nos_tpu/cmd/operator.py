"""nos-tpu-operator — quota reconcilers + validating webhooks.

Analog of cmd/operator/operator.go:50-126: a manager running the
ElasticQuota + CompositeElasticQuota reconcilers with healthz/readyz
probes and metrics. On the in-process double the admission checks run
server-side (apiserver binary); with ``--webhook-certs`` the operator
additionally serves them as TLS AdmissionReview endpoints
(elasticquota_webhook.go:30-80 analog) for real clusters, where a
ValidatingWebhookConfiguration (helm templates/operator/webhook.yaml)
points the API server at this pod.
"""
from __future__ import annotations

import argparse
import os
from typing import Optional, Sequence

from nos_tpu.api.configs import OperatorConfig
from nos_tpu.cmd import serve
from nos_tpu.kube.controller import Manager
from nos_tpu.quota.controller import (
    CompositeElasticQuotaReconciler,
    ElasticQuotaReconciler,
)
from nos_tpu.quota.pdb import PdbReconciler
from nos_tpu.tpu.resource_calc import ResourceCalculator


def build(server, config: Optional[OperatorConfig] = None) -> Manager:
    cfg = config or OperatorConfig()
    calc = ResourceCalculator(
        tpu_memory_gb=cfg.tpu_resource_memory_gb,
        nvidia_gpu_memory_gb=cfg.nvidia_gpu_resource_memory_gb,
    )
    mgr = Manager(server, leader_election=cfg.leader_election_config("operator"))
    mgr.add_controller(ElasticQuotaReconciler(calc).controller())
    mgr.add_controller(CompositeElasticQuotaReconciler(calc).controller())
    # disruption-controller analog: this control plane IS the cluster, so
    # PDB status (consumed by the scheduler's preemption ordering) is
    # maintained here rather than by kube-controller-manager
    mgr.add_controller(PdbReconciler().controller())
    return mgr


def main(argv: Optional[Sequence[str]] = None) -> None:
    parser = argparse.ArgumentParser(prog="nos-tpu-operator", description=__doc__)
    serve.common_flags(parser)
    parser.add_argument(
        "--webhook-certs", default=os.environ.get("NOS_TPU_WEBHOOK_CERTS", ""),
        help="directory with cert.pem/key.pem: serve the EQ/CEQ validating "
             "webhooks over TLS (real-cluster admission path)")
    parser.add_argument(
        "--webhook-port", type=int, default=9443,
        help="TLS port for the validating webhooks")
    args = parser.parse_args(argv)

    cfg = OperatorConfig.from_yaml_file(args.config) if args.config \
        else OperatorConfig()
    serve.setup_observability(
        args, args.log_level if args.log_level is not None
        else cfg.log_level)
    server = serve.connect(args)
    webhook = None
    if args.webhook_certs:
        from nos_tpu.api.webhook_server import QuotaWebhookServer

        webhook = QuotaWebhookServer(
            server,
            certfile=os.path.join(args.webhook_certs, "cert.pem"),
            keyfile=os.path.join(args.webhook_certs, "key.pem"),
            host="0.0.0.0", port=args.webhook_port,
        ).start()
    mgr = build(server, cfg)
    try:
        serve.run_daemon(mgr, args.health_port, args.health_host)
    finally:
        if webhook is not None:
            webhook.stop()


if __name__ == "__main__":
    main()
