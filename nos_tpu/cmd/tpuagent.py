"""nos-tpu-agent — the per-node daemon.

Analog of cmd/migagent (reporter + actuator + startup resync,
migagent.go:165-199) and cmd/gpuagent. The device boundary is the C++
native layer (native/tpuagent/tpuagent.cc via ctypes — the cgo/NVML
analog); --mock substitutes the in-memory device double for clusters
without the library (and for tests).
"""
from __future__ import annotations

import argparse
import os
from typing import Optional, Sequence

from nos_tpu.agents.tpu_native import MockTpuClient, TpuClientError, TpuNativeClient
from nos_tpu.agents.tpuagent import TpuAgent
from nos_tpu.api.configs import TpuAgentConfig
from nos_tpu.cmd import serve
from nos_tpu.kube.client import Client
from nos_tpu.kube.controller import Manager


def build(server, node_name: str, config: Optional[TpuAgentConfig] = None,
          tpu_client=None, mock_chips: int = 8,
          pod_resources_socket: str = "") -> Manager:
    cfg = config or TpuAgentConfig()
    if tpu_client is None:
        try:
            tpu_client = TpuNativeClient()
        except TpuClientError:
            # A deployment that explicitly configured the native library
            # must never silently report fake device state.
            if os.environ.get("NOS_TPU_NATIVE_LIB"):
                raise
            tpu_client = MockTpuClient(chips=mock_chips)
    podres = None
    if pod_resources_socket:
        from nos_tpu.agents.podresources import KubeletPodResourcesClient

        podres = KubeletPodResourcesClient(pod_resources_socket)
    agent = TpuAgent(
        node_name,
        tpu_client,
        report_interval_s=cfg.report_interval_seconds,
        manage_allocatable=cfg.manage_allocatable,
        podres_client=podres,
    )
    agent.startup_cleanup(Client(server))
    mgr = Manager(server)
    for c in agent.controllers():
        mgr.add_controller(c)
    return mgr


def main(argv: Optional[Sequence[str]] = None) -> None:
    parser = argparse.ArgumentParser(prog="nos-tpu-agent", description=__doc__)
    serve.common_flags(parser)
    parser.add_argument(
        "--node-name", default=os.environ.get("NODE_NAME", ""),
        help="this node's name (GKE downward API sets NODE_NAME)",
    )
    parser.add_argument(
        "--mock", action="store_true",
        help="use the in-memory device double instead of the native layer",
    )
    parser.add_argument("--mock-chips", type=int, default=8)
    parser.add_argument(
        "--pod-resources-socket", default="",
        help="kubelet pod-resources socket path (e.g. "
             "/var/lib/kubelet/pod-resources/kubelet.sock); empty "
             "disables the kubelet allocation view",
    )
    args = parser.parse_args(argv)
    if not args.node_name:
        parser.error("--node-name (or NODE_NAME env) is required")

    cfg = TpuAgentConfig.from_yaml_file(args.config) if args.config \
        else TpuAgentConfig()
    serve.setup_observability(
        args, args.log_level if args.log_level is not None
        else cfg.log_level)
    tpu_client = MockTpuClient(chips=args.mock_chips) if args.mock else None
    mgr = build(serve.connect(args), args.node_name, cfg, tpu_client=tpu_client,
                mock_chips=args.mock_chips,
                pod_resources_socket=args.pod_resources_socket)
    serve.run_daemon(mgr, args.health_port, args.health_host)


if __name__ == "__main__":
    main()
